#!/usr/bin/env bash
# Run the bench partition_failover + router_failover phases across
# ACTUAL processes (docs/podnet.md) — the wire, the heartbeats, and
# the replicated placement epochs cross real socket boundaries.
#
#   deploy/run_partition_bench.sh            # compose mode: 2 containers
#   deploy/run_partition_bench.sh --local    # no docker: a peer process
#
# Compose mode brings up the 2-member pod from docker-compose.yml and
# runs the bench inside router-a, with router-b's wire address as the
# pod peer. Local mode spawns deploy/placement_peer.py (a real
# KVWireServer in its own process) and points ROOM_TPU_POD_PEERS at
# it. Either way the gate is the same as ci.yml's cpu-proxy smoke:
# tokens_lost==0 on both phases, bystander unstalled, stale epoch
# refused after the shard adoption.
set -euo pipefail

HERE="$(cd "$(dirname "$0")" && pwd)"
REPO="$(dirname "$HERE")"
PHASES="$(mktemp /tmp/bench_pod_phases.XXXXXX.jsonl)"

# every phase except the two podnet failover phases is switched off
BENCH_ENV=(
  JAX_PLATFORMS=cpu
  ROOM_TPU_BENCH_CPU_PROXY=1
  ROOM_TPU_BENCH_PIPELINE=0 ROOM_TPU_BENCH_SPEC=0
  ROOM_TPU_BENCH_SPEC_PIPELINE=0 ROOM_TPU_BENCH_PREFILL=0
  ROOM_TPU_BENCH_LATENCY=0 ROOM_TPU_BENCH_OFFLOAD=0
  ROOM_TPU_BENCH_RESTART=0 ROOM_TPU_BENCH_FLEET=0
  ROOM_TPU_BENCH_DISAGG=0 ROOM_TPU_BENCH_SCHED=0
  ROOM_TPU_BENCH_RAGGED=0 ROOM_TPU_BENCH_KVQ=0
  ROOM_TPU_BENCH_TRACE=0
)

assert_phases() {
  python - "$1" <<'PYEOF'
import json, sys

phases = [json.loads(ln) for ln in open(sys.argv[1]) if ln.strip()]
part = [p for p in phases if p.get("phase") == "partition_failover"]
assert part, "partition_failover phase missing"
row = part[-1]
assert row.get("tokens_lost") == 0, row
assert row.get("ships_reprefill", 0) >= 1, row
print("partition_failover: tokens_lost=0, ttft",
      row.get("ttft_after_partition_s"))
rt = [p for p in phases if p.get("phase") == "router_failover"]
assert rt, "router_failover phase missing"
row = rt[-1]
assert row.get("tokens_lost") == 0, row
assert row.get("bystander_ok") is True, row
assert row.get("victim_shed_during_lease") is True, row
assert row.get("stale_epoch_refused") is True, row
assert row.get("adoptions", 0) >= 1, row
print("router_failover: tokens_lost=0, adoption ttft",
      row.get("ttft_after_adoption_s"))
print("POD BENCH OK")
PYEOF
}

if [[ "${1:-}" == "--local" ]]; then
  # ---- local mode: a real peer process, no docker ----
  cd "$REPO"
  PEER_LOG="$(mktemp /tmp/placement_peer.XXXXXX.log)"
  python deploy/placement_peer.py --port 0 >"$PEER_LOG" 2>&1 &
  PEER_PID=$!
  trap 'kill "$PEER_PID" 2>/dev/null || true' EXIT
  for _ in $(seq 1 50); do
    if grep -q PEER_READY "$PEER_LOG"; then break; fi
    sleep 0.2
  done
  PEER_ADDR="$(grep PEER_READY "$PEER_LOG" | awk '{print $2}' || true)"
  [[ -n "$PEER_ADDR" ]] || { echo "peer never came up"; cat "$PEER_LOG"; exit 1; }
  echo "pod peer listening at $PEER_ADDR"
  env "${BENCH_ENV[@]}" \
    ROOM_TPU_POD_PEERS="$PEER_ADDR" \
    ROOM_TPU_BENCH_PHASES="$PHASES" \
    python bench.py >/dev/null
  assert_phases "$PHASES"
  # the adoption's epoch bump must have reached the peer process
  grep -q '"control": "placement"' "$PEER_LOG" || {
    echo "peer process never received a placement frame"; exit 1; }
  echo "placement frames crossed the process boundary:"
  grep '"control": "placement"' "$PEER_LOG"
else
  # ---- compose mode: the 2-member pod from docker-compose.yml ----
  cd "$HERE"
  docker compose up -d --build
  trap 'docker compose down -v' EXIT
  echo "waiting for both members to report healthy..."
  for _ in $(seq 1 60); do
    healthy="$(docker compose ps --format json 2>/dev/null \
      | grep -c '"Health":"healthy"' || true)"
    if [[ "$healthy" == "2" ]]; then break; fi
    sleep 5
  done
  docker compose exec -T \
    $(for kv in "${BENCH_ENV[@]}"; do printf -- "-e %s " "$kv"; done) \
    -e ROOM_TPU_POD_PEERS=router-b:3710 \
    -e ROOM_TPU_BENCH_PHASES=/tmp/pod_phases.jsonl \
    router-a python bench.py >/dev/null
  docker compose cp router-a:/tmp/pod_phases.jsonl "$PHASES"
  assert_phases "$PHASES"
fi
