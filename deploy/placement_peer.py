"""Standalone pod-peer control endpoint (docs/podnet.md).

Hosts a real ``KVWireServer`` and answers the pod's control frames the
way a sibling router process would: heartbeats are observed into a
``PodMembership``, replicated placement frames install into a
``PlacementMap`` under the strictly-newer epoch fence. Used by
``run_partition_bench.sh --local`` so the bench's placement publishes
and heartbeats cross a REAL process + socket boundary instead of the
in-process loopback the unit tiers use.

    python deploy/placement_peer.py --port 3710

Prints ``PEER_READY host:port`` once listening, then one line per
control frame; exits on SIGTERM/SIGINT.
"""

import argparse
import json
import os
import signal
import sys
import tempfile
import threading

# runnable from a source checkout (no `pip install -e .`): the repo
# root is this file's grandparent
sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--shards", type=int, default=2)
    args = ap.parse_args()

    from room_tpu.parallel.multihost import KVWireServer
    from room_tpu.serving.podnet import PlacementMap, PodMembership

    placement = PlacementMap(args.shards)
    membership = PodMembership()

    def on_control(control: dict) -> dict:
        kind = control.get("kind")
        if kind == "heartbeat":
            member = str(control.get("member") or "")
            membership.register(member)
            applied = membership.observe(member)
            print(json.dumps({"control": "heartbeat",
                              "member": member}), flush=True)
            return {"ok": True, "applied": applied,
                    "member_state": membership.state_of(member)}
        if kind == "placement":
            applied = placement.apply(control)
            print(json.dumps({
                "control": "placement",
                "epoch": control.get("epoch"),
                "applied": applied,
                "local_epoch": placement.epoch,
            }), flush=True)
            return {"ok": True, "applied": applied,
                    "epoch": placement.epoch}
        return {"ok": False, "error": f"unknown control {kind!r}"}

    def on_entry(header, payload, path):
        # a control-only peer: KV shipments belong to the fleet tier
        return {"ok": False, "error": "control-only peer"}

    spool = tempfile.mkdtemp(prefix="placement-peer-")
    server = KVWireServer(
        spool, on_entry, host=args.host, port=args.port,
        on_control=on_control,
    )
    print(f"PEER_READY {server.address[0]}:{server.address[1]}",
          flush=True)
    done = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: done.set())
    done.wait()
    server.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
