# room-tpu developer entry points

PY ?= python
CPU_ENV = PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu

.PHONY: test lint knobs-doc lock-graph bench bench-tiny serve mcp native experiment dryrun clean

test:            ## hermetic suite on the virtual 8-device CPU mesh
	$(PY) -m pytest tests/ -q

lint:            ## roomlint (docs/static_analysis.md) + knobs.md/lock_graph.dot freshness + ruff
	$(PY) -m room_tpu.analysis
	$(PY) -m room_tpu.analysis --check-docs
	$(PY) -m room_tpu.analysis --graph | diff - docs/lock_graph.dot
	@if command -v ruff >/dev/null 2>&1; then ruff check .; \
	else echo "ruff not installed; skipping generic lint tier"; fi

knobs-doc:       ## regenerate docs/knobs.md from room_tpu/utils/knobs.py
	$(PY) -m room_tpu.analysis --write-docs

lock-graph:      ## regenerate docs/lock_graph.dot (lockmap, docs/static_analysis.md)
	$(PY) -m room_tpu.analysis --graph > docs/lock_graph.dot

bench:           ## decode benchmark (real accelerator; one JSON line)
	$(PY) bench.py

bench-tiny:      ## CPU smoke of the benchmark harness
	env $(CPU_ENV) ROOM_TPU_BENCH_TINY=1 $(PY) bench.py

serve:           ## API server + dashboard + runtime loops
	$(PY) -m room_tpu.cli.main serve

mcp:             ## MCP stdio server (shares the data dir's database)
	$(PY) -m room_tpu.cli.main mcp

native:          ## build the C++ vector-search core
	$(MAKE) -C native

experiment:      ## swarm perf harness (reference experiment.js parity)
	env $(CPU_ENV) $(PY) scripts/experiment.py --models echo \
		--workers 4 --cycles 3

dryrun:          ## multi-chip sharding dry run on 8 virtual devices
	env $(CPU_ENV) XLA_FLAGS=--xla_force_host_platform_device_count=8 \
		$(PY) -c "import __graft_entry__ as g; g.dryrun_multichip(8)"

clean:
	$(MAKE) -C native clean
	find . -name __pycache__ -type d -exec rm -rf {} + 2>/dev/null; true
