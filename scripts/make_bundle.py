#!/usr/bin/env python
"""Build the staged-update bundle ``server/updater.py`` consumes
(reference: .github/workflows/release.yml packaging the autoUpdate.ts
bundle — tar.gz + sha256 manifest).

Layout of ``room-tpu-update-<version>.tar.gz``::

    version.json          {"version": V, "checksums": {rel: sha256}}
    room_tpu/**           the package tree (py only)
    ui/**                 dashboard bundle
    bench.py              driver benchmark entry

The updater downloads it, extracts to a scratch dir, verifies every
checksum, atomically renames into the staging dir, and promotes on the
next update-restart (updater.py:248-305). CI builds this on tag; the
round-trip is pinned by tests/test_updater.py.

Usage: python scripts/make_bundle.py [--version 1.2.3] [--out dist/]
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import sys
import tarfile
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

INCLUDE_TREES = ("room_tpu", "ui")
INCLUDE_FILES = ("bench.py",)
EXCLUDE_DIRS = {"__pycache__", ".pytest_cache"}
EXCLUDE_SUFFIXES = (".pyc", ".so", ".o")


def bundle_files() -> list[str]:
    """Repo-relative paths that ship in the bundle, sorted for a
    deterministic manifest."""
    out: list[str] = []
    for tree in INCLUDE_TREES:
        for root, dirs, files in os.walk(os.path.join(REPO, tree)):
            dirs[:] = sorted(d for d in dirs if d not in EXCLUDE_DIRS)
            for f in sorted(files):
                if f.endswith(EXCLUDE_SUFFIXES):
                    continue
                out.append(
                    os.path.relpath(os.path.join(root, f), REPO)
                )
    for f in INCLUDE_FILES:
        if os.path.exists(os.path.join(REPO, f)):
            out.append(f)
    return sorted(out)


def sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(65536), b""):
            h.update(chunk)
    return h.hexdigest()


def build_bundle(version: str, out_dir: str) -> str:
    """Write room-tpu-update-<version>.tar.gz and return its path."""
    files = bundle_files()
    checksums = {
        rel: sha256_file(os.path.join(REPO, rel)) for rel in files
    }
    manifest = {"version": version, "checksums": checksums}

    os.makedirs(out_dir, exist_ok=True)
    bundle = os.path.join(
        out_dir, f"room-tpu-update-{version}.tar.gz"
    )
    with tempfile.TemporaryDirectory() as td:
        vf = os.path.join(td, "version.json")
        with open(vf, "w") as f:
            json.dump(manifest, f, indent=1, sort_keys=True)
        with tarfile.open(bundle, "w:gz") as tf:
            tf.add(vf, arcname="version.json")
            for rel in files:
                tf.add(os.path.join(REPO, rel), arcname=rel)
    return bundle


def main(argv: list[str] | None = None) -> int:
    sys.path.insert(0, REPO)
    from room_tpu import __version__

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--version", default=__version__)
    ap.add_argument("--out", default=os.path.join(REPO, "dist"))
    args = ap.parse_args(argv)

    bundle = build_bundle(args.version.lstrip("v"), args.out)
    sha = sha256_file(bundle)
    print(json.dumps({
        "bundle": bundle,
        "sha256": sha,
        "bytes": os.path.getsize(bundle),
    }))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
