"""Single-process TPU tuning sweep for the serving engine.

Runs bench-shaped decode measurements across the engine's perf knobs —
decode chunk, batch size, page size, weight quantization, speculative
tokens — sequentially in ONE process (the axon tunnel wedges if two
processes claim the chip). Prints one JSON line per configuration and a
final "best" line; use the winner to set bench.py / engine defaults.

Usage (on the real chip):
    python scripts/tpu_tune.py                 # default grid
    python scripts/tpu_tune.py --quick         # 1 rep, small grid
    ROOM_TPU_TUNE_GRID=chunk=8,16,32;batch=8,16 python scripts/tpu_tune.py

Each measurement reuses the same params (one init) but builds a fresh
engine, so compile caches persist across configs that share shapes.
"""

from __future__ import annotations

import argparse
import itertools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def parse_grid(spec: str) -> dict[str, list]:
    grid: dict[str, list] = {}
    for part in filter(None, spec.split(";")):
        key, _, vals = part.partition("=")
        grid[key.strip()] = [v.strip() for v in vals.split(",") if v.strip()]
    return grid


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--gen", type=int, default=128,
                    help="timed tokens per request")
    ap.add_argument("--out", default="")
    args = ap.parse_args()

    from bench import acquire_chip_lock, bench_config

    _chip_lock = acquire_chip_lock()  # noqa: F841 (held till exit)

    import jax

    from room_tpu.models import qwen3
    from room_tpu.serving import SamplingParams, ServingEngine

    platform = jax.devices()[0].platform
    cfg = bench_config()
    params = qwen3.init_params(cfg, jax.random.PRNGKey(0))

    default_grid = {
        "chunk": ["8", "16", "32"],
        "batch": ["8", "16"],
        "page": ["32"],
        "quant": ["none", "int8"],
        "kvq": ["none", "int8"],
    }
    if args.quick:
        default_grid = {"chunk": ["16"], "batch": ["8"],
                        "page": ["32"], "quant": ["none"],
                        "kvq": ["none", "int8"]}
    grid = parse_grid(os.environ.get("ROOM_TPU_TUNE_GRID", "")) or default_grid

    from room_tpu.ops.quant import quantize_decoder_params

    q_params = None

    def measure(chunk: int, batch: int, page: int, quant: str,
                kvq: str = "none") -> dict:
        nonlocal q_params
        # chunk tunes the dispatch-window depth (docs/serving.md)
        os.environ["ROOM_TPU_DECODE_STEPS_PER_DISPATCH"] = str(chunk)
        if kvq == "int8":
            os.environ["ROOM_TPU_KV_QUANT"] = "int8"
        else:
            os.environ.pop("ROOM_TPU_KV_QUANT", None)
        p = params
        if quant == "int8":
            if q_params is None:
                q_params = quantize_decoder_params(params, cfg)
            p = q_params
        eng = ServingEngine(cfg, p, max_batch=batch, page_size=page,
                            n_pages=2048)
        prompt = list(range(1, 33))
        sp = SamplingParams(temperature=0.0, max_new_tokens=32)
        warm = [eng.submit(prompt, sampling=sp) for _ in range(batch)]
        eng.run_until_idle()
        for t in warm:
            eng.release_session(t.session_id)
        start = eng.stats()["tokens_decoded"]
        for _ in range(batch * 2):
            eng.submit(prompt, sampling=SamplingParams(
                temperature=0.0, max_new_tokens=args.gen))
        t0 = time.perf_counter()
        eng.run_until_idle()
        dt = time.perf_counter() - t0
        decoded = eng.stats()["tokens_decoded"] - start
        return {"chunk": chunk, "batch": batch, "page": page,
                "quant": quant, "kvq": kvq,
                "tok_s": round(decoded / dt, 2),
                "decoded": decoded, "dt": round(dt, 2)}

    results = []
    combos = list(itertools.product(
        grid.get("chunk", ["16"]), grid.get("batch", ["8"]),
        grid.get("page", ["32"]), grid.get("quant", ["none"]),
        grid.get("kvq", ["none"])))
    for chunk, batch, page, quant, kvq in combos:
        try:
            row = measure(int(chunk), int(batch), int(page), quant, kvq)
        except Exception as e:  # keep sweeping; record the failure
            row = {"chunk": chunk, "batch": batch, "page": page,
                   "quant": quant, "kvq": kvq,
                   "error": f"{type(e).__name__}: {e}"[:200]}
        row["platform"] = platform
        results.append(row)
        print(json.dumps(row), flush=True)

    ok = [r for r in results if "tok_s" in r]
    if ok:
        best = max(ok, key=lambda r: r["tok_s"])
        print(json.dumps({"best": best}), flush=True)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(results, f, indent=1)


if __name__ == "__main__":
    main()
