"""Generate the committed mini-BPE fixture tokenizer used by the
tokenizer-fidelity golden tests (tests/fixtures/qwen_mini_tokenizer/).

The fixture mirrors the *shape* of the upstream Qwen3 tokenizer — a
byte-level BPE with the chat/tool special tokens registered as added
special tokens (each one id) and eos = <|im_end|> — at a tiny vocab so
it loads instantly and lives in-tree. Real-vocab golden ids require the
actual checkpoint assets, which the image cannot download; the fixture
pins the HFTokenizer code path, the specials-are-single-ids contract,
and the chat-template renderer byte-for-byte.

Deterministic: re-running produces identical files (fixed corpus, fixed
vocab size, sorted merges).
"""

from __future__ import annotations

import os
import sys

FIXTURE_DIR = os.path.join(
    os.path.dirname(__file__), "..", "tests", "fixtures",
    "qwen_mini_tokenizer",
)

# the Qwen3 chat/tool specials, one token id each (upstream ids differ;
# the contract under test is single-id-ness, not the numeric value)
SPECIALS = [
    "<|endoftext|>",
    "<|im_start|>",
    "<|im_end|>",
    "<tool_call>",
    "</tool_call>",
    "<tool_response>",
    "</tool_response>",
    "<tools>",
    "</tools>",
]

CORPUS = [
    "You are a helpful assistant.",
    "You may call one or more functions to assist with the user query.",
    "You are provided with function signatures within XML tags:",
    "For each function call, return a json object with function name "
    "and arguments within XML tags:",
    '{"name": "get_weather", "arguments": {"city": "Paris"}}',
    '{"type": "function", "function": {"name": "search", '
    '"description": "Search the web", "parameters": {"type": "object", '
    '"properties": {"query": {"type": "string"}}}}}',
    "# Tools",
    "What is the weather in Paris today?",
    "The weather in Paris is sunny, 22 degrees.",
    "system user assistant tool",
    "hello world the quick brown fox jumps over the lazy dog",
]


def main() -> None:
    from tokenizers import Tokenizer, models, pre_tokenizers, trainers
    from tokenizers import decoders

    tok = Tokenizer(models.BPE(unk_token=None))
    tok.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False)
    tok.decoder = decoders.ByteLevel()
    trainer = trainers.BpeTrainer(
        vocab_size=1024,
        special_tokens=SPECIALS,
        show_progress=False,
        initial_alphabet=pre_tokenizers.ByteLevel.alphabet(),
    )
    tok.train_from_iterator(CORPUS, trainer)

    os.makedirs(FIXTURE_DIR, exist_ok=True)
    tok.save(os.path.join(FIXTURE_DIR, "tokenizer.json"))

    import json

    with open(
        os.path.join(FIXTURE_DIR, "tokenizer_config.json"), "w"
    ) as f:
        json.dump(
            {
                "tokenizer_class": "PreTrainedTokenizerFast",
                "eos_token": "<|im_end|>",
                "pad_token": "<|endoftext|>",
                "model_max_length": 32768,
            },
            f,
            indent=1,
        )
    print(f"wrote fixture tokenizer to {FIXTURE_DIR}")


if __name__ == "__main__":
    sys.exit(main())
