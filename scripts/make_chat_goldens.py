"""Generate the chat-template golden fixtures
(tests/fixtures/chat_template/golden.json).

Each case pins (a) the exact rendered string of render_chat — the
Qwen3-template contract — and (b) the exact token ids under the
committed mini fixture tokenizer. Regenerate ONLY when the template
contract deliberately changes; the point of the file is that accidental
renderer/tokenizer drift fails the golden tests.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from room_tpu.serving.tokenizer import HFTokenizer, render_chat  # noqa: E402

FIXTURES = os.path.join(
    os.path.dirname(__file__), "..", "tests", "fixtures"
)

WEATHER_TOOL = {
    "type": "function",
    "function": {
        "name": "get_weather",
        "description": "Get the weather for a city",
        "parameters": {
            "type": "object",
            "properties": {"city": {"type": "string"}},
            "required": ["city"],
        },
    },
}

CASES = [
    {
        "name": "system_user",
        "messages": [
            {"role": "system", "content": "You are a helpful assistant."},
            {"role": "user",
             "content": "What is the weather in Paris today?"},
        ],
        "tools": None,
        "add_generation_prompt": True,
    },
    {
        "name": "tools_section",
        "messages": [
            {"role": "system", "content": "You are a helpful assistant."},
            {"role": "user",
             "content": "What is the weather in Paris today?"},
        ],
        "tools": [WEATHER_TOOL],
        "add_generation_prompt": True,
    },
    {
        "name": "tool_call_roundtrip",
        "messages": [
            {"role": "user",
             "content": "What is the weather in Paris today?"},
            {"role": "assistant", "content": "",
             "tool_calls": [{
                 "function": {
                     "name": "get_weather",
                     "arguments": {"city": "Paris"},
                 },
             }]},
            {"role": "tool",
             "content": "The weather in Paris is sunny, 22 degrees."},
            {"role": "tool", "content": "hello world"},
        ],
        "tools": [WEATHER_TOOL],
        "add_generation_prompt": True,
    },
    {
        "name": "no_system_no_genprompt",
        "messages": [
            {"role": "user", "content": "hello world"},
            {"role": "assistant", "content": "the quick brown fox"},
        ],
        "tools": None,
        "add_generation_prompt": False,
    },
]


def main() -> None:
    tok = HFTokenizer(os.path.join(FIXTURES, "qwen_mini_tokenizer"))
    out = []
    for case in CASES:
        rendered = render_chat(
            case["messages"], case["tools"],
            add_generation_prompt=case["add_generation_prompt"],
        )
        out.append({**case, "rendered": rendered,
                    "ids": tok.encode(rendered)})
    dest = os.path.join(FIXTURES, "chat_template")
    os.makedirs(dest, exist_ok=True)
    with open(os.path.join(dest, "golden.json"), "w") as f:
        json.dump(out, f, indent=1, ensure_ascii=False)
    print(f"wrote {len(out)} golden cases to {dest}/golden.json")


if __name__ == "__main__":
    main()
