"""Live swarm benchmark harness (reference: scripts/experiment.js — the
reference's only perf harness: isolated server, temp DB, N cycles across
models, comparison table).

Runs real rooms against an in-process server + runtime on an isolated
temp database, measures cycle latency per model, and prints a table plus
a JSON summary.

Usage:
    python scripts/experiment.py --models echo tpu:tiny-moe \
        --workers 4 --cycles 3
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)
)))


def _pct(vals: list[float], q: float) -> float:
    """Linear-interpolated percentile (statistics.quantiles inclusive
    convention) shared by both runners so modes are comparable."""
    if not vals:
        return 0.0
    s = sorted(vals)
    if len(s) == 1:
        return round(s[0], 3)
    pos = q * (len(s) - 1)
    lo = int(pos)
    hi = min(lo + 1, len(s) - 1)
    return round(s[lo] + (s[hi] - s[lo]) * (pos - lo), 3)


def run_config(model: str, n_workers: int, n_cycles: int) -> dict:
    from room_tpu.core import agent_loop, rooms, workers
    from room_tpu.db import Database

    db = Database(":memory:")
    room = rooms.create_room(
        db, f"bench-{model.replace(':', '-')}", goal="benchmark run",
        worker_model=model, create_wallet=False,
    )
    agent_loop.set_room_launch_enabled(room["id"], True)
    team = [room["queen_worker_id"]]
    for i in range(n_workers):
        team.append(workers.create_worker(
            db, f"w{i}", "benchmark worker", room_id=room["id"],
            role="executor", model=model,
        ))

    latencies: list[float] = []
    tokens_out = 0
    errors = 0
    wall_start = time.perf_counter()
    for cycle_no in range(n_cycles):
        for wid in team:
            w = workers.get_worker(db, wid)
            t0 = time.perf_counter()
            try:
                row = agent_loop.run_cycle(db, room, w)
                latencies.append(time.perf_counter() - t0)
                tokens_out += row["output_tokens"] or 0
                if row["status"] != "success":
                    errors += 1
            except Exception:
                errors += 1
    wall = time.perf_counter() - wall_start

    agent_loop.set_room_launch_enabled(room["id"], False)
    db.close()
    return {
        "model": model,
        "agents": len(team),
        "cycles_run": len(latencies),
        "errors": errors,
        "p50_cycle_s": _pct(latencies, 0.5),
        "p90_cycle_s": _pct(latencies, 0.9),
        "output_tokens": tokens_out,
        "wall_s": round(wall, 2),
        "tokens_per_s": round(tokens_out / wall, 1) if wall else 0.0,
    }


def run_config_concurrent(
    model: str, n_workers: int, n_cycles: int
) -> dict:
    """The north-star shape (BASELINE.md): every worker streams cycles
    concurrently while the queen takes turns — queen-turn latency is
    reported separately. All agents share one serving engine, so this
    exercises the continuous batcher the way a live swarm does."""
    import threading

    from room_tpu.core import agent_loop, rooms, workers
    from room_tpu.db import Database

    db = Database(":memory:")
    room = rooms.create_room(
        db, f"bench-{model.replace(':', '-')}", goal="benchmark run",
        worker_model=model, create_wallet=False,
    )
    agent_loop.set_room_launch_enabled(room["id"], True)
    queen_id = room["queen_worker_id"]
    worker_ids = [
        workers.create_worker(
            db, f"w{i}", "benchmark worker", room_id=room["id"],
            role="executor", model=model,
        )
        for i in range(n_workers)
    ]

    queen_lat: list[float] = []
    worker_lat: list[float] = []
    tokens = [0]
    errors = [0]
    lock = threading.Lock()

    # barrier: every agent runs one untimed warmup cycle (XLA compiles
    # are a boot cost), then the timed phase starts together
    warm_barrier = threading.Barrier(1 + n_workers)
    wall_box = [0.0]

    def drive(wid: int, sink: list[float]) -> None:
        for cycle_no in range(n_cycles + 1):
            t0 = time.perf_counter()
            try:
                # inside the try: a get_worker failure during warmup
                # must still reach the barrier below or every other
                # thread deadlocks at it
                w = workers.get_worker(db, wid)
                row = agent_loop.run_cycle(db, room, w)
                dt = time.perf_counter() - t0
                if cycle_no > 0:
                    with lock:
                        sink.append(dt)
                        tokens[0] += row["output_tokens"] or 0
                        if row["status"] != "success":
                            errors[0] += 1
            except Exception:
                if cycle_no > 0:
                    with lock:
                        errors[0] += 1
            if cycle_no == 0:
                try:
                    # timeout breaks the barrier for everyone instead of
                    # hanging the run if a peer died before reaching it
                    warm_barrier.wait(timeout=600)
                except threading.BrokenBarrierError:
                    pass
                if wid == queen_id:
                    wall_box[0] = time.perf_counter()

    from room_tpu.serving.embed_service import get_embed_host

    get_embed_host().warmup()

    t_start = time.perf_counter()
    threads = [
        threading.Thread(target=drive, args=(wid, worker_lat))
        for wid in worker_ids
    ]
    for t in threads:
        t.start()
    drive(queen_id, queen_lat)          # queen turns on this thread
    for t in threads:
        t.join()
    wall = time.perf_counter() - (wall_box[0] or t_start)

    agent_loop.set_room_launch_enabled(room["id"], False)
    db.close()

    return {
        "model": model,
        "agents": 1 + n_workers,
        "cycles_run": len(queen_lat) + len(worker_lat),
        "errors": errors[0],
        "p50_cycle_s": _pct(worker_lat + queen_lat, 0.5),
        "p90_cycle_s": _pct(worker_lat + queen_lat, 0.9),
        "queen_p50_s": _pct(queen_lat, 0.5),
        "queen_p90_s": _pct(queen_lat, 0.9),
        "output_tokens": tokens[0],
        "wall_s": round(wall, 2),
        "tokens_per_s": round(tokens[0] / wall, 1) if wall else 0.0,
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--models", nargs="+", default=["echo"])
    ap.add_argument("--workers", type=int, default=4)
    ap.add_argument("--cycles", type=int, default=3)
    ap.add_argument("--concurrent", action="store_true",
                    help="all agents cycle in parallel against the "
                         "shared engine; queen latency reported "
                         "separately (the BASELINE.md p50 shape)")
    ap.add_argument("--json-out", default="")
    args = ap.parse_args()

    os.environ.setdefault("ROOM_TPU_DATA_DIR", tempfile.mkdtemp())

    runner = run_config_concurrent if args.concurrent else run_config
    results = [
        runner(m, args.workers, args.cycles) for m in args.models
    ]
    if args.json_out:
        with open(args.json_out, "w") as f:
            json.dump({"results": results}, f, indent=1)

    cols = ("model", "agents", "cycles_run", "errors", "p50_cycle_s",
            "p90_cycle_s", "output_tokens", "tokens_per_s")
    if args.concurrent:
        cols = cols[:6] + ("queen_p50_s", "queen_p90_s") + cols[6:]
    widths = {c: max(len(c), *(len(str(r[c])) for r in results))
              for c in cols}
    print("  ".join(c.ljust(widths[c]) for c in cols))
    print("  ".join("-" * widths[c] for c in cols))
    for r in results:
        print("  ".join(str(r[c]).ljust(widths[c]) for c in cols))
    print(json.dumps({"results": results}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
