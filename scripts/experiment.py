"""Live swarm benchmark harness (reference: scripts/experiment.js — the
reference's only perf harness: isolated server, temp DB, N cycles across
models, comparison table).

Runs real rooms against an in-process server + runtime on an isolated
temp database, measures cycle latency per model, and prints a table plus
a JSON summary.

Usage:
    python scripts/experiment.py --models echo tpu:tiny-moe \
        --workers 4 --cycles 3
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)
)))


def run_config(model: str, n_workers: int, n_cycles: int) -> dict:
    from room_tpu.core import agent_loop, rooms, workers
    from room_tpu.db import Database

    db = Database(":memory:")
    room = rooms.create_room(
        db, f"bench-{model.replace(':', '-')}", goal="benchmark run",
        worker_model=model, create_wallet=False,
    )
    agent_loop.set_room_launch_enabled(room["id"], True)
    team = [room["queen_worker_id"]]
    for i in range(n_workers):
        team.append(workers.create_worker(
            db, f"w{i}", "benchmark worker", room_id=room["id"],
            role="executor", model=model,
        ))

    latencies: list[float] = []
    tokens_out = 0
    errors = 0
    wall_start = time.perf_counter()
    for cycle_no in range(n_cycles):
        for wid in team:
            w = workers.get_worker(db, wid)
            t0 = time.perf_counter()
            try:
                row = agent_loop.run_cycle(db, room, w)
                latencies.append(time.perf_counter() - t0)
                tokens_out += row["output_tokens"] or 0
                if row["status"] != "success":
                    errors += 1
            except Exception:
                errors += 1
    wall = time.perf_counter() - wall_start

    agent_loop.set_room_launch_enabled(room["id"], False)
    db.close()
    lat_sorted = sorted(latencies) or [0.0]
    return {
        "model": model,
        "agents": len(team),
        "cycles_run": len(latencies),
        "errors": errors,
        "p50_cycle_s": round(statistics.median(lat_sorted), 3),
        "p90_cycle_s": round(
            lat_sorted[min(len(lat_sorted) - 1,
                           -(-9 * len(lat_sorted) // 10) - 1)], 3
        ),
        "output_tokens": tokens_out,
        "wall_s": round(wall, 2),
        "tokens_per_s": round(tokens_out / wall, 1) if wall else 0.0,
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--models", nargs="+", default=["echo"])
    ap.add_argument("--workers", type=int, default=4)
    ap.add_argument("--cycles", type=int, default=3)
    args = ap.parse_args()

    os.environ.setdefault("ROOM_TPU_DATA_DIR", tempfile.mkdtemp())

    results = [
        run_config(m, args.workers, args.cycles) for m in args.models
    ]

    cols = ("model", "agents", "cycles_run", "errors", "p50_cycle_s",
            "p90_cycle_s", "output_tokens", "tokens_per_s")
    widths = {c: max(len(c), *(len(str(r[c])) for r in results))
              for c in cols}
    print("  ".join(c.ljust(widths[c]) for c in cols))
    print("  ".join("-" * widths[c] for c in cols))
    for r in results:
        print("  ".join(str(r[c]).ljust(widths[c]) for c in cols))
    print(json.dumps({"results": results}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
