#!/bin/bash
# TPU window watcher (round 3): probe the axon tunnel until a green
# window opens, then immediately run the full bench + autotune sweep so
# the round records a real hardware number (VERDICT r2 item #1).
#
# Usage: bash scripts/tpu_watch.sh  (intended to run in the background)
# Logs:  /tmp/tpu_watch3.log, results in /tmp/bench_r3.json
LOG=${TPU_WATCH_LOG:-/tmp/tpu_watch3.log}
PROBE_TIMEOUT=${TPU_PROBE_TIMEOUT:-300}
COOLDOWN=${TPU_PROBE_COOLDOWN:-480}
cd "$(dirname "$0")/.." || exit 1

while true; do
  ts=$(date -u +%FT%TZ)
  echo "[$ts] probe start" >>"$LOG"
  if timeout "$PROBE_TIMEOUT" python -c "
import jax
d = jax.devices()
assert d and d[0].platform == 'tpu', d
import jax.numpy as jnp
x = jnp.ones((128, 128), jnp.bfloat16)
print('probe ok:', (x @ x).sum(), d)
" >>"$LOG" 2>&1; then
    ts=$(date -u +%FT%TZ)
    echo "[$ts] PROBE GREEN - running bench" >>"$LOG"
    timeout 2100 python bench.py >/tmp/bench_r3.json 2>>"$LOG"
    cat /tmp/bench_r3.json >>"$LOG"
    val=$(python -c "
import json
try:
    print(json.load(open('/tmp/bench_r3.json'))['value'])
except Exception:
    print(0)
")
    if python -c "import sys; sys.exit(0 if float('${val:-0}') > 0 else 1)"; then
      ts=$(date -u +%FT%TZ)
      echo "[$ts] BENCH NONZERO ($val tok/s) - running tune sweep" >>"$LOG"
      timeout 3600 python scripts/tpu_tune.py --quick --out /tmp/tpu_tune_r3.json \
        >>"$LOG" 2>&1
      echo "[$ts] watcher done" >>"$LOG"
      exit 0
    fi
    echo "[$ts] bench returned zero; cooling down" >>"$LOG"
  fi
  sleep "$COOLDOWN"
done
