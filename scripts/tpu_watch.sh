#!/bin/bash
# TPU window watcher: probe the axon tunnel until a green window opens,
# then immediately run the full bench + autotune sweep so the round
# records a real hardware number (VERDICT r2 #1).
#
# HARD RULE (learned in round 4): exactly ONE process may touch the
# chip. Three stale watchers probing concurrently — and SIGKILLing
# their own probes mid-claim on timeout — is itself the documented
# tunnel-wedge trigger. Exclusivity is a cooperative flock on
# /tmp/axon_chip.lock shared by every chip entry point: this watcher
# wraps each probe in it, and bench.py / scripts/tpu_tune.py acquire
# it themselves (bench.acquire_chip_lock), so the watcher must NOT
# hold it while invoking them. A separate instance lock stops a second
# watcher from ever starting.
#
# Usage: bash scripts/tpu_watch.sh  (intended to run in the background)
LOG=${TPU_WATCH_LOG:-/tmp/tpu_watch4.log}
CHIP_LOCK=${ROOM_TPU_CHIP_LOCK:-/tmp/axon_chip.lock}
INSTANCE_LOCK=${TPU_WATCH_INSTANCE_LOCK:-/tmp/tpu_watch.instance.lock}
PROBE_TIMEOUT=${TPU_PROBE_TIMEOUT:-600}
COOLDOWN=${TPU_PROBE_COOLDOWN:-900}
OUT=${TPU_BENCH_OUT:-/tmp/bench_r5.json}
cd "$(dirname "$0")/.." || exit 1

exec 8>"$INSTANCE_LOCK"
if ! flock -n 8; then
  echo "another watcher instance is running; refusing to double-probe" >&2
  exit 1
fi

while true; do
  ts=$(date -u +%FT%TZ)
  echo "[$ts] probe start (timeout ${PROBE_TIMEOUT}s)" >>"$LOG"
  if flock -w 900 "$CHIP_LOCK" timeout "$PROBE_TIMEOUT" python -c "
import jax
d = jax.devices()
assert d and d[0].platform == 'tpu', d
import jax.numpy as jnp
x = jnp.ones((128, 128), jnp.bfloat16)
print('probe ok:', (x @ x).sum(), d)
" >>"$LOG" 2>&1; then
    ts=$(date -u +%FT%TZ)
    echo "[$ts] PROBE GREEN - running bench" >>"$LOG"
    # bench/tune take the chip lock themselves (acquire_chip_lock)
    timeout 2100 python bench.py >"$OUT" 2>>"$LOG"
    cat "$OUT" >>"$LOG"
    val=$(python -c "
import json
try:
    print(json.load(open('$OUT'))['value'])
except Exception:
    print(0)
")
    if python -c "import sys; sys.exit(0 if float('${val:-0}') > 0 else 1)"; then
      ts=$(date -u +%FT%TZ)
      echo "[$ts] BENCH NONZERO ($val tok/s) - running tune sweep" >>"$LOG"
      timeout 3600 python scripts/tpu_tune.py --quick \
        --out /tmp/tpu_tune_r5.json >>"$LOG" 2>&1
      echo "[$ts] watcher done" >>"$LOG"
      exit 0
    fi
    echo "[$ts] bench returned zero; cooling down" >>"$LOG"
  else
    ts=$(date -u +%FT%TZ)
    echo "[$ts] probe timed out/failed; cooldown ${COOLDOWN}s" >>"$LOG"
  fi
  sleep "$COOLDOWN"
done
