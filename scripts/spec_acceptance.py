"""Per-class speculative-drafting acceptance on realistic traffic
(VERDICT r4 #5) — the data behind the deployment gamma default.

Replays the engine's greedy acceptance rule (longest draft prefix
matching the true continuation; room_tpu/serving/spec_replay.py)
over committed transcript fixtures: novel prose, code, and agent
tool-call traffic. Prints a markdown table of acceptance rate, draft
engage rate, and tokens emitted per forward (sequential decode = 1.0)
per class x gamma.

Usage: python scripts/spec_acceptance.py [--split 0.5]
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

FIXTURES = os.path.join(os.path.dirname(__file__), "..",
                        "tests", "fixtures", "traffic")
CLASSES = ("prose", "code", "toolcalls")


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--split", type=float, default=0.5,
                    help="fraction of each transcript used as history")
    ap.add_argument("--gammas", default="2,4,8")
    ap.add_argument("--tokenizer", choices=("byte", "bpe"),
                    default="byte",
                    help="byte = deployment ByteTokenizer; bpe = the "
                    "qwen-style mini BPE fixture (sensitivity check: "
                    "BPE merges shrink byte-level repetition)")
    args = ap.parse_args()

    from room_tpu.serving.spec_replay import replay_acceptance
    from room_tpu.serving.tokenizer import ByteTokenizer

    if args.tokenizer == "bpe":
        from room_tpu.serving.tokenizer import HFTokenizer

        tok = HFTokenizer(os.path.join(
            os.path.dirname(__file__), "..", "tests", "fixtures",
            "qwen_mini_tokenizer",
        ))
    else:
        tok = ByteTokenizer()
    gammas = [int(g) for g in args.gammas.split(",")]

    from room_tpu.models.config import qwen2_72b, qwen3_coder_30b
    from room_tpu.perf.roofline import V5E, predict_spec_class

    print("| class | gamma | acceptance | engage | tok/forward | "
          "30b-moe bs8 | 30b-moe bs32 | 72b-dense bs8 |")
    print("|---|---|---|---|---|---|---|---|")
    for cls in CLASSES:
        text = open(os.path.join(FIXTURES, cls + ".txt")).read()
        toks = tok.encode(text)
        cut = int(len(toks) * args.split)
        history, cont = toks[:cut], toks[cut:]
        for gamma in gammas:
            st = replay_acceptance(history, cont, gamma)
            # net TPU uplift: measured round mix x roofline step costs
            # (int8 weights+KV — the deployment quant config)
            ups = []
            for cfg, bs in ((qwen3_coder_30b(), 8),
                            (qwen3_coder_30b(), 32),
                            (qwen2_72b(), 8)):
                p = predict_spec_class(
                    cfg, V5E, bs, 2048.0, gamma,
                    st.rounds, st.plain_steps, st.emitted,
                    weight_bytes=1.0, kv_bytes=1.0,
                )
                ups.append(f"{p['uplift']:.2f}x")
            print(f"| {cls} | {gamma} | {st.acceptance:.3f} | "
                  f"{st.draft_engage_rate:.3f} | "
                  f"{st.tokens_per_forward:.2f} | "
                  f"{ups[0]} | {ups[1]} | {ups[2]} |")

    from room_tpu.perf.roofline import spec_cost_ratio

    print()
    print("Adaptive gate (engine default): round runs only when "
          "expected emission clears the verify/plain cost ratio")
    print("| class | shape | ratio | throttles | tok/forward | "
          "net uplift |")
    print("|---|---|---|---|---|---|")
    gamma = 4
    for cls in CLASSES:
        text = open(os.path.join(FIXTURES, cls + ".txt")).read()
        toks = tok.encode(text)
        cut = int(len(toks) * args.split)
        history, cont = toks[:cut], toks[cut:]
        for cfg, bs, label in ((qwen3_coder_30b(), 8, "30b-moe bs8"),
                               (qwen3_coder_30b(), 32, "30b-moe bs32"),
                               (qwen2_72b(), 8, "72b-dense bs8")):
            ratio = spec_cost_ratio(cfg, bs, gamma)
            st = replay_acceptance(history, cont, gamma,
                                   cost_ratio=ratio)
            p = predict_spec_class(
                cfg, V5E, bs, 2048.0, gamma,
                st.rounds, st.plain_steps, st.emitted,
                weight_bytes=1.0, kv_bytes=1.0,
            )
            print(f"| {cls} | {label} | {ratio:.2f} | {st.throttles} | "
                  f"{st.tokens_per_forward:.2f} | {p['uplift']:.2f}x |")


if __name__ == "__main__":
    main()
