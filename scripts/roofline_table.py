"""Print the roofline prediction tables (markdown) for ROUND5.md.

Usage: python scripts/roofline_table.py [--ctx 2048]

Covers the flagship serving model (qwen3-coder-30b), the hetero queen
model (qwen2.5-72b, per-chip slice not modeled — whole-model on one
chip shown for the bound structure), and the bench model bench.py
actually measures, so the first green hardware window can be compared
line-for-line.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--ctx", type=float, default=2048.0)
    ap.add_argument("--acceptance", type=float, default=0.8)
    args = ap.parse_args()

    from bench import bench_config
    from room_tpu.models.config import qwen3_coder_30b
    from room_tpu.perf.roofline import V5E, format_markdown, roofline_table

    for cfg in (bench_config(), qwen3_coder_30b()):
        rows = roofline_table(cfg, V5E, mean_ctx=args.ctx,
                              spec_acceptance=args.acceptance)
        print(format_markdown(rows, V5E, cfg, args.ctx))


if __name__ == "__main__":
    main()
