from .base import (
    ExecutionRequest,
    ExecutionResult,
    Provider,
    ProviderError,
    RateLimitExceeded,
    ToolDef,
)
from .registry import (
    get_model_auth_status,
    get_model_provider,
    model_name,
    normalize_model,
    provider_kind,
    reset_provider_cache,
)

__all__ = [
    "ExecutionRequest",
    "ExecutionResult",
    "Provider",
    "ProviderError",
    "RateLimitExceeded",
    "ToolDef",
    "get_model_auth_status",
    "get_model_provider",
    "model_name",
    "normalize_model",
    "provider_kind",
    "reset_provider_cache",
]
