"""Provider abstraction: one interface over every way a turn can execute.

Mirrors the reference's executor contract (reference:
src/shared/agent-executor.ts:11-39 AgentExecutionOptions /
executeAgent:91) — prompt + system + tools + tool-callback + session
continuity — with the tpu: provider as the first-class in-tree path.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Optional, Protocol

# OpenAI-format tool definition
ToolDef = dict  # {"name","description","parameters": json-schema}

# on_tool_call(name, arguments) -> result string
ToolCallback = Callable[[str, dict], str]


@dataclass
class ExecutionRequest:
    prompt: str
    system_prompt: Optional[str] = None
    model: str = "tpu"
    tools: list[ToolDef] = field(default_factory=list)
    on_tool_call: Optional[ToolCallback] = None
    max_turns: int = 10
    timeout_s: float = 900.0
    # continuity: either a provider-native session id, or the full
    # message history for stateless API providers
    session_id: Optional[str] = None
    messages: Optional[list[dict]] = None
    temperature: float = 0.7
    max_new_tokens: int = 1024
    on_text: Optional[Callable[[str], None]] = None
    # SLO class for the serving scheduler (docs/scheduler.md):
    # "queen" | "worker" | "background", tagged from the swarm role
    # that produced the turn (agent loop cycles, task runner). None /
    # unknown runs as "worker". Providers without class-aware
    # scheduling (API backends) simply ignore it.
    turn_class: Optional[str] = None
    # audit tag from journaled callers (agent loop / task runner),
    # matching the journal's provider_call record for this attempt
    # (docs/swarm_recovery.md). Scoped to ONE attempt — a recovery
    # retry is a new cycle/run and carries a new key — so forwarding it
    # upstream dedupes transport-level retries of the same request, not
    # crash replays (the journal's effect layer owns those).
    idempotency_key: Optional[str] = None


@dataclass
class ExecutionResult:
    text: str = ""
    success: bool = True
    error: Optional[str] = None
    session_id: Optional[str] = None
    messages: Optional[list[dict]] = None
    input_tokens: int = 0
    output_tokens: int = 0
    tool_calls: list[dict] = field(default_factory=list)
    turns_used: int = 0


class Provider(Protocol):
    name: str

    def execute(self, request: ExecutionRequest) -> ExecutionResult: ...

    def is_ready(self) -> tuple[bool, str]: ...


class ProviderError(RuntimeError):
    pass


class RateLimitExceeded(ProviderError):
    """Raised (or recorded) when the underlying model reports a
    rate/usage limit; carries the suggested wait."""

    def __init__(self, message: str, wait_s: float = 300.0) -> None:
        super().__init__(message)
        self.wait_s = wait_s
