"""Model-provider registry (reference: src/shared/model-provider.ts —
model-string grammar → provider, readiness probes, normalization).

Grammar:
    tpu / tpu:<model-name>      in-tree TPU serving engine (default)
    echo / echo:<script>        deterministic fake for hermetic tests
    openai:<model>              OpenAI-compatible HTTP API
    anthropic:<model>           Anthropic HTTP API
    gemini:<model>              Gemini HTTP API (OpenAI-compat endpoint)
    ollama:<tag>                localhost Ollama daemon (compat path)
    claude / claude:<model>     installed claude CLI (subscription auth)
    codex / codex:<model>       installed codex CLI (subscription auth)

Resilience: with ``ROOM_TPU_FALLBACK_MODELS`` set (comma-separated
model strings, e.g. ``claude,openai:gpt-4o-mini``), tpu: providers are
wrapped in a fail-closed fallback chain — when the in-tree engine is
unhealthy (crash loop) or raises a ProviderError out of execution, the
request routes to the first ready fallback provider instead of dying
with the engine. A single engine crash *within* the restart budget
surfaces as a crash-failed ExecutionResult, not an exception; set
``ROOM_TPU_FALLBACK_ON_CRASH=1`` to reroute those through the chain
too (off by default: the crashed attempt already burned a full turn's
latency, so the reroute roughly doubles time-to-answer). Fail-closed
means: if no fallback is ready either, the original error surfaces;
nothing silently swallows failures.
"""

from __future__ import annotations

from typing import Optional

from ..db import Database
from ..utils import knobs
from .base import (
    ExecutionRequest, ExecutionResult, Provider, ProviderError,
)

PROVIDER_PREFIXES = (
    "tpu", "echo", "openai", "anthropic", "gemini", "ollama",
    "claude", "codex",
)
DEFAULT_MODEL = "tpu"
DEFAULT_TPU_MODEL = "qwen3-coder-30b"

_instances: dict[str, Provider] = {}


def normalize_model(model: Optional[str]) -> str:
    if not model or model.strip() == "":
        return DEFAULT_MODEL
    return model.strip()


def provider_kind(model: Optional[str]) -> str:
    model = normalize_model(model)
    head = model.split(":", 1)[0]
    if head in PROVIDER_PREFIXES:
        return head
    # bare model names route to the in-tree engine
    return "tpu"


def model_name(model: Optional[str]) -> str:
    model = normalize_model(model)
    if ":" in model:
        return model.split(":", 1)[1]
    if model in PROVIDER_PREFIXES:
        return DEFAULT_TPU_MODEL if model == "tpu" else ""
    return model


FALLBACK_ENV = "ROOM_TPU_FALLBACK_MODELS"
FALLBACK_ON_CRASH_ENV = "ROOM_TPU_FALLBACK_ON_CRASH"


def fallback_models() -> list[str]:
    return [
        m.strip()
        for m in (knobs.get_str(FALLBACK_ENV) or "").split(",")
        if m.strip()
    ]


def fallback_on_crash() -> bool:
    """Opt-in reroute of crash-failed ExecutionResults (engine crashed
    mid-turn but stayed within its restart budget) through the fallback
    chain. Default off: the primary already spent the turn's latency
    before crashing, so rerouting roughly doubles time-to-answer."""
    return knobs.get_bool(FALLBACK_ON_CRASH_ENV)


def _is_crash_result(result: ExecutionResult) -> bool:
    """An engine-infrastructure crash surfaced as a failed result (the
    engine fails pending turns with an explicit 'engine crashed: ...'
    message). Model-level failures (max_turns, refusals, timeouts) are
    NOT crashes and never reroute."""
    return (
        not result.success
        and bool(result.error)
        and "engine crashed" in result.error
    )


class FallbackProvider:
    """Fail-closed fallback chain around the tpu: provider: when the
    engine is unhealthy (crash loop / not ready) or raises out of
    execution, try the configured CLI/HTTP fallbacks in order — first
    ready one serves the request. With ROOM_TPU_FALLBACK_ON_CRASH set,
    a crash-failed ExecutionResult (engine crashed within its restart
    budget) also reroutes. If nothing is ready, the PRIMARY error
    surfaces (never a silent swallow). Other result-level failures
    (model said something wrong, max_turns) do NOT fall back: only
    infrastructure failures reroute."""

    def __init__(
        self,
        primary: Provider,
        chain: list[str],
        db: Optional[Database] = None,
    ) -> None:
        self.name = f"{primary.name}+fallback"
        self.primary = primary
        self.chain = chain
        self._db = db

    def _primary_healthy(self) -> bool:
        try:
            from .tpu import get_model_host

            return get_model_host(
                getattr(self.primary, "model_name", "")
            ).is_healthy()
        except Exception:
            return False

    def is_ready(self) -> tuple[bool, str]:
        ready, detail = (False, "unknown")
        try:
            ready, detail = self.primary.is_ready()
        except Exception as e:
            detail = str(e)
        if ready:
            return True, detail
        for model in self.chain:
            try:
                # chain entries resolve UNWRAPPED (wrap_fallback=False)
                # so a tpu: fallback can never recurse into this chain
                fb_ready, fb_detail = get_model_provider(
                    model, self._db, wrap_fallback=False
                ).is_ready()
            except Exception:
                continue
            if fb_ready:
                return True, (
                    f"primary not ready ({detail}); falling back to "
                    f"{model}: {fb_detail}"
                )
        return False, detail

    def execute(self, request: ExecutionRequest) -> ExecutionResult:
        primary_error: Optional[BaseException] = None
        crash_result: Optional[ExecutionResult] = None
        if self._primary_healthy():
            try:
                result = self.primary.execute(request)
                if not (_is_crash_result(result) and fallback_on_crash()):
                    return result
                # engine crashed mid-turn (within its restart budget)
                # and the operator opted into the double-latency reroute
                crash_result = result
                primary_error = ProviderError(result.error)
            except ProviderError as e:
                primary_error = e
        else:
            primary_error = ProviderError(
                "tpu engine unhealthy (crash loop)"
            )
        for model in self.chain:
            try:
                provider = get_model_provider(
                    model, self._db, wrap_fallback=False
                )
                ready, _ = provider.is_ready()
                if not ready:
                    continue
                try:
                    from ..core.telemetry import incr_counter

                    incr_counter("provider.fallback")
                    if crash_result is not None:
                        incr_counter("provider.fallback_on_crash")
                except Exception:
                    pass
                return provider.execute(request)
            except ProviderError:
                continue
        if crash_result is not None:
            # chain exhausted: surface the crash-failed result exactly
            # as the un-rerouted path would have
            return crash_result
        raise primary_error  # fail closed: surface the real failure


def get_model_provider(
    model: Optional[str], db: Optional[Database] = None,
    wrap_fallback: bool = True,
) -> Provider:
    kind = provider_kind(model)
    fb = fallback_models() if (kind == "tpu" and wrap_fallback) else []
    # HTTP providers resolve credentials through the db, so the binding
    # is part of their identity (a db-less probe must not pin a cached
    # instance that can never see DB-stored keys); tpu/echo are db-free
    # and stay process-wide. A fallback-wrapped tpu provider carries the
    # db (its chain may include HTTP providers) and its chain spec.
    db_key = id(db) if (
        db is not None and (fb or kind in ("openai", "anthropic",
                                           "gemini", "ollama"))
    ) else 0
    key = f"{kind}:{model_name(model)}:{db_key}:{','.join(fb)}"
    if key in _instances:
        return _instances[key]

    if kind == "echo":
        from .echo import EchoProvider

        inst: Provider = EchoProvider(script=model_name(model))
    elif kind == "tpu":
        from .tpu import TpuProvider

        inst = TpuProvider(model_name(model) or DEFAULT_TPU_MODEL)
        if fb:
            inst = FallbackProvider(inst, fb, db=db)
    elif kind in ("openai", "gemini", "ollama"):
        from .http_api import OpenAICompatProvider

        inst = OpenAICompatProvider(kind, model_name(model), db=db)
    elif kind == "anthropic":
        from .http_api import AnthropicProvider

        inst = AnthropicProvider(model_name(model), db=db)
    elif kind == "claude":
        from .cli import ClaudeCliProvider

        inst = ClaudeCliProvider(model_name(model))
    elif kind == "codex":
        from .cli import CodexCliProvider

        inst = CodexCliProvider(model_name(model))
    else:  # pragma: no cover
        raise ProviderError(f"unknown provider for model {model!r}")

    _instances[key] = inst
    return inst


def get_model_auth_status(
    model: Optional[str], db: Optional[Database] = None
) -> dict:
    """Readiness probe (reference: getModelAuthStatus): can this model
    execute right now, and if not, why."""
    kind = provider_kind(model)
    try:
        ready, detail = get_model_provider(model, db).is_ready()
    except Exception as e:  # construction failure == not ready
        ready, detail = False, str(e)
    return {"provider": kind, "ready": ready, "detail": detail}


def reset_provider_cache() -> None:
    _instances.clear()
