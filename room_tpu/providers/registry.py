"""Model-provider registry (reference: src/shared/model-provider.ts —
model-string grammar → provider, readiness probes, normalization).

Grammar:
    tpu / tpu:<model-name>      in-tree TPU serving engine (default)
    echo / echo:<script>        deterministic fake for hermetic tests
    openai:<model>              OpenAI-compatible HTTP API
    anthropic:<model>           Anthropic HTTP API
    gemini:<model>              Gemini HTTP API (OpenAI-compat endpoint)
    ollama:<tag>                localhost Ollama daemon (compat path)
    claude / claude:<model>     installed claude CLI (subscription auth)
    codex / codex:<model>       installed codex CLI (subscription auth)
"""

from __future__ import annotations

from typing import Optional

from ..db import Database
from .base import Provider, ProviderError

PROVIDER_PREFIXES = (
    "tpu", "echo", "openai", "anthropic", "gemini", "ollama",
    "claude", "codex",
)
DEFAULT_MODEL = "tpu"
DEFAULT_TPU_MODEL = "qwen3-coder-30b"

_instances: dict[str, Provider] = {}


def normalize_model(model: Optional[str]) -> str:
    if not model or model.strip() == "":
        return DEFAULT_MODEL
    return model.strip()


def provider_kind(model: Optional[str]) -> str:
    model = normalize_model(model)
    head = model.split(":", 1)[0]
    if head in PROVIDER_PREFIXES:
        return head
    # bare model names route to the in-tree engine
    return "tpu"


def model_name(model: Optional[str]) -> str:
    model = normalize_model(model)
    if ":" in model:
        return model.split(":", 1)[1]
    if model in PROVIDER_PREFIXES:
        return DEFAULT_TPU_MODEL if model == "tpu" else ""
    return model


def get_model_provider(
    model: Optional[str], db: Optional[Database] = None
) -> Provider:
    kind = provider_kind(model)
    # HTTP providers resolve credentials through the db, so the binding
    # is part of their identity (a db-less probe must not pin a cached
    # instance that can never see DB-stored keys); tpu/echo are db-free
    # and stay process-wide.
    db_key = id(db) if (
        db is not None and kind in ("openai", "anthropic", "gemini",
                                    "ollama")
    ) else 0
    key = f"{kind}:{model_name(model)}:{db_key}"
    if key in _instances:
        return _instances[key]

    if kind == "echo":
        from .echo import EchoProvider

        inst: Provider = EchoProvider(script=model_name(model))
    elif kind == "tpu":
        from .tpu import TpuProvider

        inst = TpuProvider(model_name(model) or DEFAULT_TPU_MODEL)
    elif kind in ("openai", "gemini", "ollama"):
        from .http_api import OpenAICompatProvider

        inst = OpenAICompatProvider(kind, model_name(model), db=db)
    elif kind == "anthropic":
        from .http_api import AnthropicProvider

        inst = AnthropicProvider(model_name(model), db=db)
    elif kind == "claude":
        from .cli import ClaudeCliProvider

        inst = ClaudeCliProvider(model_name(model))
    elif kind == "codex":
        from .cli import CodexCliProvider

        inst = CodexCliProvider(model_name(model))
    else:  # pragma: no cover
        raise ProviderError(f"unknown provider for model {model!r}")

    _instances[key] = inst
    return inst


def get_model_auth_status(
    model: Optional[str], db: Optional[Database] = None
) -> dict:
    """Readiness probe (reference: getModelAuthStatus): can this model
    execute right now, and if not, why."""
    kind = provider_kind(model)
    try:
        ready, detail = get_model_provider(model, db).is_ready()
    except Exception as e:  # construction failure == not ready
        ready, detail = False, str(e)
    return {"provider": kind, "ready": ready, "detail": detail}


def reset_provider_cache() -> None:
    _instances.clear()
