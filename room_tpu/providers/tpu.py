"""The tpu: provider — in-tree serving replacing the reference's Ollama
daemon (reference: src/shared/local-model.ts pinned qwen3-coder:30b;
src/server/local-model.ts hardware gate / install session).

A ModelHost owns one served model: mesh, sharded params, tokenizer, and a
ServingEngine running on a background thread. The provider implements the
multi-turn tool loop *on top of parked decode*: when the engine stops a
turn at a closed <tool_call> block, the session's KV pages stay resident,
the host runs the tool, and the conversation resumes with only the tool
response prefilled (reference behavior: agent-executor.ts:404-471, but
with suspended-KV resume instead of a stateless re-send).

Weight resolution is fail-closed like the reference's Ollama probe
(local-model.ts:69-108): a checkpoint directory must exist for real
models; random-init is allowed only for tiny/bench models or when
ROOM_TPU_ALLOW_RANDOM_INIT=1.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Optional

from ..models import config as model_configs
from ..models import qwen3
from ..serving import faults
from ..utils import knobs, locks
from ..serving import lifecycle as lifecycle_mod
from ..serving.faults import FaultError
from ..serving.fleet import fleet_replicas_from_env
from ..serving.kv_offload import offload_enabled_from_env
from ..serving.prefix_store import prefix_store_enabled_from_env
from .base import ExecutionRequest, ExecutionResult, ProviderError

MODEL_CONFIGS: dict[str, Callable] = {
    "qwen3-coder-30b": model_configs.qwen3_coder_30b,
    "qwen2.5-72b": model_configs.qwen2_72b,
    "llama31-8b": model_configs.llama31_8b,
    "tiny-moe": model_configs.tiny_moe,
    "tiny-dense": model_configs.tiny_dense,
    "tiny-llama": model_configs.tiny_llama,
}

_hosts: dict[str, "ModelHost"] = {}
_hosts_lock = locks.make_lock("model_hosts")
# flipped by begin_drain_model_hosts: while True, engine() refuses to
# cold-build (a straggler request during the drain window would
# otherwise rebuild a host whose restore consumes the manifest the
# drain just wrote). Cleared by reset_model_hosts for same-process
# reuse (tests); a fresh process starts False.
_draining = False


def _random_init_allowed(name: str) -> bool:
    return (
        name.startswith("tiny")
        or knobs.get_bool("ROOM_TPU_ALLOW_RANDOM_INIT")
    )


def checkpoint_dir(name: str) -> Optional[str]:
    base = knobs.get_str("ROOM_TPU_CKPT_DIR")
    if not base:
        return None
    path = os.path.join(base, name)
    return path if os.path.isdir(path) else None


def _env_for(prefix: str, name: str) -> Optional[str]:
    """Per-model env override: ``<prefix>_<SLUG>`` wins over the bare
    ``<prefix>`` (slug = model name uppercased, non-alnum → ``_``)."""
    import re

    slug = re.sub(r"[^A-Z0-9]", "_", name.upper())
    return (
        knobs.get_dynamic(prefix + "_{MODEL}", slug)
        or knobs.get_raw(prefix)
    )


def mesh_env_for(name: str) -> Optional[str]:
    """Resolve the mesh spec string for a model: a per-model override
    (``ROOM_TPU_MESH_QWEN2_5_72B="1,1,4@0"``) wins over the global
    ``ROOM_TPU_MESH``. The ``@start`` device offset lets the hetero swarm
    place the queen and worker models on disjoint submeshes of one pod
    (BASELINE.md config #5)."""
    return _env_for("ROOM_TPU_MESH", name)


def replica_mesh_env_for(name: str, replica_idx: int) -> Optional[str]:
    """Mesh spec for ONE fleet replica (docs/fleet.md):
    ``ROOM_TPU_FLEET_MESHES="1,1,4@0;1,1,4@4"`` places replica i on
    the i-th ';'-separated submesh spec (disjoint device windows, the
    MULTICHIP hetero pattern). Fewer specs than replicas wraps around;
    unset falls back to the model's single-engine mesh env — every
    replica on the SAME spec is only sane on CPU or with distinct
    ``@start`` offsets, so deployments set the fleet knob."""
    specs = knobs.get_str("ROOM_TPU_FLEET_MESHES")
    if specs:
        parts = [s.strip() for s in specs.split(";") if s.strip()]
        if parts:
            return parts[replica_idx % len(parts)]
    return mesh_env_for(name)


def quant_env_for(name: str) -> Optional[str]:
    """Weight quantization mode for a model: ``ROOM_TPU_QUANT=int8``
    (or per-model ``ROOM_TPU_QUANT_<SLUG>``) serves int8 weight-only."""
    return _env_for("ROOM_TPU_QUANT", name)


class ModelHost:
    """One served model: engine + tokenizer + background scheduler."""

    def __init__(self, name: str) -> None:
        if name not in MODEL_CONFIGS:
            raise ProviderError(
                f"unknown tpu model {name!r}; known: "
                f"{sorted(MODEL_CONFIGS)}"
            )
        self.name = name
        self.cfg = MODEL_CONFIGS[name]()
        self._engine = None
        # host-side params (post-load/quant) cached across fleet
        # replica builds — init + checkpoint load runs once per host
        self._built_params = None
        self._built_draft = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._lock = locks.make_lock("model_host")

    def readiness(self) -> tuple[bool, str]:
        if checkpoint_dir(self.name):
            return True, f"checkpoint found for {self.name}"
        if _random_init_allowed(self.name):
            return True, f"random-init allowed for {self.name}"
        return False, (
            f"no checkpoint for {self.name}: set ROOM_TPU_CKPT_DIR to a "
            f"directory containing {self.name}/ (orbax tree), or "
            "ROOM_TPU_ALLOW_RANDOM_INIT=1 for synthetic weights"
        )

    def is_healthy(self) -> bool:
        """False once the engine has exhausted its crash-restart budget
        (or its thread died without supervision) — the registry's
        fallback chain keys off this."""
        with self._lock:
            eng = self._engine
            if eng is None:
                return True   # cold is not unhealthy
            # getattr: tests stub the engine with minimal doubles
            if not getattr(eng, "healthy", True):
                return False
            if self._thread is not None and \
                    not self._thread.is_alive() and \
                    not self._stop.is_set():
                return False
        return True

    def _start_engine_thread(self) -> None:
        self._thread = threading.Thread(
            target=self._engine.serve_forever,
            args=(self._stop,),
            daemon=True,
            name=f"tpu-engine-{self.name}",
        )
        self._thread.start()

    def engine(self):
        with self._lock:
            if self._engine is not None:
                if not getattr(self._engine, "healthy", True):
                    # fail-closed: a crash-looping engine must not
                    # accept traffic it will lose
                    raise ProviderError(
                        f"tpu engine for {self.name} is unhealthy "
                        "(crash-restart budget exhausted)"
                    )
                if self._thread is not None and \
                        not self._thread.is_alive() and \
                        not self._stop.is_set():
                    # supervised restart: the loop thread died (e.g. a
                    # crash escaped recovery) but the engine itself is
                    # serviceable
                    self._start_engine_thread()
                return self._engine
            if _draining:
                # the process is draining to its lifecycle manifest: a
                # straggler request must NOT cold-build a fresh engine
                # here — its restore_from_manifest would consume the
                # manifest the drain just wrote, destroying the
                # warm-restart handoff (the new engine would die
                # un-drained at exit while the clean marker still
                # claims every step completed)
                raise ProviderError(
                    f"tpu engine for {self.name} is draining for a "
                    "process restart; retry shortly"
                )
            ok, why = self.readiness()
            if not ok:
                raise ProviderError(why)

            n_replicas = fleet_replicas_from_env()
            if n_replicas > 1:
                # engine replica fleet (docs/fleet.md): N replicas on
                # hetero submeshes behind the KV-affinity router; a
                # replica crash fails over to siblings instead of
                # taking the model down, and drain_replica is the
                # blue/green deploy primitive
                from ..serving.fleet import EngineFleet

                if self.cfg.is_moe and (
                    knobs.get_str("ROOM_TPU_MOE_IMPL")
                    or self.cfg.moe_impl
                ) == "shardmap":
                    raise ProviderError(
                        "ROOM_TPU_FLEET_REPLICAS>1 is incompatible "
                        "with moe_impl=shardmap (the ep mesh registry "
                        "is keyed per model, not per replica)"
                    )
                self._engine = EngineFleet(
                    self.name, self._build_engine, n_replicas
                )
            else:
                self._engine = self._build_engine(0)
                # no fleet supervisor will ever rebuild this engine:
                # drop the cached host-RAM params copy instead of
                # pinning a second multi-GB weight set for the host's
                # lifetime (fleets keep it for replica rebuilds)
                self._built_params = None
                self._built_draft = None
            # warm restart (docs/lifecycle.md): rehydrate sessions a
            # previous process drained — BEFORE the serve thread owns
            # the engine (restore has engine-thread semantics). A
            # missing/stale manifest is a no-op; deployment default ON
            # (ROOM_TPU_LIFECYCLE=0 opts out) and drains only happen on
            # the graceful shutdown path, so tests never cross-talk.
            if lifecycle_mod.lifecycle_enabled_from_env("1"):
                self._engine.restore_from_manifest(
                    lifecycle_mod.engine_dir(self.name)
                )
            self._start_engine_thread()
            return self._engine

    def _build_engine(self, replica_idx: int = 0):
        """Build ONE ServingEngine — the classic single engine, or one
        fleet replica (its mesh resolved per replica via
        ROOM_TPU_FLEET_MESHES). Host-side param construction (init /
        checkpoint load / quantization) is cached across replicas so a
        fleet build pays it once; each replica shards its own copy
        onto its own submesh. No locks held here; callers serialize
        (ModelHost.engine holds the host lock, the fleet supervisor
        rebuilds one replica at a time)."""
        import jax

        from ..parallel import (
            decoder_param_specs, make_submesh, parse_mesh_spec,
            shard_pytree,
        )
        from ..serving import ServingEngine, load_tokenizer

        moe_env = knobs.get_str("ROOM_TPU_MOE_IMPL")
        if moe_env and self.cfg.is_moe:
            import dataclasses

            self.cfg = dataclasses.replace(
                self.cfg, moe_impl=moe_env
            )

        if self._built_params is None:
            params = qwen3.init_params(self.cfg, jax.random.PRNGKey(0))
            ckpt = checkpoint_dir(self.name)
            if ckpt:
                from ..utils.checkpoint import load_params

                params = load_params(ckpt, like=params)

            quant = quant_env_for(self.name)
            param_specs = decoder_param_specs(self.cfg)
            if quant:
                from ..ops.quant import (
                    quantize_decoder_params,
                    quantized_decoder_param_specs,
                    validate_quant_mode,
                )

                try:
                    validate_quant_mode(quant)
                except ValueError as e:
                    raise ProviderError(str(e)) from None
                params = quantize_decoder_params(params, self.cfg)
                param_specs = quantized_decoder_param_specs(self.cfg)
            self._built_params = (params, param_specs)
        params, param_specs = self._built_params

        mesh_env = replica_mesh_env_for(self.name, replica_idx)
        mesh = None
        if mesh_env:
            spec, start = parse_mesh_spec(mesh_env)
            mesh = make_submesh(spec, start)
            params = shard_pytree(params, param_specs, mesh)
        if self.cfg.moe_impl == "shardmap":
            if mesh is None:
                raise ProviderError(
                    "moe_impl=shardmap needs ROOM_TPU_MESH with an "
                    "ep axis"
                )
            from ..ops.moe_shardmap import set_ep_mesh

            set_ep_mesh(mesh, key=self.cfg.name)

        # optional tier-2 draft model (docs/serving.md): a tiny
        # decoder riding the SAME serving mesh as the target (the
        # embedder convention), proposing in-window drafts for lanes
        # where prompt-lookup finds no repeating n-gram.
        # ROOM_TPU_DRAFT_MODEL names a models.config.DRAFT_PRESETS
        # entry; its checkpoint loads from the shared CKPT_DIR under
        # that name. A missing checkpoint falls back to prompt-lookup
        # only (a randomly-initialized draft can't hurt correctness —
        # the target's verify rejects its noise — but drafting noise
        # burns verify width for nothing) unless random init is
        # explicitly allowed for tiny/test configs.
        draft = None
        draft_name = knobs.get_str(
            "ROOM_TPU_DRAFT_MODEL", scope="provider"
        )
        if draft_name:
            if self._built_draft is None:
                dcfg = model_configs.resolve_draft_config(
                    draft_name, self.cfg.vocab_size
                )
                dckpt = checkpoint_dir(draft_name)
                if dckpt or _random_init_allowed(draft_name):
                    dparams = qwen3.init_params(
                        dcfg, jax.random.PRNGKey(7)
                    )
                    if dckpt:
                        from ..utils.checkpoint import load_params

                        dparams = load_params(dckpt, like=dparams)
                    self._built_draft = (dcfg, dparams)
            if self._built_draft is not None:
                dcfg, dparams = self._built_draft
                if mesh is not None:
                    dparams = shard_pytree(
                        dparams, decoder_param_specs(dcfg), mesh
                    )
                draft = (dcfg, dparams)

        # the engine places its page pool on the same mesh as the
        # params so KV reads never cross chips
        return ServingEngine(
            self.cfg,
            params,
            tokenizer=load_tokenizer(),
            max_batch=knobs.get_int("ROOM_TPU_MAX_BATCH"),
            page_size=knobs.get_int("ROOM_TPU_PAGE_SIZE"),
            n_pages=knobs.get_int("ROOM_TPU_N_PAGES"),
            mesh=mesh,
            # speculative decoding ON by default in deployment
            # (VERDICT r2 #8, from the bench spec_agent A/B: 3.1x
            # tok/s at gamma=4 with 100% acceptance on tool-call-
            # repeating agent traffic; a no-draft round falls back
            # to the chunked scan, so non-repeating traffic pays
            # nothing). ROOM_TPU_SPEC_TOKENS=0 opts out. The
            # provider-on/library-off split is declared in the
            # knob registry (provider_default=4 vs default=0),
            # same convention as ROOM_TPU_OFFLOAD/LIFECYCLE.
            spec_tokens=knobs.get_int(
                "ROOM_TPU_SPEC_TOKENS", scope="provider"
            ),
            draft=draft,
            # tiered KV offload ON by default in deployment
            # (docs/kv_offload.md): the room workload parks every
            # worker mid-turn for tool calls, and hibernating
            # parked KV to host RAM/disk is what lets room size
            # scale past HBM capacity. The library default stays
            # off; ROOM_TPU_OFFLOAD=0 opts a deployment out.
            offload=offload_enabled_from_env("1"),
            # fleet-global shared prefix store ON by default in
            # deployment (docs/disagg.md): replicas publish computed
            # system-prompt prefix KV and pull it instead of
            # re-prefilling — the multiplier under disaggregated
            # routing, where decode replicas admit re-homed sessions.
            # Library default off; ROOM_TPU_PREFIX_STORE=0 opts out.
            prefix_store=prefix_store_enabled_from_env("1"),
        )

    def shutdown(
        self, drain: bool = False, budget_s: Optional[float] = None,
    ) -> Optional[dict]:
        """Stop the serve thread; with ``drain=True`` (the graceful
        SIGTERM path) also quiesce + spool the engine's sessions to the
        lifecycle manifest so the next process resumes them warm.
        ``budget_s`` lets drain_model_hosts hand each host the
        REMAINDER of one shared budget instead of a fresh one."""
        # ONE budget for the whole graceful path (ROOM_TPU_DRAIN_
        # DEADLINE_S, default 30 s): the serve-thread join and the KV
        # spooling share it, so a supervisor's terminationGracePeriod
        # sized to the knob actually holds — a join that eats the
        # budget leaves deadline 0 for drain (history-only manifest),
        # never budget + budget serially.
        if not drain:
            budget = 5.0
        elif budget_s is not None:
            budget = max(budget_s, 0.0)
        else:
            budget = max(5.0, lifecycle_mod.drain_deadline_s())
        t0 = time.monotonic()
        # _stop BEFORE the lock: a builder holding self._lock that is
        # about to start the serve thread will start it already-stopped
        self._stop.set()
        # serialize the ref capture against an in-flight engine()
        # build (which holds self._lock through build + restore +
        # thread start): without this, a straggler's
        # restore_from_manifest can consume the manifest this drain is
        # writing and sweep its fresh spool files — a "clean" shutdown
        # with every session's warm state destroyed. The wait burns
        # shared drain budget; if the builder eats it all the drain
        # degrades to a history-only manifest, still a correct (cold)
        # handoff. But the join + spool themselves run OUTSIDE the
        # lock: health probes (is_healthy) and straggler engine()
        # calls must get their fast draining/503 answer during the
        # drain window, not block on the host lock for 30 s — new
        # builds stay barred by _draining, so nothing can swap
        # self._engine under the drain. The acquire is BOUNDED by the
        # remaining budget: a multi-minute cold build must not stall
        # the exit past the deadline a supervisor's grace period is
        # sized to — on timeout there is no built engine serving
        # sessions to drain, so exit; with drain=True report the
        # failure so the clean marker is withheld (the mid-build
        # engine dies un-drained: next boot must say crash, not clean)
        thread = eng = None
        timed_out = not self._lock.acquire(
            timeout=max(0.1, budget - (time.monotonic() - t0))
        )
        if not timed_out:
            try:
                thread = self._thread
                eng = self._engine
            finally:
                self._lock.release()
        wedged = False
        if thread is not None:
            # wait the full budget, not a token 5 s: the serve thread
            # can only observe _stop between steps, and a cold
            # first-dispatch compile legitimately runs for most of a
            # minute — calling that "wedged" would abandon every
            # session's KV on a healthy engine. Operators with slower
            # compiles raise the knob; a second SIGTERM always
            # escalates past the wait.
            thread.join(
                timeout=max(0.0, budget - (time.monotonic() - t0))
            )
            wedged = thread.is_alive()
        summary = None
        if drain and eng is not None and hasattr(eng, "drain") and \
                getattr(eng, "healthy", True):
            try:
                # a serve thread that failed to quiesce may still be
                # mutating slot/KV state: never flush its window or
                # gather pages from under it — a zero deadline routes
                # every session to the history-only abandonment path
                # (the next boot re-prefills; nothing is adopted from
                # pages that might have been mid-mutation)
                remaining = max(
                    0.0, budget - (time.monotonic() - t0)
                )
                summary = eng.drain(
                    lifecycle_mod.engine_dir(self.name),
                    deadline_s=0.0 if wedged else remaining,
                    flush=not wedged,
                )
            except Exception:
                summary = None   # best-effort; never block exit
        if drain and timed_out:
            summary = {"manifest_written": False,
                       "error": "host lock timeout (build in flight)"}
        if self.cfg.moe_impl == "shardmap":
            from ..ops.moe_shardmap import set_ep_mesh

            set_ep_mesh(None, key=self.cfg.name)
        return summary


def get_model_host(name: str) -> ModelHost:
    with _hosts_lock:
        if name not in _hosts:
            _hosts[name] = ModelHost(name)
        return _hosts[name]


def reset_model_hosts() -> None:
    """Hard reset (tests, crash paths): no drain, no manifest."""
    global _draining
    with _hosts_lock:
        for h in _hosts.values():
            h.shutdown()
        _hosts.clear()
        _draining = False


def begin_drain_model_hosts() -> None:
    """Flip every warm engine to draining NOW (admission 503s) and bar
    cold engine builds, without waiting for the serve threads to
    quiesce — the first thing the SIGTERM handler does."""
    global _draining
    with _hosts_lock:
        _draining = True
        hosts = list(_hosts.values())
    for h in hosts:
        eng = h._engine
        if eng is not None and hasattr(eng, "begin_drain"):
            eng.begin_drain()


def end_drain_model_hosts() -> None:
    """Re-open engine builds once the graceful stop has fully torn
    down (API stopped, marker written). Not part of drain_model_hosts:
    while the old API is still accepting requests a straggler must
    keep getting 503s, or it could rebuild a host whose restore
    consumes the manifest the drain just wrote. After teardown the
    flag would only sabotage a same-process start_server(), whose
    first build SHOULD consume that manifest — that is the
    warm-restart contract."""
    global _draining
    with _hosts_lock:
        _draining = False


def drain_model_hosts() -> dict[str, dict]:
    """Graceful counterpart of reset_model_hosts: drain every warm
    engine to its lifecycle manifest (docs/lifecycle.md). Returns the
    per-model drain summaries."""
    with _hosts_lock:
        hosts = dict(_hosts)
        _hosts.clear()
    out: dict[str, dict] = {}
    enabled = lifecycle_mod.lifecycle_enabled_from_env("1")
    # ONE drain budget shared across every host (serial drains): a
    # supervisor grace period sized to ROOM_TPU_DRAIN_DEADLINE_S must
    # bound the whole exit, not deadline × n_models — later hosts get
    # whatever remains (0 ⇒ history-only manifest, still written)
    budget = max(5.0, lifecycle_mod.drain_deadline_s())
    t0 = time.monotonic()
    for name, h in hosts.items():
        had_engine = h._engine is not None
        summary = h.shutdown(
            drain=enabled,
            budget_s=max(0.0, budget - (time.monotonic() - t0)),
        )
        if summary is not None:
            out[name] = summary
        elif enabled and had_engine:
            # drain raised (shutdown swallowed it): record the failure
            # so the caller can withhold the clean-shutdown marker —
            # this shutdown did NOT complete every step
            out[name] = {"manifest_written": False,
                         "error": "drain failed"}
    return out


def engines_snapshot() -> dict[str, dict]:
    """Public stats view over the live model hosts (for /api/tpu/engines
    and monitoring) — takes the registry lock, never exposes internals.

    Fleet hosts (docs/fleet.md) emit one block PER REPLICA under
    ``model#rid`` keys — siblings must never overwrite each other's
    scheduler/offload/lifecycle blocks — plus a fleet aggregate under
    the bare model name carrying the router/failover surface."""
    from ..serving.fleet import EngineFleet

    with _hosts_lock:
        hosts = dict(_hosts)
    out: dict[str, dict] = {}
    for name, host in hosts.items():
        engine = host._engine
        if engine is None:
            out[name] = {"status": "cold", "healthy": True}
        elif isinstance(engine, EngineFleet):
            healthy = host.is_healthy()
            out[name] = {
                "status": "serving" if healthy else "unhealthy",
                **engine.stats(),
                "sessions": len(engine.sessions),
                "max_batch": engine.max_batch,
                "healthy": healthy,
                # sharded router tier (docs/podnet.md): the full
                # per-shard block rides inside the fleet stats above;
                # the flat count is the cheap capacity-planning signal
                "router_shards": engine.n_router_shards,
            }
            for h in engine.replicas:
                e = h.engine
                r_healthy = getattr(e, "healthy", True)
                out[f"{name}#{h.rid}"] = {
                    "status": h.state if h.state != "serving"
                    else ("serving" if r_healthy else "unhealthy"),
                    **e.stats(),
                    "free_pages": e.page_table.free_pages,
                    "sessions": len(e.sessions),
                    "max_batch": e.max_batch,
                    "healthy": r_healthy,
                    "replica": {
                        "rid": h.rid,
                        "state": h.state,
                        "strikes": h.strikes,
                        "score": round(h.health_score(), 1),
                    },
                }
        else:
            healthy = host.is_healthy()
            out[name] = {
                "status": "serving" if healthy else "unhealthy",
                **engine.stats(),
                "free_pages": engine.page_table.free_pages,
                "sessions": len(engine.sessions),
                "max_batch": engine.max_batch,
                "healthy": healthy,
            }
    return out


def _encode_retrying(tok, text: str) -> list[int]:
    """Tokenizer call with a bounded retry on injected transient
    faults; exhaustion surfaces as ProviderError (a failed result /
    registry fallback), never a half-submitted turn."""
    last: Optional[BaseException] = None
    for attempt in range(3):
        try:
            faults.maybe_fail("tokenizer")
            return tok.encode(text)
        except FaultError as e:
            last = e
            if not e.transient:
                break
            time.sleep(0.01 * (attempt + 1))
    raise ProviderError(f"tokenizer failed: {last}")


class TpuProvider:
    def __init__(self, model_name: str) -> None:
        self.name = "tpu"
        self.model_name = model_name

    def is_ready(self) -> tuple[bool, str]:
        host = get_model_host(self.model_name)
        if not host.is_healthy():
            return False, (
                f"tpu engine for {self.model_name} is unhealthy "
                "(crash loop)"
            )
        return host.readiness()

    def execute(self, request: ExecutionRequest) -> ExecutionResult:
        from ..serving import SamplingParams, render_chat

        host = get_model_host(self.model_name)
        engine = host.engine()
        tok = engine.tokenizer

        messages = list(request.messages or [])
        if request.system_prompt and not any(
            m.get("role") == "system" for m in messages
        ):
            messages.insert(
                0, {"role": "system", "content": request.system_prompt}
            )
        messages.append({"role": "user", "content": request.prompt})

        ephemeral = request.session_id is None
        session_id = request.session_id or f"tpu-{time.monotonic_ns()}"
        fresh_session = session_id not in engine.sessions

        # a fresh session prefills the whole conversation; a resumed one
        # only prefills the new user turn
        if fresh_session:
            prompt_text = render_chat(messages, request.tools)
        else:
            prompt_text = render_chat(
                messages[-1:], None, add_generation_prompt=True
            )

        sampling = SamplingParams(
            temperature=request.temperature,
            top_p=0.95,
            max_new_tokens=request.max_new_tokens,
        )

        deadline = time.monotonic() + request.timeout_s
        if faults.should_fire("provider_timeout"):
            # chaos fault point: force the deadline so the timeout
            # path (clean failure + session release) is exercised
            deadline = time.monotonic()
        result = ExecutionResult(session_id=session_id)
        assistant_text = ""
        try:
            prompt_tokens = _encode_retrying(tok, prompt_text)
            assistant_text = self._turn_loop(
                request, engine, tok, session_id, sampling,
                prompt_tokens, deadline, result,
            )
        finally:
            if ephemeral:
                # every exit — success, timeout, tokenizer fault,
                # engine crash — must return the one-shot session's
                # paged-KV pages to the pool
                engine.release_session(session_id)
                result.session_id = None

        # strip chat scaffolding from the visible reply
        visible = assistant_text.replace("<|im_end|>", "").strip()
        result.text = visible
        messages.append({"role": "assistant", "content": visible})
        result.messages = messages
        return result

    def _turn_loop(
        self, request, engine, tok, session_id, sampling,
        prompt_tokens, deadline, result,
    ) -> str:
        from ..serving import extract_tool_call

        assistant_text = ""
        for turn_no in range(max(request.max_turns, 1)):
            t = engine.submit(
                prompt_tokens, session_id=session_id, sampling=sampling,
                # SLO class from the swarm role (docs/scheduler.md):
                # queen turns admit ahead of workers ahead of
                # background task runs, and the ladder sheds in the
                # reverse order
                turn_class=request.turn_class,
            )
            remaining = deadline - time.monotonic()
            if not t.done.wait(timeout=max(remaining, 0.001)):
                result.success = False
                result.error = f"timeout after {request.timeout_s}s"
                break
            result.turns_used += 1
            result.input_tokens += len(prompt_tokens)
            result.output_tokens += len(t.new_tokens)

            text = engine.text_of(t)
            if request.on_text:
                request.on_text(text)

            if t.finish_reason == "error":
                result.success = False
                result.error = t.error
                break

            if t.finish_reason == "tool_call" and request.on_tool_call:
                call = extract_tool_call(text)
                assistant_text += text
                if call is None:
                    # corrective nudge instead of failing the turn
                    prompt_tokens = _encode_retrying(
                        tok,
                        "\n<tool_response>\nerror: malformed tool call —"
                        " emit exactly one JSON object with \"name\" and"
                        " \"arguments\".\n</tool_response>\n"
                    )
                    continue
                tool_result = request.on_tool_call(
                    call.get("name", ""), call.get("arguments", {}) or {}
                )
                result.tool_calls.append(
                    {
                        "name": call.get("name"),
                        "arguments": call.get("arguments"),
                        "result": tool_result,
                    }
                )
                # resume the parked session with only the tool response
                prompt_tokens = _encode_retrying(
                    tok,
                    f"\n<tool_response>\n{tool_result}\n"
                    "</tool_response>\n"
                )
                continue

            assistant_text += text
            break
        else:
            result.success = False
            result.error = f"max_turns {request.max_turns} exceeded"
        return assistant_text
