"""Deterministic fake provider for hermetic tests — the equivalent of the
reference suite's mocked executor boundary (reference:
src/shared/__tests__/agent-loop.test.ts:7-19 vi.mock('../agent-executor')).

Behavior is scriptable per instance:
- default: echoes a digest of the prompt
- `responses` queue: pop one per call
- `tool_script`: list of (tool_name, arguments) the fake "model" calls
  through on_tool_call before emitting its final text
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from .base import ExecutionRequest, ExecutionResult


@dataclass
class EchoProvider:
    script: str = ""
    name: str = "echo"
    responses: list[str] = field(default_factory=list)
    tool_script: list[tuple[str, dict]] = field(default_factory=list)
    fail_with: Optional[str] = None
    calls: list[ExecutionRequest] = field(default_factory=list)

    def is_ready(self) -> tuple[bool, str]:
        return True, "echo provider always ready"

    def execute(self, request: ExecutionRequest) -> ExecutionResult:
        self.calls.append(request)
        if self.fail_with:
            return ExecutionResult(
                success=False, error=self.fail_with,
                session_id=request.session_id,
            )

        tool_calls = []
        turns = 1
        if request.on_tool_call is not None:
            for name, args in self.tool_script[: request.max_turns]:
                result = request.on_tool_call(name, args)
                tool_calls.append(
                    {"name": name, "arguments": args, "result": result}
                )
                turns += 1

        if self.responses:
            text = self.responses.pop(0)
        else:
            text = f"echo: {request.prompt[:120]}"
        if request.on_text:
            request.on_text(text)

        prompt_len = len(request.prompt.split())
        return ExecutionResult(
            text=text,
            success=True,
            session_id=request.session_id or "echo-session",
            messages=(request.messages or [])
            + [
                {"role": "user", "content": request.prompt},
                {"role": "assistant", "content": text},
            ],
            input_tokens=prompt_len,
            output_tokens=len(text.split()),
            tool_calls=tool_calls,
            turns_used=turns,
        )
