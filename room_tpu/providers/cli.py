"""claude/codex CLI providers: subprocess spawn + streamed JSON parsing.

The reference's primary execution path for subscription users drives the
installed agent CLIs (reference: src/shared/claude-code.ts:165-353 spawns
``claude -p <prompt> --output-format stream-json --verbose`` and parses
assistant/result events; src/shared/agent-executor.ts:154-313 spawns
``codex exec --json --skip-git-repo-check`` and parses thread.started /
item.completed JSONL; src/server/provider-cli.ts probes install/auth
state). This module rebuilds that contract on subprocess + reader
threads: line-buffered stream parsing, hard timeout with SIGTERM→SIGKILL
escalation, cooperative abort, session-id capture for resume.

Test seam: ROOM_TPU_CLAUDE_CLI / ROOM_TPU_CODEX_CLI point at the binary
(mock scripts in tests); otherwise PATH lookup like the reference.
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from .base import ExecutionRequest, ExecutionResult
from ..utils import knobs

PROBE_TIMEOUT_S = 1.5
KILL_GRACE_S = 5.0


def resolve_cli_path(provider: str) -> Optional[str]:
    env_override = knobs.get_dynamic(
        "ROOM_TPU_{PROVIDER}_CLI", provider.upper()
    )
    if env_override:
        return env_override if os.path.exists(env_override) else None
    return shutil.which(provider)


def probe_installed(provider: str) -> dict:
    """{"installed": bool, "version": str?} via `<cli> --version`
    (reference: provider-cli.ts probeProviderInstalled)."""
    path = resolve_cli_path(provider)
    if not path:
        return {"installed": False}
    try:
        out = subprocess.run(
            [path, "--version"], capture_output=True, text=True,
            timeout=PROBE_TIMEOUT_S, env=_clean_env(),
        )
    except (OSError, subprocess.TimeoutExpired):
        return {"installed": False}
    if out.returncode != 0:
        return {"installed": False}
    return {"installed": True, "version": out.stdout.strip() or None}


def probe_connected(provider: str) -> Optional[bool]:
    """True/False when determinable, None when the CLI isn't installed
    (reference: provider-cli.ts probeProviderConnected)."""
    if not probe_installed(provider)["installed"]:
        return None
    home = os.path.expanduser("~")
    if provider == "claude":
        if os.environ.get("ANTHROPIC_API_KEY"):
            return True
        cred = os.path.join(home, ".claude", ".credentials.json")
        try:
            with open(cred) as f:
                creds = json.load(f)
            return bool(
                (creds.get("claudeAiOAuth") or {}).get("accessToken")
            )
        except (OSError, json.JSONDecodeError):
            return False
    if provider == "codex":
        if os.environ.get("OPENAI_API_KEY"):
            return True
        auth = os.path.join(home, ".codex", "auth.json")
        try:
            with open(auth) as f:
                data = json.load(f)
            return bool(data.get("OPENAI_API_KEY") or data.get("tokens"))
        except (OSError, json.JSONDecodeError):
            return False
    return None


def _clean_env() -> dict:
    """Reference deletes ELECTRON_RUN_AS_NODE / CLAUDECODE so a spawned
    claude doesn't think it's nested inside itself."""
    env = dict(os.environ)
    env.pop("ELECTRON_RUN_AS_NODE", None)
    env.pop("CLAUDECODE", None)
    return env


@dataclass
class CliRunResult:
    exit_code: int
    stdout: str = ""
    stderr: str = ""
    timed_out: bool = False
    aborted: bool = False
    duration_s: float = 0.0


def stream_cli(
    cmd: list[str],
    on_line: Callable[[str], None],
    *,
    timeout_s: float = 900.0,
    abort_event: Optional[threading.Event] = None,
) -> CliRunResult:
    """Spawn, stream stdout line-by-line into ``on_line`` (partial final
    line included at close, matching the reference's buffer flush), hard
    timeout + abort with SIGTERM then SIGKILL."""
    t0 = time.monotonic()
    try:
        proc = subprocess.Popen(
            cmd, stdin=subprocess.DEVNULL, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True, env=_clean_env(),
        )
    except OSError as e:
        return CliRunResult(exit_code=1, stderr=f"failed to spawn: {e}")

    stdout_parts: list[str] = []
    stderr_parts: list[str] = []

    def read_stdout() -> None:
        buf = ""
        while True:
            chunk = proc.stdout.read(4096)
            if not chunk:
                break
            stdout_parts.append(chunk)
            buf += chunk
            *lines, buf = buf.split("\n")
            for line in lines:
                if line.strip():
                    try:
                        on_line(line)
                    except Exception:
                        pass
        if buf.strip():
            try:
                on_line(buf)
            except Exception:
                pass

    def read_stderr() -> None:
        while True:
            chunk = proc.stderr.read(4096)
            if not chunk:
                break
            stderr_parts.append(chunk)

    t_out = threading.Thread(target=read_stdout, daemon=True)
    t_err = threading.Thread(target=read_stderr, daemon=True)
    t_out.start()
    t_err.start()

    deadline = t0 + timeout_s
    timed_out = aborted = False
    while True:
        if proc.poll() is not None:
            break
        if abort_event is not None and abort_event.is_set():
            aborted = True
            _kill_graceful(proc)
            break
        if time.monotonic() >= deadline:
            timed_out = True
            _kill_graceful(proc)
            break
        time.sleep(0.05)

    proc.wait()
    t_out.join(timeout=2)
    t_err.join(timeout=2)
    code = proc.returncode
    if aborted:
        code = 130
    elif timed_out and code == 0:
        code = 124
    return CliRunResult(
        exit_code=code,
        stdout="".join(stdout_parts),
        stderr="".join(stderr_parts),
        timed_out=timed_out,
        aborted=aborted,
        duration_s=time.monotonic() - t0,
    )


def _kill_graceful(proc: subprocess.Popen) -> None:
    """SIGTERM, then SIGKILL after the grace window (reference's
    kill('SIGTERM') + 5s setTimeout SIGKILL)."""
    try:
        proc.terminate()
    except OSError:
        return
    try:
        proc.wait(timeout=KILL_GRACE_S)
    except subprocess.TimeoutExpired:
        try:
            proc.kill()
        except OSError:
            pass


# ---- event parsers ----

@dataclass
class StreamEvents:
    """Accumulated output of one CLI run."""
    texts: list[str] = field(default_factory=list)
    tool_calls: list[dict] = field(default_factory=list)
    session_id: Optional[str] = None
    result_text: Optional[str] = None


def parse_claude_line(line: str, ev: StreamEvents,
                      on_text: Optional[Callable] = None) -> None:
    """claude stream-json events (reference: claude-code.ts:286-330)."""
    try:
        event = json.loads(line)
    except json.JSONDecodeError:
        return
    etype = event.get("type")
    if etype == "assistant":
        content = (event.get("message") or {}).get("content")
        if isinstance(content, list):
            for block in content:
                if not isinstance(block, dict):
                    continue
                if block.get("type") == "text" and \
                        isinstance(block.get("text"), str):
                    ev.texts.append(block["text"])
                    if on_text:
                        on_text(block["text"])
                elif block.get("type") == "tool_use":
                    ev.tool_calls.append({
                        "name": block.get("name", "tool"),
                        "arguments": block.get("input") or {},
                    })
    elif etype == "result":
        if event.get("result"):
            ev.result_text = str(event["result"])
        if event.get("session_id"):
            ev.session_id = str(event["session_id"])


def parse_codex_line(line: str, ev: StreamEvents,
                     on_text: Optional[Callable] = None) -> None:
    """codex exec --json JSONL (reference: agent-executor.ts:768-808)."""
    try:
        event = json.loads(line)
    except json.JSONDecodeError:
        return
    etype = event.get("type")
    if etype == "thread.started" and \
            isinstance(event.get("thread_id"), str):
        ev.session_id = event["thread_id"]
        return
    if etype == "item.completed":
        item = event.get("item") or {}
        itype = item.get("type")
        if itype == "agent_message" and isinstance(item.get("text"), str):
            ev.texts.append(item["text"])
            if on_text:
                on_text(item["text"])
        elif itype == "mcp_tool_call":
            ev.tool_calls.append({
                "name": item.get("tool", "unknown"),
                "arguments": item.get("arguments") or {},
            })


# ---- providers ----

class ClaudeCliProvider:
    """Drives the installed `claude` CLI in headless print mode."""

    def __init__(self, model: str = "") -> None:
        self.name = "claude"
        self.model = model  # suffix after claude:, may be ""

    def is_ready(self) -> tuple[bool, str]:
        probe = probe_installed("claude")
        if not probe["installed"]:
            return False, (
                "claude CLI not found. Install from "
                "https://docs.anthropic.com/en/docs/claude-code"
            )
        connected = probe_connected("claude")
        if connected is False:
            return False, (
                "claude CLI not authenticated: run `claude login` or "
                "set ANTHROPIC_API_KEY"
            )
        return True, f"claude CLI {probe.get('version') or ''}".strip()

    def execute(self, request: ExecutionRequest) -> ExecutionResult:
        path = resolve_cli_path("claude")
        if not path:
            return ExecutionResult(
                success=False, error="claude CLI not found",
            )
        args = [path, "-p", request.prompt,
                "--output-format", "stream-json", "--verbose"]
        if request.session_id:
            args += ["--resume", request.session_id]
        if request.system_prompt:
            args += ["--system-prompt", request.system_prompt]
        if self.model:
            args += ["--model", self.model]
        if request.max_turns:
            args += ["--max-turns", str(request.max_turns)]

        ev = StreamEvents()
        run = stream_cli(
            args,
            lambda line: parse_claude_line(line, ev, request.on_text),
            timeout_s=request.timeout_s,
        )
        text = ev.result_text or "\n\n".join(ev.texts).strip()
        result = ExecutionResult(
            text=text,
            session_id=ev.session_id,
            tool_calls=ev.tool_calls,
            turns_used=1,
        )
        if run.timed_out:
            result.success = False
            result.error = f"timeout after {request.timeout_s}s"
        elif run.exit_code != 0:
            result.success = False
            result.error = (
                run.stderr.strip() or f"claude exited {run.exit_code}"
            )
        return result


class CodexCliProvider:
    """Drives the installed `codex` CLI in exec JSONL mode."""

    def __init__(self, model: str = "") -> None:
        self.name = "codex"
        self.model = model

    def is_ready(self) -> tuple[bool, str]:
        probe = probe_installed("codex")
        if not probe["installed"]:
            return False, (
                "codex CLI not found. Install with "
                "`npm install -g @openai/codex`"
            )
        connected = probe_connected("codex")
        if connected is False:
            return False, (
                "codex CLI not authenticated: run `codex login` or set "
                "OPENAI_API_KEY"
            )
        return True, f"codex CLI {probe.get('version') or ''}".strip()

    def execute(self, request: ExecutionRequest) -> ExecutionResult:
        path = resolve_cli_path("codex")
        if not path:
            return ExecutionResult(
                success=False, error="codex CLI not found",
            )
        prompt = request.prompt
        if request.system_prompt:
            prompt = f"{request.system_prompt}\n\n{prompt}"
        if request.session_id:
            args = [path, "exec", "resume", "--json",
                    "--skip-git-repo-check", request.session_id, prompt]
        else:
            args = [path, "exec", "--json", "--skip-git-repo-check",
                    prompt]
        if self.model:
            # lands before --json in both forms (reference's splice(2))
            i = args.index("--json")
            args[i:i] = ["--model", self.model]

        ev = StreamEvents()
        run = stream_cli(
            args,
            lambda line: parse_codex_line(line, ev, request.on_text),
            timeout_s=request.timeout_s,
        )
        text = "\n\n".join(ev.texts).strip() or run.stderr.strip()
        result = ExecutionResult(
            text=text,
            session_id=ev.session_id or request.session_id,
            tool_calls=ev.tool_calls,
            turns_used=1,
        )
        if run.timed_out:
            result.success = False
            result.error = f"timeout after {request.timeout_s}s"
        elif run.exit_code != 0:
            result.success = False
            result.error = (
                run.stderr.strip() or f"codex exited {run.exit_code}"
            )
        return result
