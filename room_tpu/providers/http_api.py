"""HTTP API providers: OpenAI-compatible (OpenAI / Gemini / Ollama
localhost) and Anthropic — the external-provider paths the reference
drives with fetch (reference: src/shared/agent-executor.ts:327-740).

All requests go through one seam (`_post_json`) so tests can stub the
network; failures are fail-closed with rate-limit detection feeding the
engine's backoff."""

from __future__ import annotations

import json
import os
import urllib.error
import urllib.request
from typing import Any, Optional

from ..core.rate_limit import detect_rate_limit
from ..utils import knobs
from .base import (
    ExecutionRequest,
    ExecutionResult,
    ProviderError,
    RateLimitExceeded,
)

API_BASES = {
    "openai": "https://api.openai.com/v1",
    "gemini": "https://generativelanguage.googleapis.com/v1beta/openai",
    "ollama": "http://127.0.0.1:11434/v1",
    "anthropic": "https://api.anthropic.com/v1",
}

KEY_ENV = {
    "openai": "OPENAI_API_KEY",
    "gemini": "GEMINI_API_KEY",
    "anthropic": "ANTHROPIC_API_KEY",
    "ollama": None,
}


def _post_json(
    url: str, body: dict, headers: dict, timeout: float
) -> dict:
    req = urllib.request.Request(
        url,
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json", **headers},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return json.loads(resp.read())
    except urllib.error.HTTPError as e:
        payload = e.read().decode(errors="replace")
        wait = detect_rate_limit(payload) or (
            60.0 if e.code == 429 else None
        )
        if wait is not None:
            raise RateLimitExceeded(payload[:500], wait) from e
        raise ProviderError(f"HTTP {e.code}: {payload[:500]}") from e
    except (urllib.error.URLError, OSError, TimeoutError) as e:
        raise ProviderError(f"network unreachable: {e}") from e


def _resolve_key(kind: str, db) -> Optional[str]:
    env = KEY_ENV.get(kind)
    if env is None:
        return None  # keyless (ollama)
    if db is not None:
        from ..core.credentials import resolve_api_key

        v = resolve_api_key(db, env)
        if v:
            return v
    return os.environ.get(env)


class OpenAICompatProvider:
    def __init__(self, kind: str, model: str, db=None) -> None:
        self.name = kind
        self.model = model
        self._db = db
        self.base = knobs.get_dynamic(
            "ROOM_TPU_{KIND}_BASE", kind.upper(),
            default=API_BASES[kind],
        )

    def is_ready(self) -> tuple[bool, str]:
        if KEY_ENV.get(self.name) is None:
            return True, "keyless provider"
        key = _resolve_key(self.name, self._db)
        if key:
            return True, "api key resolved"
        return False, f"no {KEY_ENV[self.name]} available"

    def _headers(self) -> dict:
        key = _resolve_key(self.name, self._db)
        return {"Authorization": f"Bearer {key}"} if key else {}

    def execute(self, request: ExecutionRequest) -> ExecutionResult:
        messages = list(request.messages or [])
        if request.system_prompt and not any(
            m.get("role") == "system" for m in messages
        ):
            messages.insert(
                0, {"role": "system", "content": request.system_prompt}
            )
        messages.append({"role": "user", "content": request.prompt})

        tools = [
            {"type": "function", "function": t} for t in request.tools
        ] or None
        result = ExecutionResult(session_id=request.session_id)

        for _ in range(max(request.max_turns, 1)):
            body: dict[str, Any] = {
                "model": self.model,
                "messages": messages,
                "temperature": request.temperature,
                "max_tokens": request.max_new_tokens,
            }
            if tools:
                body["tools"] = tools
            try:
                headers = self._headers()
                if request.idempotency_key:
                    # transport-retry dedup where the service supports
                    # it; unknown headers are ignored harmlessly
                    headers["Idempotency-Key"] = request.idempotency_key
                out = _post_json(
                    f"{self.base}/chat/completions", body,
                    headers, request.timeout_s,
                )
            except RateLimitExceeded:
                raise
            except ProviderError as e:
                result.success = False
                result.error = str(e)
                result.messages = messages
                return result

            usage = out.get("usage", {})
            result.input_tokens += usage.get("prompt_tokens", 0)
            result.output_tokens += usage.get("completion_tokens", 0)
            result.turns_used += 1

            choice = out.get("choices", [{}])[0]
            msg = choice.get("message", {})
            messages.append(msg)
            calls = msg.get("tool_calls")
            if calls and request.on_tool_call:
                for call in calls:
                    fn = call.get("function", {})
                    try:
                        args = json.loads(fn.get("arguments") or "{}")
                    except json.JSONDecodeError:
                        args = {}
                    tool_out = request.on_tool_call(
                        fn.get("name", ""), args
                    )
                    result.tool_calls.append(
                        {"name": fn.get("name"), "arguments": args,
                         "result": tool_out}
                    )
                    messages.append(
                        {
                            "role": "tool",
                            "tool_call_id": call.get("id", ""),
                            "content": tool_out,
                        }
                    )
                continue

            result.text = msg.get("content") or ""
            if request.on_text:
                request.on_text(result.text)
            result.messages = messages
            return result

        result.success = False
        result.error = f"max_turns {request.max_turns} exceeded"
        result.messages = messages
        return result


class AnthropicProvider:
    def __init__(self, model: str, db=None) -> None:
        self.name = "anthropic"
        self.model = model
        self._db = db
        self.base = knobs.get_dynamic(
            "ROOM_TPU_{KIND}_BASE", "ANTHROPIC",
            default=API_BASES["anthropic"],
        )

    def is_ready(self) -> tuple[bool, str]:
        key = _resolve_key("anthropic", self._db)
        return (True, "api key resolved") if key else (
            False, "no ANTHROPIC_API_KEY available"
        )

    def _headers(self) -> dict:
        return {
            "x-api-key": _resolve_key("anthropic", self._db) or "",
            "anthropic-version": "2023-06-01",
        }

    def execute(self, request: ExecutionRequest) -> ExecutionResult:
        messages = list(request.messages or [])
        messages.append({"role": "user", "content": request.prompt})
        tools = [
            {
                "name": t["name"],
                "description": t.get("description", ""),
                "input_schema": t.get(
                    "parameters", {"type": "object", "properties": {}}
                ),
            }
            for t in request.tools
        ] or None

        result = ExecutionResult(session_id=request.session_id)
        for _ in range(max(request.max_turns, 1)):
            body: dict[str, Any] = {
                "model": self.model,
                "max_tokens": request.max_new_tokens,
                "messages": messages,
            }
            if request.system_prompt:
                body["system"] = request.system_prompt
            if tools:
                body["tools"] = tools
            try:
                headers = self._headers()
                if request.idempotency_key:
                    headers["Idempotency-Key"] = request.idempotency_key
                out = _post_json(
                    f"{self.base}/messages", body, headers,
                    request.timeout_s,
                )
            except RateLimitExceeded:
                raise
            except ProviderError as e:
                result.success = False
                result.error = str(e)
                result.messages = messages
                return result

            usage = out.get("usage", {})
            result.input_tokens += usage.get("input_tokens", 0)
            result.output_tokens += usage.get("output_tokens", 0)
            result.turns_used += 1

            content = out.get("content", [])
            messages.append({"role": "assistant", "content": content})
            tool_uses = [
                c for c in content if c.get("type") == "tool_use"
            ]
            if tool_uses and request.on_tool_call:
                tool_results = []
                for tu in tool_uses:
                    tool_out = request.on_tool_call(
                        tu.get("name", ""), tu.get("input", {}) or {}
                    )
                    result.tool_calls.append(
                        {"name": tu.get("name"),
                         "arguments": tu.get("input"),
                         "result": tool_out}
                    )
                    tool_results.append(
                        {
                            "type": "tool_result",
                            "tool_use_id": tu.get("id", ""),
                            "content": tool_out,
                        }
                    )
                messages.append(
                    {"role": "user", "content": tool_results}
                )
                continue

            result.text = "".join(
                c.get("text", "") for c in content
                if c.get("type") == "text"
            )
            if request.on_text:
                request.on_text(result.text)
            result.messages = messages
            return result

        result.success = False
        result.error = f"max_turns {request.max_turns} exceeded"
        result.messages = messages
        return result
