"""Runtime lock-order witness ("lockdep") — the dynamic half of the
lockmap concurrency layer (docs/static_analysis.md).

Armed via ``ROOM_TPU_LOCKDEP`` (off by default), ``locks.make_lock``
returns a :class:`LockdepLock` instead of a bare ``threading`` lock.
The wrapper records, process-wide:

- the **observed acquisition order**: acquiring B while holding A
  records the directed edge A -> B (lock *names*, the registry's
  class-level granularity — same-name pairs are skipped, they are
  cross-instance hierarchies the name graph cannot order);
- an **inversion**: acquiring B while holding A when B -> A was
  observed earlier anywhere in the process — the classic ABBA
  deadlock precursor. Strict mode (``ROOM_TPU_LOCKDEP_STRICT``,
  default on — the CI chaos tiers are the primary consumer) raises
  :class:`LockOrderError` *before* blocking; production arms with
  strict off and gets a counter (``lockdep_inversions``) plus the
  recorded pair in :func:`snapshot` instead;
- a **same-instance re-acquire** of a non-reentrant lock, which would
  deadlock the thread silently: always raises, strict or not (raising
  is strictly better than hanging);
- **hold times** per lock name, into the telemetry histogram
  ``lockdep_hold_ms.<name>`` when telemetry is loaded.

The witness asserts observed order against the static graph only in
tests (``tests/test_analysis.py`` pins observed edges ⊆ the lockmap
AST graph); at runtime it is self-contained — no AST pass on the hot
path. State is process-global so edges learned in one subsystem
protect every other; :func:`reset` exists for test isolation.

All bookkeeping runs under one plain meta-lock with a thread-local
reentrancy guard, so instrumenting the telemetry counter lock itself
cannot recurse.
"""

from __future__ import annotations

import sys
import threading
import time
from typing import Optional

__all__ = [
    "LockdepLock", "LockOrderError", "enabled", "strict",
    "observed_edges", "inversions", "snapshot", "reset",
]

_MAX_INVERSIONS = 256   # bounded evidence ring; counter keeps the total


class LockOrderError(RuntimeError):
    """A lock-order inversion (or same-instance re-acquire) the
    witness refused to let block."""


def enabled() -> bool:
    from . import knobs

    return knobs.get_bool("ROOM_TPU_LOCKDEP")


def strict() -> bool:
    from . import knobs

    return knobs.get_bool("ROOM_TPU_LOCKDEP_STRICT")


# ---- global witness state (meta-locked, never instrumented) ----

_meta = threading.Lock()
# (held_name, acquired_name) -> first-witness description
_edges: dict[tuple, str] = {}
_inversion_count = 0
_inversions: list[dict] = []


class _PerThread(threading.local):
    def __init__(self) -> None:
        self.held: list = []     # [name, id(lock), t_acquire, depth]
        self.in_lockdep = False  # reentrancy guard for telemetry


_tls = _PerThread()


def _telemetry_observe(name: str, ms: float) -> None:
    """Hold-time histogram via telemetry IF it is loaded — resolved
    through sys.modules so utils never imports the core package."""
    mod = sys.modules.get("room_tpu.core.telemetry")
    if mod is None:
        return
    try:
        mod.observe_ms(f"lockdep_hold_ms.{name}", ms)
    except Exception:
        pass


def _telemetry_count(name: str, n: int = 1) -> None:
    mod = sys.modules.get("room_tpu.core.telemetry")
    if mod is None:
        return
    try:
        mod.incr_counter(name, n)
    except Exception:
        pass


class LockdepLock:
    """Instrumented Lock/RLock with the full ``threading`` surface the
    tree uses: ``acquire(blocking=, timeout=)``, ``release()``,
    context manager, ``locked()``."""

    __slots__ = ("name", "_inner", "_kind")

    def __init__(self, name: str, inner, kind: str) -> None:
        self.name = name
        self._inner = inner
        self._kind = kind

    # -- bookkeeping --------------------------------------------------

    def _precheck(self) -> Optional[str]:
        """Ordering verdict BEFORE blocking on the inner lock.
        Returns an error description, or None when clean. Reentrant
        re-acquires bump the held entry's depth and return None."""
        tls = _tls
        if tls.in_lockdep:
            return None
        me = id(self)
        for entry in tls.held:
            if entry[1] == me:
                if self._kind == "rlock":
                    return None   # counted at _book time
                return (
                    f"same-instance re-acquire of non-reentrant lock "
                    f"'{self.name}' — this thread would deadlock"
                )
        verdict: Optional[str] = None
        recorded = 0
        with _meta:
            for entry in tls.held:
                held_name = entry[0]
                if held_name == self.name:
                    continue   # cross-instance hierarchy, unordered
                if (self.name, held_name) in _edges:
                    verdict = (
                        f"lock-order inversion: acquiring "
                        f"'{self.name}' while holding '{held_name}', "
                        f"but '{self.name}' -> '{held_name}' was "
                        f"observed at {_edges[(self.name, held_name)]}"
                    )
                    self._record_inversion(held_name)
                    recorded += 1
        if recorded:
            # OUTSIDE _meta, reentrancy-guarded: the telemetry counter
            # lock is itself a LockdepLock, so counting from inside
            # the meta section would re-enter _precheck and deadlock
            # on _meta — the witness must never hang the thread it is
            # protecting
            tls.in_lockdep = True
            try:
                _telemetry_count("lockdep_inversions", recorded)
            finally:
                tls.in_lockdep = False
        return verdict

    def _record_inversion(self, held_name: str) -> None:
        # caller holds _meta; telemetry is counted by the caller AFTER
        # _meta is released (counting here would recurse into lockdep
        # through the instrumented telemetry lock)
        global _inversion_count
        _inversion_count += 1
        if len(_inversions) < _MAX_INVERSIONS:
            _inversions.append({
                "acquired": self.name, "held": held_name,
                "thread": threading.current_thread().name,
                "prior": _edges.get((self.name, held_name), ""),
            })

    def _book(self) -> None:
        """Record the successful acquire: held stack + order edges."""
        tls = _tls
        if tls.in_lockdep:
            return
        me = id(self)
        for entry in tls.held:
            if entry[1] == me:
                entry[3] += 1
                return
        where = threading.current_thread().name
        with _meta:
            for entry in tls.held:
                held_name = entry[0]
                if held_name == self.name:
                    continue
                if (self.name, held_name) in _edges:
                    # a counted (non-strict) inversion proceeds to
                    # acquire: recording the reverse direction here
                    # would make every LATER acquisition in the
                    # original sanctioned order count as an inversion
                    # too — the one real ABBA becomes unbounded noise.
                    # The pair stays ordered as first witnessed.
                    continue
                if (held_name, self.name) not in _edges:
                    _edges[(held_name, self.name)] = where
        tls.held.append([self.name, me, time.monotonic(), 1])

    def _unbook(self) -> None:
        tls = _tls
        if tls.in_lockdep:
            return
        me = id(self)
        for i in range(len(tls.held) - 1, -1, -1):
            entry = tls.held[i]
            if entry[1] == me:
                entry[3] -= 1
                if entry[3] <= 0:
                    del tls.held[i]
                    tls.in_lockdep = True
                    try:
                        _telemetry_observe(
                            self.name,
                            (time.monotonic() - entry[2]) * 1000.0,
                        )
                    finally:
                        tls.in_lockdep = False
                return

    # -- threading.Lock surface ---------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1
                ) -> bool:
        problem = self._precheck()
        if problem is not None:
            if "same-instance" in problem or strict():
                raise LockOrderError(problem)
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._book()
        return got

    def release(self) -> None:
        self._inner.release()
        self._unbook()

    def locked(self) -> bool:
        inner_locked = getattr(self._inner, "locked", None)
        if inner_locked is not None:
            return inner_locked()
        return False   # RLock has no locked(); mirror that

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:   # pragma: no cover - debug aid
        return f"<LockdepLock {self.name!r} {self._inner!r}>"


# ---- inspection / test surface ----

def observed_edges() -> set:
    """The witnessed (held, then-acquired) name pairs so far."""
    with _meta:
        return set(_edges)


def inversions() -> list[dict]:
    with _meta:
        return list(_inversions)


def snapshot() -> dict:
    """Health/metrics surface: edge count, inversion count + bounded
    evidence."""
    with _meta:
        return {
            "enabled": enabled(),
            "edges": len(_edges),
            "inversions": _inversion_count,
            "evidence": list(_inversions),
        }


def reset() -> None:
    """Drop all witnessed state (test isolation). Also clears the
    CALLING thread's held stack — a strict-mode raise mid-test leaves
    the outer acquire booked with no release, and carrying that
    phantom hold across tests would fabricate edges implicating the
    wrong code (other threads' stacks are unreachable thread-locals
    and die with their threads)."""
    global _inversion_count
    with _meta:
        _edges.clear()
        _inversions.clear()
        _inversion_count = 0
    _tls.held.clear()
