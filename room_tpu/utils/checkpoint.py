"""Model checkpoint save/restore via orbax.

Weights are immutable at serving time; per-session state lives in the KV
pages (SURVEY.md §5 checkpoint/resume — the WIP/session tables map to
paged-KV session ids, while model checkpoints are plain orbax trees)."""

from __future__ import annotations

import os
from typing import Any, Optional

import jax


def save_params(path: str, params: Any) -> None:
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(path, params)
    ckptr.wait_until_finished()


def load_params(
    path: str, like: Optional[Any] = None, shardings: Optional[Any] = None
) -> Any:
    """Restore a param pytree. `like` provides structure/dtypes;
    `shardings` (a pytree of NamedSharding) restores directly onto the
    mesh without a host round-trip."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    ckptr = ocp.StandardCheckpointer()
    if like is None:
        return ckptr.restore(path)
    target = like
    if shardings is not None:
        target = jax.tree.map(
            lambda x, s: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s),
            like, shardings,
        )
    return ckptr.restore(path, target)
