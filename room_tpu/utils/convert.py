"""Checkpoint converter: HuggingFace Qwen3-MoE / Qwen2 safetensors →
room_tpu param tree (stacked layer axes), saved as an orbax tree the
`tpu:` provider loads directly.

Usage:
    python -m room_tpu.utils.convert /path/to/hf-model \
        /path/to/ckpts/qwen3-coder-30b --model qwen3-coder-30b

The mapping is the inverse of models.qwen3.init_params' layout: per-layer
HF tensors are transposed into [in, out] matmul orientation and stacked
along a leading layer axis; MoE experts stack along the expert axis.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
from typing import Iterator

import numpy as np

from ..models import qwen3
from ..models.config import DecoderConfig


def _iter_safetensors(model_dir: str) -> Iterator[tuple[str, np.ndarray]]:
    from safetensors import safe_open

    files = sorted(glob.glob(os.path.join(model_dir, "*.safetensors")))
    if not files:
        raise FileNotFoundError(
            f"no .safetensors files under {model_dir}"
        )
    for path in files:
        with safe_open(path, framework="np") as f:
            for name in f.keys():
                yield name, f.get_tensor(name)


def convert_hf_decoder(
    model_dir: str, cfg: DecoderConfig, dtype: str = "bfloat16"
):
    """Returns the room_tpu param pytree as numpy (ml_dtypes for bf16)."""
    import ml_dtypes

    np_dtype = (
        ml_dtypes.bfloat16 if dtype == "bfloat16" else np.float32
    )
    L = cfg.n_layers

    def zeros(shape):
        return np.zeros(shape, np_dtype)

    layers: dict[str, np.ndarray] = {
        "wq": zeros((L, cfg.hidden, cfg.q_dim)),
        "wk": zeros((L, cfg.hidden, cfg.kv_dim)),
        "wv": zeros((L, cfg.hidden, cfg.kv_dim)),
        "wo": zeros((L, cfg.q_dim, cfg.hidden)),
        "ln1": zeros((L, cfg.hidden)),
        "ln2": zeros((L, cfg.hidden)),
    }
    if cfg.qkv_bias:
        layers["bq"] = zeros((L, cfg.q_dim))
        layers["bk"] = zeros((L, cfg.kv_dim))
        layers["bv"] = zeros((L, cfg.kv_dim))
    if cfg.qk_norm:
        layers["q_norm"] = zeros((L, cfg.head_dim))
        layers["k_norm"] = zeros((L, cfg.head_dim))
    if cfg.is_moe:
        E, F = cfg.n_experts, cfg.moe_intermediate
        layers["router"] = np.zeros((L, cfg.hidden, E), np.float32)
        layers["w_gate"] = zeros((L, E, cfg.hidden, F))
        layers["w_up"] = zeros((L, E, cfg.hidden, F))
        layers["w_down"] = zeros((L, E, F, cfg.hidden))
    else:
        F = cfg.intermediate
        layers["w_gate"] = zeros((L, cfg.hidden, F))
        layers["w_up"] = zeros((L, cfg.hidden, F))
        layers["w_down"] = zeros((L, F, cfg.hidden))

    params: dict = {
        "embed": zeros((cfg.vocab_size, cfg.hidden)),
        "layers": layers,
        "final_norm": zeros((cfg.hidden,)),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = zeros((cfg.hidden, cfg.vocab_size))

    def put(target: np.ndarray, index, tensor: np.ndarray,
            transpose: bool = False) -> None:
        t = tensor.astype(np.float32)
        if transpose:
            t = t.T
        target[index] = t.astype(target.dtype)

    n_loaded = 0
    for name, tensor in _iter_safetensors(model_dir):
        n_loaded += 1
        if name == "model.embed_tokens.weight":
            put(params["embed"], slice(None), tensor)
            continue
        if name == "lm_head.weight":
            if "lm_head" in params:
                put(params["lm_head"], slice(None), tensor,
                    transpose=True)
            continue
        if name == "model.norm.weight":
            put(params["final_norm"], slice(None), tensor)
            continue
        parts = name.split(".")
        if len(parts) < 4 or parts[1] != "layers":
            continue
        li = int(parts[2])
        rest = ".".join(parts[3:])

        # attention (HF Linear weights are [out, in] -> transpose)
        simple = {
            "self_attn.q_proj.weight": ("wq", True),
            "self_attn.k_proj.weight": ("wk", True),
            "self_attn.v_proj.weight": ("wv", True),
            "self_attn.o_proj.weight": ("wo", True),
            "self_attn.q_proj.bias": ("bq", False),
            "self_attn.k_proj.bias": ("bk", False),
            "self_attn.v_proj.bias": ("bv", False),
            "self_attn.q_norm.weight": ("q_norm", False),
            "self_attn.k_norm.weight": ("k_norm", False),
            "input_layernorm.weight": ("ln1", False),
            "post_attention_layernorm.weight": ("ln2", False),
            "mlp.gate_proj.weight": ("w_gate", True),
            "mlp.up_proj.weight": ("w_up", True),
            "mlp.down_proj.weight": ("w_down", True),
            "mlp.gate.weight": ("router", True),
        }
        if rest in simple:
            key, transpose = simple[rest]
            if key in layers:
                put(layers[key], li, tensor, transpose=transpose)
            continue
        # MoE experts: mlp.experts.<e>.{gate,up,down}_proj.weight
        if rest.startswith("mlp.experts."):
            ep = rest.split(".")
            ei = int(ep[2])
            proj = ep[3]
            key = {"gate_proj": "w_gate", "up_proj": "w_up",
                   "down_proj": "w_down"}.get(proj)
            if key is not None:
                put(layers[key], (li, ei), tensor, transpose=True)
            continue
    if n_loaded == 0:
        raise RuntimeError("no tensors read")
    return params


def convert_hf_encoder(model_dir: str, cfg=None):
    """BERT/MiniLM-style safetensors → models.embedder param tree
    (stacked layer axes, [in, out] matmul orientation). Covers the
    all-MiniLM-L6-v2 checkpoint the reference embedded with via ONNX
    (reference: src/shared/embeddings.ts:33-100)."""
    from ..models.config import EncoderConfig

    cfg = cfg or EncoderConfig()
    dt = np.float32
    D, L, F = cfg.hidden, cfg.n_layers, cfg.intermediate

    def zeros(shape):
        return np.zeros(shape, dt)

    layers = {
        "wq": zeros((L, D, D)), "bq": zeros((L, D)),
        "wk": zeros((L, D, D)), "bk": zeros((L, D)),
        "wv": zeros((L, D, D)), "bv": zeros((L, D)),
        "wo": zeros((L, D, D)), "bo": zeros((L, D)),
        "attn_ln_scale": zeros((L, D)), "attn_ln_bias": zeros((L, D)),
        "w_in": zeros((L, D, F)), "b_in": zeros((L, F)),
        "w_out": zeros((L, F, D)), "b_out": zeros((L, D)),
        "ffn_ln_scale": zeros((L, D)), "ffn_ln_bias": zeros((L, D)),
    }
    params = {
        "word_embed": zeros((cfg.vocab_size, D)),
        "pos_embed": zeros((cfg.max_positions, D)),
        "type_embed": zeros((2, D)),
        "embed_ln_scale": zeros((D,)),
        "embed_ln_bias": zeros((D,)),
        "layers": layers,
    }

    top = {
        "embeddings.word_embeddings.weight": "word_embed",
        "embeddings.position_embeddings.weight": "pos_embed",
        "embeddings.token_type_embeddings.weight": "type_embed",
        "embeddings.LayerNorm.weight": "embed_ln_scale",
        "embeddings.LayerNorm.bias": "embed_ln_bias",
    }
    per_layer = {
        "attention.self.query.weight": ("wq", True),
        "attention.self.query.bias": ("bq", False),
        "attention.self.key.weight": ("wk", True),
        "attention.self.key.bias": ("bk", False),
        "attention.self.value.weight": ("wv", True),
        "attention.self.value.bias": ("bv", False),
        "attention.output.dense.weight": ("wo", True),
        "attention.output.dense.bias": ("bo", False),
        "attention.output.LayerNorm.weight": ("attn_ln_scale", False),
        "attention.output.LayerNorm.bias": ("attn_ln_bias", False),
        "intermediate.dense.weight": ("w_in", True),
        "intermediate.dense.bias": ("b_in", False),
        "output.dense.weight": ("w_out", True),
        "output.dense.bias": ("b_out", False),
        "output.LayerNorm.weight": ("ffn_ln_scale", False),
        "output.LayerNorm.bias": ("ffn_ln_bias", False),
    }

    n_loaded = 0
    for name, tensor in _iter_safetensors(model_dir):
        # some exports nest under "bert." / "model."
        for prefix in ("bert.", "model.", ""):
            if name.startswith(prefix):
                key = name[len(prefix):]
                break
        if key in top:
            params[top[key]][...] = tensor.astype(dt)
            n_loaded += 1
            continue
        parts = key.split(".")
        if len(parts) > 3 and parts[0] == "encoder" and \
                parts[1] == "layer":
            li = int(parts[2])
            rest = ".".join(parts[3:])
            if rest in per_layer:
                tgt, transpose = per_layer[rest]
                t = tensor.astype(dt)
                layers[tgt][li] = t.T if transpose else t
                n_loaded += 1
    if n_loaded == 0:
        raise RuntimeError("no encoder tensors read")
    return params


def main() -> int:
    from .checkpoint import save_params
    from ..providers.tpu import MODEL_CONFIGS

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("hf_dir")
    ap.add_argument("out_dir")
    ap.add_argument("--model", default="qwen3-coder-30b",
                    choices=sorted(MODEL_CONFIGS) + ["embedder"])
    args = ap.parse_args()

    if args.model == "embedder":
        params = convert_hf_encoder(args.hf_dir)
    else:
        cfg = MODEL_CONFIGS[args.model]()
        params = convert_hf_decoder(args.hf_dir, cfg, cfg.dtype)
    save_params(args.out_dir, params)
    total = sum(int(np.prod(v.shape)) for v in
                __import__("jax").tree.leaves(params))
    print(json.dumps({"model": args.model, "params": total,
                      "out": args.out_dir}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
