"""Central registry for every ``ROOM_TPU_*`` configuration knob.

This module is the ONLY sanctioned way to read a ``ROOM_TPU_*``
environment variable from library code — roomlint
(``python -m room_tpu.analysis``, rule ``knob-raw-env-read``) flags raw
``os.environ`` reads of the namespace anywhere else under ``room_tpu/``.
Registering here is what makes a knob exist: the registry carries the
name, type, default, one-line doc, and scope, and ``docs/knobs.md`` is
GENERATED from it (``python -m room_tpu.analysis --write-docs``), so a
knob can no longer ship undocumented or drift between a call site's
inline default and the docs.

Scopes
------
``library``
    Read by engine/serving/core library code. The registered ``default``
    applies when the env var is unset.
``provider``
    Same knob, but the production deployment path
    (``providers/tpu.ModelHost``) applies ``provider_default`` instead —
    the documented provider-on / library-off convention that
    ``ROOM_TPU_OFFLOAD``, ``ROOM_TPU_LIFECYCLE`` and (as of this
    registry) ``ROOM_TPU_SPEC_TOKENS`` share. Call sites on the
    deployment path pass ``scope="provider"`` to the typed getters.
``server`` / ``swarm`` / ``bench`` / ``test-seam``
    Documentation grouping only; resolution is identical to ``library``.

Typed getters re-read ``os.environ`` on every call (tests monkeypatch
env vars and re-construct engines), so nothing is cached here.

Boolean semantics are standardized: unset -> registered default; a set
value is false iff it strips/lowers to one of ``"", "0", "off",
"false", "no"``. The handful of legacy sites that compared ``== "1"``
or ``!= "0"`` now share this one rule.

Dynamic families (per-model / per-provider names such as
``ROOM_TPU_MESH_<SLUG>`` or ``ROOM_TPU_CLAUDE_CLI``) are registered as
patterns with ``{PLACEHOLDER}`` segments and resolved through
``get_dynamic`` — an f-string fed straight to ``os.environ`` is a lint
violation, a registered pattern is not.
"""

from __future__ import annotations

import os
import re
from dataclasses import dataclass
from typing import Optional

__all__ = [
    "Knob", "REGISTRY", "DYNAMIC", "register", "register_dynamic",
    "get_raw", "get_str", "get_int", "get_float", "get_bool",
    "is_set", "get_dynamic", "all_knobs", "resolve_default",
    "FALSEY",
]

# standardized false spellings for bool knobs (after strip().lower())
FALSEY = ("", "0", "off", "false", "no")


@dataclass(frozen=True)
class Knob:
    """One registered configuration knob.

    ``default`` is the raw env-string default (``None`` = unset, the
    knob is optional); ``provider_default`` is the deployment-path
    default when the provider/library split applies (None = same as
    ``default``). ``type`` is documentation + getter intent: one of
    ``str``/``int``/``float``/``bool``/``path``/``list``/``secret``.
    """

    name: str
    type: str
    default: Optional[str]
    doc: str
    scope: str = "library"
    provider_default: Optional[str] = None
    choices: Optional[tuple] = None


REGISTRY: dict[str, Knob] = {}
# pattern -> Knob, e.g. "ROOM_TPU_MESH_{MODEL}"
DYNAMIC: dict[str, Knob] = {}

_TYPES = ("str", "int", "float", "bool", "path", "list", "secret")
_SCOPES = ("library", "provider", "server", "swarm", "bench",
           "test-seam")


def register(
    name: str,
    type: str,
    default: Optional[str],
    doc: str,
    scope: str = "library",
    provider_default: Optional[str] = None,
    choices: Optional[tuple] = None,
) -> Knob:
    if not name.startswith("ROOM_TPU_"):
        raise ValueError(f"knob {name!r} outside the ROOM_TPU_ namespace")
    if type not in _TYPES:
        raise ValueError(f"knob {name!r}: unknown type {type!r}")
    if scope not in _SCOPES:
        raise ValueError(f"knob {name!r}: unknown scope {scope!r}")
    if name in REGISTRY:
        raise ValueError(f"knob {name!r} registered twice")
    if not doc.strip():
        raise ValueError(f"knob {name!r} registered without a doc line")
    knob = Knob(name, type, default, doc, scope, provider_default, choices)
    REGISTRY[name] = knob
    return knob


def register_dynamic(
    pattern: str,
    type: str,
    default: Optional[str],
    doc: str,
    scope: str = "library",
) -> Knob:
    """Register a family of knobs whose concrete names carry a runtime
    part, e.g. ``ROOM_TPU_MESH_{MODEL}``."""
    if "{" not in pattern:
        raise ValueError(f"dynamic knob {pattern!r} has no placeholder")
    if pattern in DYNAMIC:
        raise ValueError(f"dynamic knob {pattern!r} registered twice")
    knob = Knob(pattern, type, default, doc, scope)
    DYNAMIC[pattern] = knob
    return knob


class _Unset:
    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return "<registered default>"


_UNSET = _Unset()


def _lookup(name: str) -> Knob:
    knob = REGISTRY.get(name)
    if knob is None:
        raise KeyError(
            f"unregistered knob {name!r}: add it to "
            "room_tpu/utils/knobs.py (roomlint rule knob-unregistered)"
        )
    return knob


def resolve_default(name: str, scope: Optional[str] = None) -> Optional[str]:
    """Registered default for a knob under the given scope: the
    provider scope prefers ``provider_default`` when declared."""
    knob = _lookup(name)
    if scope == "provider" and knob.provider_default is not None:
        return knob.provider_default
    return knob.default


def get_raw(
    name: str,
    default=_UNSET,
    scope: Optional[str] = None,
) -> Optional[str]:
    """The raw env string for a registered knob: the live env value,
    else the explicit call-site ``default`` (for the few contextual
    defaults, e.g. ``ROOM_TPU_BIND_HOST`` falling back to the
    configured host), else the registered (scope-resolved) default."""
    val = os.environ.get(name)
    if val is not None:
        return val
    if not isinstance(default, _Unset):
        _lookup(name)  # even explicit-default reads must be registered
        return default
    return resolve_default(name, scope)


def get_str(name: str, default=_UNSET, scope: Optional[str] = None
            ) -> Optional[str]:
    return get_raw(name, default, scope)


def get_int(name: str, default=_UNSET, scope: Optional[str] = None
            ) -> Optional[int]:
    raw = get_raw(name, default, scope)
    if raw is None or isinstance(raw, int):
        return raw
    return int(str(raw).strip())


def get_float(name: str, default=_UNSET, scope: Optional[str] = None
              ) -> Optional[float]:
    raw = get_raw(name, default, scope)
    if raw is None or isinstance(raw, float):
        return raw
    return float(str(raw).strip())


def get_bool(name: str, default=_UNSET, scope: Optional[str] = None
             ) -> bool:
    raw = get_raw(name, default, scope)
    if raw is None or isinstance(raw, bool):
        return bool(raw)
    return str(raw).strip().lower() not in FALSEY


def is_set(name: str) -> bool:
    """Whether the env var is explicitly present (registered knobs
    only) — for sites where an explicit empty/zero value means
    something different from unset (e.g. ``ROOM_TPU_JAX_CACHE=0``)."""
    _lookup(name)
    return name in os.environ


def get_dynamic(pattern: str, *parts: str, default: Optional[str] = None
                ) -> Optional[str]:
    """Resolve one member of a registered dynamic family:
    ``get_dynamic("ROOM_TPU_MESH_{MODEL}", "QWEN3_30B")`` reads
    ``ROOM_TPU_MESH_QWEN3_30B``. Placeholders are substituted left to
    right with ``parts``."""
    knob = DYNAMIC.get(pattern)
    if knob is None:
        raise KeyError(
            f"unregistered dynamic knob family {pattern!r}: add it to "
            "room_tpu/utils/knobs.py (roomlint rule knob-unregistered)"
        )
    name = pattern
    for part in parts:
        name = re.sub(r"\{[A-Za-z_]+\}", part, name, count=1)
    if "{" in name:
        raise ValueError(
            f"dynamic knob {pattern!r}: unresolved placeholder in {name!r}"
        )
    val = os.environ.get(name)
    if val is not None:
        return val
    if default is not None:
        return default
    return knob.default


def all_knobs() -> dict[str, Knob]:
    """Static + dynamic registry, for the doc generator and roomlint."""
    out = dict(REGISTRY)
    out.update(DYNAMIC)
    return out


# ---------------------------------------------------------------------------
# The registry. Grouped by subsystem; docs/knobs.md mirrors these groups.
# Keep doc lines to one sentence — the generator renders them verbatim.
# ---------------------------------------------------------------------------

# ---- serving engine: decode pipeline + prefill (docs/serving.md) ----
register("ROOM_TPU_DECODE_STEPS_PER_DISPATCH", "int", None,
         "Tokens decoded per device dispatch (multi-step pipeline window "
         "depth); unset -> 4, 1 = legacy step-at-a-time.")
register("ROOM_TPU_DECODE_CHUNK", "int", None,
         "Back-compat alias for ROOM_TPU_DECODE_STEPS_PER_DISPATCH "
         "(honored when the primary is unset).")
register("ROOM_TPU_PREFILL_CHUNK", "int", "2048",
         "Compile-width cap for chunked prefill in tokens (0 disables "
         "chunking).")
register("ROOM_TPU_PREFILL_CHUNK_PAGES", "int", "16",
         "Width in KV pages of one interleaved scheduler prefill chunk "
         "(0 = monolithic admission-time prefill).")
register("ROOM_TPU_PREFIX_CACHE_PAGES", "int", "2",
         "Minimum shared-prefix length in pages before a prefix-cache "
         "entry is published.")
register("ROOM_TPU_GREEDY_TIE_EPS", "float", "1e-6",
         "Logit-tie epsilon for greedy sampling (argmax determinism "
         "guard across backends).")

# ---- speculative decoding (docs/serving.md, ROUND5.md) ----
register("ROOM_TPU_SPEC_TOKENS", "int", "0",
         "Max draft tokens per in-window speculative step (gamma "
         "ceiling; per-class gamma adapts below it); 0 disables "
         "speculation.",
         scope="provider", provider_default="4")
register("ROOM_TPU_SPEC_EMA", "float", "0.1",
         "EMA alpha for per-class speculative acceptance tracking "
         "(scheduler.SpecTuner).")
register("ROOM_TPU_SPEC_COOLDOWN", "int", "16",
         "Emitted tokens a class decodes plainly after its acceptance "
         "EMA falls below the floor, before a gamma-1 probe round.")
register("ROOM_TPU_SPEC_MIN_ACCEPT", "float", None,
         "Explicit per-class acceptance-EMA floor below which a class "
         "goes spec-off (unset = roofline spec_accept_floor for this "
         "model/batch/gamma shape).")
register("ROOM_TPU_SPEC_TUNE_EVERY", "int", "16",
         "Draft proposals a class accumulates between gamma "
         "adjustments (scheduler.SpecTuner).")
register("ROOM_TPU_SPEC_TAIL", "int", "256",
         "Device-resident recent-token tail per decode lane that "
         "on-mesh prompt-lookup drafting matches against (ops/spec.py). "
         "The replay study (scripts/spec_acceptance.py) picks 256: "
         "tool-call acceptance falls ~30% at 128 and the matching "
         "cost is negligible next to the verify forward.")
register("ROOM_TPU_DRAFT_MODEL", "str", None,
         "Tier-2 draft model config name (models/config.py "
         "resolve_draft_config) loaded onto the serving mesh alongside "
         "the target; unset = prompt-lookup drafting only.",
         scope="provider")
register("ROOM_TPU_DRAFT_WINDOW", "int", "64",
         "Trailing tail tokens the on-mesh draft model reads per "
         "proposal step.")

# ---- serving engine: robustness / chaos (docs/chaos.md) ----
register("ROOM_TPU_TURN_DEADLINE_S", "float", "0",
         "Default per-turn deadline in seconds (0 disables; submit() "
         "can set a per-request deadline on top).")
register("ROOM_TPU_STEP_STALL_S", "float", "120",
         "Decode-round duration that counts as a stall and triggers "
         "park+requeue of its sessions.")
register("ROOM_TPU_MAX_REQUEUES", "int", "3",
         "Stall-watchdog park+requeue budget per turn before it rides "
         "out the slowness.")
register("ROOM_TPU_FAULT_RETRIES", "int", "3",
         "Bounded retries for transient faults at device-call sites.")
register("ROOM_TPU_RETRY_BACKOFF_S", "float", "0.05",
         "Initial exponential-backoff delay for transient-fault "
         "retries.")
register("ROOM_TPU_DEGRADE_WINDOW_S", "float", "30",
         "Sliding window over pressure events that drives the "
         "degradation ladder.")
register("ROOM_TPU_DEGRADE_THRESHOLDS", "list", "2,4,6,12",
         "Four comma-separated pressure-event counts mapping to ladder "
         "rungs 1-4.")
register("ROOM_TPU_ENGINE_MAX_RESTARTS", "int", "3",
         "Engine-thread crash restarts inside the window before the "
         "engine is marked unhealthy (fail-closed).")
register("ROOM_TPU_FAULTS", "str", "",
         "Chaos fault-arming spec, ';'-separated "
         "name[:k=v,...] entries (docs/chaos.md).",
         scope="test-seam")

# ---- tiered KV offload (docs/kv_offload.md) ----
register("ROOM_TPU_OFFLOAD", "bool", "0",
         "Enable tiered KV offload (host RAM + disk spool) for cold "
         "sessions.",
         scope="provider", provider_default="1")
register("ROOM_TPU_OFFLOAD_HOST_MB", "float", "512",
         "Host-RAM tier cap in MB for offloaded KV pages.")
register("ROOM_TPU_OFFLOAD_DISK_MB", "float", "2048",
         "Disk-spool tier cap in MB (0 disables the disk tier).")
register("ROOM_TPU_OFFLOAD_DIR", "path", None,
         "KV spool directory (default: a per-process temp dir).")
register("ROOM_TPU_OFFLOAD_LOW_WM", "float", "0.25",
         "Free-page fraction below which the offload sweep starts "
         "hibernating cold sessions.")
register("ROOM_TPU_OFFLOAD_HIGH_WM", "float", "0.5",
         "Free-page fraction at which the sweep stops / restores.")
register("ROOM_TPU_OFFLOAD_ON_PARK", "bool", "1",
         "Offload a session's pages immediately on tool-call park.")
register("ROOM_TPU_OFFLOAD_PREFETCH", "int", "2",
         "Queued-session restores started per scheduler step "
         "(prefetch-on-queue).")

# ---- process lifecycle (docs/lifecycle.md) ----
register("ROOM_TPU_LIFECYCLE", "bool", "0",
         "Enable graceful drain to a manifest on SIGTERM and warm "
         "restore on boot.",
         scope="provider", provider_default="1")
register("ROOM_TPU_LIFECYCLE_DIR", "path", None,
         "Durable lifecycle state root (default: a stable dir under "
         "the system temp dir).")
register("ROOM_TPU_DRAIN_DEADLINE_S", "float", "30",
         "Budget in seconds for the whole graceful-drain path.")
register("ROOM_TPU_SPOOL_SWEEP_AGE_S", "float", "3600",
         "Orphan spool files older than this are swept at store "
         "construction.")

# ---- engine replica fleet (docs/fleet.md) ----
register("ROOM_TPU_FLEET_REPLICAS", "int", "1",
         "Engine replicas per served model (1 = no fleet); a fleet "
         "fails replicas over to siblings and drains blue/green.",
         scope="provider")
register("ROOM_TPU_FLEET_MESHES", "str", None,
         "Per-replica mesh specs, ';'-separated 'dp,pp,tp[@start]' "
         "entries (replica i takes entry i, wrapping); unset falls "
         "back to the model's single-engine mesh.",
         scope="provider")
register("ROOM_TPU_FLEET_STRIKES", "int", "3",
         "Replica death strikes before the fleet supervisor stops "
         "rebuilding it.")
register("ROOM_TPU_FLEET_TICK_S", "float", "0.5",
         "Fleet supervision poll interval in seconds.")
register("ROOM_TPU_FLEET_REBUILD", "bool", "1",
         "Auto-rebuild crashed replicas (within the strike budget); "
         "0 leaves them dead for operator-driven re-admission.")
register("ROOM_TPU_FLEET_MIRROR_TOKENS", "int", "2000000",
         "Fleet-wide cap on router history-mirror tokens; past it the "
         "least-recently-used sessions' mirrors are dropped (warm-only "
         "failover for those sessions; 0 = unbounded).")

# ---- disaggregated prefill/decode serving (docs/disagg.md) ----
register("ROOM_TPU_FLEET_ROLES", "str", None,
         "Per-replica roles, ','/';'-separated prefill|decode|mixed "
         "entries (replica i takes entry i; missing entries default "
         "mixed). Any non-mixed entry enables disaggregated routing.",
         scope="provider")
register("ROOM_TPU_DISAGG_PREFILL_TOKENS", "int", "512",
         "Fresh-session prompt length (tokens) at or past which the "
         "router places the session on a prefill-role replica.")
register("ROOM_TPU_DISAGG_WIRE", "str", "0",
         "KV shipment transport: '0' ships via the in-process "
         "detached-spool adopt; 'loopback' ships spool bytes as "
         "length-prefixed checksummed frames over a local socket "
         "(the cross-host seam, exercised by tests/bench).",
         choices=("0", "loopback"))
register("ROOM_TPU_KV_WIRE_PORT", "int", "0",
         "Listen port for the KV wire receiver (0 = ephemeral).")
register("ROOM_TPU_KV_WIRE_TIMEOUT_S", "float", "10",
         "Per-shipment socket timeout for the KV wire, seconds.")

# ---- pod fault tolerance (docs/podnet.md) ----
register("ROOM_TPU_WIRE_RETRIES", "int", "3",
         "Total connection attempts for one KV-wire send or control "
         "frame (1 = no retry); exhaustion degrades to the router-"
         "mirror re-prefill contract, never a misroute.")
register("ROOM_TPU_WIRE_BACKOFF_S", "float", "0.05",
         "Base wire retry backoff, seconds; attempt n sleeps "
         "base*2^n with +/-50% jitter so a healing pod is not "
         "thundering-herded.")
register("ROOM_TPU_WIRE_BACKOFF_MAX_S", "float", "2.0",
         "Upper bound on one jittered wire retry backoff sleep.")
register("ROOM_TPU_WIRE_BREAKER_FAILS", "int", "5",
         "Consecutive wire failures to one peer that open its "
         "circuit breaker (0 disables the breaker).")
register("ROOM_TPU_WIRE_BREAKER_COOLDOWN_S", "float", "5.0",
         "Open-breaker cooldown before a half-open probe is allowed "
         "through to the peer.")
register("ROOM_TPU_POD_MEMBERSHIP", "bool", "0",
         "Enable the pod membership service: replicas/hosts heartbeat "
         "and a deadline-with-suspicion detector re-homes a dead "
         "member's sessions after its lease expires "
         "(docs/podnet.md).")
register("ROOM_TPU_POD_HEARTBEAT_S", "float", "1.0",
         "Pod heartbeat send interval, seconds.")
register("ROOM_TPU_POD_SUSPECT_S", "float", "3.0",
         "Silence after which a pod member is SUSPECT (routing "
         "unchanged; re-home not yet armed).")
register("ROOM_TPU_POD_DEAD_S", "float", "6.0",
         "Silence after which a suspect pod member is DEAD; its "
         "session lease starts expiring.")
register("ROOM_TPU_POD_LEASE_S", "float", "2.0",
         "Session-ownership lease beyond the DEAD declaration; only "
         "past it are the member's sessions re-homed (a lagging but "
         "alive host gets this long to reappear before fencing).")
register("ROOM_TPU_POD_MIRROR", "bool", "0",
         "Crash-durable router mirror: journal session placements + "
         "streamed tokens to a checksummed sidecar so a router "
         "restart rebuilds its mirror instead of orphaning in-flight "
         "rooms (docs/podnet.md).")
register("ROOM_TPU_POD_MIRROR_BATCH", "int", "1",
         "Token-append batching for the mirror journal: tokens "
         "buffered in memory before one journal line is written. 1 "
         "journals every durably-streamed token (token-identical "
         "resume after a router crash); larger values trade a "
         "bounded resume-warmth window for fewer writes.")
register("ROOM_TPU_POD_MIRROR_COMPACT", "int", "4096",
         "Journal lines past which the supervise tick compacts the "
         "mirror journal into a fresh checksummed snapshot.")

# ---- sharded router tier (docs/podnet.md) ----
register("ROOM_TPU_ROUTER_SHARDS", "int", "1",
         "Router shards per fleet: session records, fences, and the "
         "mirror journal partition by room-id hash across N "
         "independent router slices fronted by the epoch-versioned "
         "placement map (1 = the classic single router). N>1 "
         "journals each shard regardless of ROOM_TPU_POD_MIRROR — "
         "shard failover is journal adoption.",
         scope="provider")
register("ROOM_TPU_ROUTER_LEASE_S", "float", "2.0",
         "Router-shard ownership lease: a dead router shard's rooms "
         "shed (retryable) this long before a surviving sibling "
         "adopts its mirror journal, mints fences +1, and publishes "
         "a new placement epoch.")
register("ROOM_TPU_POD_PEERS", "str", None,
         "','-separated host:port control-wire addresses of peer pod "
         "members; placement-map epochs publish to each over "
         "wire_send_control frames (empty = single-process pod).")
register("ROOM_TPU_ROUTER_SHARD_HEARTBEATS", "bool", "0",
         "Drive router-shard failover from per-shard heartbeats into "
         "a PodMembership deadline-with-suspicion detector instead of "
         "the in-process died_at timer — the detector works unchanged "
         "when shard beats arrive over the control wire from separate "
         "processes (docs/podnet.md).")

# ---- swarm shards (docs/swarmshard.md) ----
register("ROOM_TPU_SWARM_SHARDS", "int", "1",
         "Swarm-runtime shards: rooms partition by room-id hash "
         "across N shard SQLite files, each with its own agent-loop "
         "supervision domain and event-bus segment, fronted by the "
         "epoch-versioned placement map (1 = the classic singleton "
         "database).", scope="swarm")
register("ROOM_TPU_SWARM_LEASE_S", "float", "2.0",
         "Swarm-shard ownership lease: a dead shard's rooms shed "
         "(retryable) this long before a surviving sibling reopens "
         "its database file, runs journal recovery over it, and "
         "publishes a new placement epoch.", scope="swarm")
register("ROOM_TPU_SWARM_DB_DIR", "path", None,
         "Directory for swarm-shard database files (shard<k>.db); "
         "default derives shard files next to the classic "
         "ROOM_TPU_DB_PATH / ROOM_TPU_DATA_DIR database.",
         scope="swarm")
register("ROOM_TPU_DB_LOCK_STATS", "bool", "0",
         "Track per-Database lock contention (waits + waited seconds) "
         "— the swarm_storm bench's journal-write-contention probe; "
         "off in production (two clock reads per contended "
         "statement).", scope="bench")

# ---- multi-process swarm shards (docs/swarmshard.md "Process mode") ----
register("ROOM_TPU_SWARM_PROC", "bool", "0",
         "Run each swarm shard as a supervised child OS process (own "
         "interpreter, SQLite handle, agent-loop domain) speaking to "
         "the parent over framed-RTKW control frames — a segfault or "
         "OOM-kill in one shard's rooms no longer takes the whole "
         "swarm down. Requires ROOM_TPU_SWARM_SHARDS > 1.",
         scope="swarm")
register("ROOM_TPU_SWARM_PROC_RESTARTS", "int", "3",
         "Restart budget per shard child process within the sliding "
         "ROOM_TPU_SWARM_PROC_WINDOW_S window; past it the shard "
         "degrades to sibling adoption and is reported unhealthy in "
         "/api/tpu/health.", scope="swarm")
register("ROOM_TPU_SWARM_PROC_WINDOW_S", "float", "60.0",
         "Sliding window for the shard-child restart budget.",
         scope="swarm")
register("ROOM_TPU_SWARM_PROC_HB_S", "float", "0.5",
         "Shard-child heartbeat + stats-frame interval over the "
         "control wire into the parent's alive→suspect→dead "
         "detector (PodMembership thresholds apply).", scope="swarm")
register("ROOM_TPU_SWARM_PROC_DRAIN_S", "float", "3.0",
         "Graceful SIGTERM drain deadline for a shard child: past it "
         "the supervisor escalates to SIGKILL (the forced-kill "
         "sweep), on shutdown and on restart alike.", scope="swarm")
register("ROOM_TPU_SWARM_PROC_BACKOFF_S", "float", "0.25",
         "Base for the jittered exponential backoff between shard-"
         "child restarts (doubled per consecutive restart).",
         scope="swarm")
register("ROOM_TPU_SWARM_PROC_HOST", "str", "127.0.0.1",
         "Bind host for the parent supervisor's control-wire "
         "listener. Keep loopback when children are local "
         "subprocesses; bind 0.0.0.0 when shard children run as "
         "separate containers (ROOM_TPU_SWARM_PROC_EXTERNAL=1).",
         scope="swarm")
register("ROOM_TPU_SWARM_PROC_PORT", "int", "0",
         "Bind port for the parent supervisor's control-wire "
         "listener (0 = ephemeral). Pin it when externally-launched "
         "shard children must be pointed at the parent via "
         "--parent host:port.", scope="swarm")
register("ROOM_TPU_SWARM_PROC_EXTERNAL", "bool", "0",
         "Shard children are launched OUTSIDE the parent (one "
         "container per shard via `python -m room_tpu.swarm."
         "procshard`). The supervisor still runs the heartbeat "
         "ladder, dispatch plane, budget accounting, and sibling "
         "adoption, but never signals or spawns processes — PIDs "
         "live in foreign namespaces; restarts belong to the "
         "container runtime. Adoption requires the shard SQLite "
         "files on a volume shared across shard containers.",
         scope="swarm")
register("ROOM_TPU_SWARM_PROC_IGNORE_TERM", "bool", "0",
         "Test seam: the shard child ignores SIGTERM, forcing the "
         "supervisor's drain deadline to escalate to SIGKILL — the "
         "forced-kill regression path.", scope="test-seam")

# ---- fleet-global shared prefix store (docs/disagg.md) ----
register("ROOM_TPU_PREFIX_STORE", "bool", "0",
         "Content-addressed shared prefix KV store: replicas/hosts "
         "publish page-aligned prompt-prefix KV and pull it instead "
         "of re-prefilling (library default off; deployment on).",
         scope="provider", provider_default="1")
register("ROOM_TPU_PREFIX_STORE_DIR", "path", None,
         "Shared prefix-store directory (default "
         "<lifecycle root>/prefix_store; point it at a shared volume "
         "for cross-host sharing).")
register("ROOM_TPU_PREFIX_STORE_MB", "float", "512",
         "Prefix-store byte cap; past it the least-recently-used "
         "entries are evicted.")
register("ROOM_TPU_PREFIX_STORE_PUBLISH", "bool", "1",
         "Publish locally computed prefix-cache entries to the "
         "shared store (0 = pull-only).")

# ---- SLO scheduler (docs/scheduler.md) ----
register("ROOM_TPU_CLASS_TARGETS", "str", "",
         "Per-class SLO targets, ';'-separated "
         "class=ttft_s:tpot_ms entries.")
register("ROOM_TPU_CLASS_CHUNKS", "str", "",
         "Per-class interleaved-prefill chunk budgets per decode "
         "window, ';'-separated class=n entries.")

# ---- kernels / quantization (docs/ARCHITECTURE.md) ----
register("ROOM_TPU_KV_QUANT", "str", "",
         "KV-cache quantization mode: 'int8' stores pages int8 with "
         "f32 scales, empty keeps bf16.",
         choices=("", "int8"))
register("ROOM_TPU_QUANT", "str", None,
         "Weight quantization mode ('int8' serves int8 weight-only).",
         choices=("int8",))
register("ROOM_TPU_PAGED_KERNEL", "str", "auto",
         "Paged attention backend: pallas | ragged | xla | auto "
         "(pallas/ragged on TPU; ragged additionally forces the "
         "unified ragged kernel for fused windows).",
         choices=("pallas", "ragged", "xla", "auto"))
register("ROOM_TPU_RAGGED_KERNEL", "str", "auto",
         "Unified ragged [prefill-chunks + decode-lanes] kernel gate: "
         "on | off | auto (one-shot compile+numerics probe).",
         choices=("on", "off", "auto"))
register("ROOM_TPU_RAGGED_INT8_KERNEL", "str", "auto",
         "int8-KV unified ragged kernel gate: on | off | auto "
         "(probe).",
         choices=("on", "off", "auto"))
register("ROOM_TPU_RAGGED_QBLOCK", "int", "8",
         "Query-block rows of the unified ragged kernel (ragged rows "
         "pad to this granularity, never to the batch max).")
register("ROOM_TPU_FUSED_WINDOW", "bool", "1",
         "Fuse the scheduler window's interleaved prefill chunks into "
         "the decode dispatch (one device round trip per window); 0 "
         "keeps the split per-chunk dispatches.")
register("ROOM_TPU_FUSED_WINDOW_DP", "bool", "1",
         "dp-sharded fused spec-window: keep the fused dispatch "
         "window (and in-window speculation) on when the decode batch "
         "shards over the dp mesh axis — the ragged token stream "
         "becomes per-dp-shard sub-batches ([ndp, T_local], "
         "shard-major chunk rows). 0 restores the legacy dp auto-off "
         "(split per-chunk dispatches under dp).")
register("ROOM_TPU_PREFILL_KERNEL", "str", "auto",
         "S>1 Pallas prefill kernel gate: on | off | auto (one-shot "
         "compile+numerics probe).",
         choices=("on", "off", "auto"))
register("ROOM_TPU_PAGED_INT8_KERNEL", "str", "auto",
         "int8-KV decode kernel gate: on | off | auto (probe).",
         choices=("on", "off", "auto"))
register("ROOM_TPU_PREFILL_INT8_KERNEL", "str", "auto",
         "int8-KV S>1 prefill kernel gate: on | off | auto (probe).",
         choices=("on", "off", "auto"))
register("ROOM_TPU_MOE_IMPL", "str", None,
         "MoE dispatch implementation: ragged | gshard | shardmap.",
         choices=("ragged", "gshard", "shardmap"))

# ---- provider deployment path (providers/tpu.py) ----
register("ROOM_TPU_CKPT_DIR", "path", None,
         "Checkpoint root; each served model loads from "
         "<dir>/<model-name>.",
         scope="provider")
register("ROOM_TPU_ALLOW_RANDOM_INIT", "bool", "0",
         "Allow synthetic (random-init) weights when no checkpoint "
         "exists — dev/test only.",
         scope="provider")
register("ROOM_TPU_MAX_BATCH", "int", "8",
         "Decode batch rows for deployment-path engines.",
         scope="provider")
register("ROOM_TPU_PAGE_SIZE", "int", "16",
         "KV page size in tokens for deployment-path engines.",
         scope="provider")
register("ROOM_TPU_N_PAGES", "int", "2048",
         "KV page-pool size for deployment-path engines.",
         scope="provider")
register("ROOM_TPU_MESH", "str", None,
         "Global device-mesh spec 'dp,pp,tp[@start]' for served "
         "models.",
         scope="provider")
register_dynamic("ROOM_TPU_MESH_{MODEL}", "str", None,
                 "Per-model mesh override (slug = model name "
                 "uppercased, non-alnum -> '_'); wins over "
                 "ROOM_TPU_MESH.",
                 scope="provider")
register_dynamic("ROOM_TPU_QUANT_{MODEL}", "str", None,
                 "Per-model weight-quantization override; wins over "
                 "ROOM_TPU_QUANT.",
                 scope="provider")
register("ROOM_TPU_TOKENIZER_PATH", "path", None,
         "HF tokenizer directory (unset = hermetic byte-level "
         "tokenizer).",
         scope="provider")
register("ROOM_TPU_EMBED_CKPT", "path", None,
         "Embedder checkpoint; unset serves the hash-projection "
         "fallback embedder.",
         scope="provider")
register("ROOM_TPU_FALLBACK_MODELS", "list", "",
         "Comma-separated fallback provider chain for unhealthy tpu: "
         "engines.",
         scope="provider")
register("ROOM_TPU_FALLBACK_ON_CRASH", "bool", "0",
         "Also reroute crash-failed results (within the restart "
         "budget) through the fallback chain.",
         scope="provider")
register_dynamic("ROOM_TPU_{PROVIDER}_CLI", "path", None,
                 "CLI binary override per CLI provider (e.g. "
                 "ROOM_TPU_CLAUDE_CLI); the test seam for subprocess "
                 "providers.",
                 scope="test-seam")
register_dynamic("ROOM_TPU_{KIND}_BASE", "str", None,
                 "API base-URL override per HTTP provider (e.g. "
                 "ROOM_TPU_OPENAI_BASE, ROOM_TPU_ANTHROPIC_BASE).",
                 scope="server")

# ---- multihost / parallel (parallel/multihost.py) ----
register("ROOM_TPU_COORDINATOR", "str", None,
         "host:port of process 0 for multihost jax.distributed "
         "initialization.")
register("ROOM_TPU_NUM_PROCESSES", "int", None,
         "Multihost world size.")
register("ROOM_TPU_PROCESS_ID", "int", None,
         "This process's multihost rank.")
register("ROOM_TPU_DCN_TIMEOUT_S", "float", None,
         "Coordinator barrier timeout for multihost startup.")

# ---- utils: caches, profiling ----
register("ROOM_TPU_JAX_CACHE", "path", None,
         "Persistent XLA compile-cache dir (default "
         "/tmp/room_tpu_jax_cache; '0'/'off' disables).")
register("ROOM_TPU_PROFILE_SLOW_MS", "float", "500",
         "HTTP endpoint latency above this is logged as slow.")
register("ROOM_TPU_PROFILE_HTTP", "bool", "0",
         "Enable per-endpoint HTTP latency profiling.")
register("ROOM_TPU_TRACE_DIR", "path", None,
         "jax.profiler device-trace output dir (default: "
         "<data dir>/traces; used by POST /api/tpu/profile).")
register("ROOM_TPU_PROFILE_MAX_S", "float", "120",
         "Upper bound on an on-demand jax.profiler device-trace "
         "capture requested via POST /api/tpu/profile.")

# ---- lockmap runtime witness (docs/static_analysis.md) ----
register("ROOM_TPU_LOCKDEP", "bool", "0",
         "Arm the lockdep runtime lock-order witness: registered "
         "locks (room_tpu/utils/locks.py) record per-thread "
         "acquisition order and hold times; the chaos/fleet/disagg "
         "CI quick tiers run armed.")
register("ROOM_TPU_LOCKDEP_STRICT", "bool", "1",
         "With lockdep armed, raise LockOrderError on an observed "
         "lock-order inversion (tests/CI); '0' counts inversions "
         "(lockdep_inversions) and records evidence instead "
         "(production posture).")

# ---- chaosfuzz: system-invariant witness + schedule fuzzer ----
register("ROOM_TPU_INVARIANTS", "bool", "0",
         "Arm the runtime system-invariant witness "
         "(docs/chaosfuzz.md): KV-page conservation, fence "
         "monotonicity, single session ownership, exactly-once "
         "xshard effects and friends, probed at the engine-step / "
         "fleet-supervise / swarm-sweep seams.")
register("ROOM_TPU_INVARIANTS_STRICT", "bool", "1",
         "With the invariant witness armed, raise "
         "InvariantViolation at the probing seam (tests/CI); '0' "
         "counts violations into stats/health/metrics with bounded "
         "evidence instead (production posture).")
register("ROOM_TPU_INVARIANTS_EVERY", "int", "1",
         "Engine-step probe cadence for the invariant witness: "
         "check every Nth step (fleet/swarm probes always run each "
         "supervise tick).")
register("ROOM_TPU_CHAOSFUZZ_TICKS", "int", "24",
         "Default schedule length (ticks) for python -m "
         "room_tpu.chaos generated fault schedules.", scope="bench")
register("ROOM_TPU_CHAOSFUZZ_PLANT", "str", None,
         "Test-only planted bug for the chaosfuzz self-test: "
         "'kv_leak' steals a KV page after the first offload_io "
         "firing, 'double_effect' double-commits an xshard journal "
         "row after the first db_io firing. The fuzzer must detect "
         "and shrink both. Never set outside tests.",
         scope="test-seam", choices=("kv_leak", "double_effect"))

# ---- turnscope: turn tracing / flight recorder / metrics ----
register("ROOM_TPU_TRACE", "bool", "1",
         "Always-on host-side turn tracing (docs/observability.md): "
         "per-turn span trees, the flight recorder, and per-class SLO "
         "attribution. 0 disables (begin() returns None; every "
         "engine hook no-ops).")
register("ROOM_TPU_TRACE_RING", "int", "256",
         "Flight-recorder ring: recently completed turn traces "
         "retained for /api/tpu/trace.")
register("ROOM_TPU_TRACE_VIOLATION_RING", "int", "256",
         "Flight-recorder evidence ring: SLO-violating / faulted / "
         "shed turn traces, retained separately so healthy traffic "
         "bursts never evict them.")
register("ROOM_TPU_TRACE_EVENTS", "int", "128",
         "Per-turn span-event cap (events past it are dropped; span "
         "accumulators keep counting).")
register("ROOM_TPU_METRICS", "bool", "1",
         "Serve the Prometheus text exposition at GET /metrics "
         "(unauthenticated, for scrapers on a private network; 0 "
         "disables the endpoint).", scope="server")

# ---- server / HTTP / cloud ----
register("ROOM_TPU_DATA_DIR", "path", "~/.room_tpu",
         "Data root for DB, auth tokens, prompts and app state.",
         scope="server")
register("ROOM_TPU_DB_PATH", "path", None,
         "Explicit SQLite database path; wins over ROOM_TPU_DATA_DIR.",
         scope="server")
register("ROOM_TPU_BIND_HOST", "str", None,
         "Overrides the HTTP server bind address.", scope="server")
register("ROOM_TPU_STATIC_DIR", "path", None,
         "Dashboard static-asset dir override.", scope="server")
register("ROOM_TPU_DEPLOYMENT_MODE", "str", None,
         "'cloud' enables cloud deployment mode.", scope="server")
register("ROOM_TPU_MCP_AUTOREGISTER", "bool", "1",
         "Autoregister the MCP server into detected agent CLI "
         "configs at startup.", scope="server")
register("ROOM_TPU_V1_TIMEOUT_S", "float", "600",
         "Per-request execution timeout for /v1/chat/completions.",
         scope="server")
register("ROOM_TPU_ALLOWED_ORIGINS", "list", "",
         "Extra comma-separated allowed CORS origins.", scope="server")
register("ROOM_TPU_CLOUD_JWT_SECRET", "secret", None,
         "HS256 secret for cloud invite JWTs (unset disables "
         "invites).", scope="server")
register("ROOM_TPU_INSTANCE_ID", "str", None,
         "Instance identifier embedded in cloud invite tokens.",
         scope="server")
register("ROOM_TPU_CLOUD_API", "str", None,
         "Cloud relay API base URL.", scope="server")
register("ROOM_TPU_SECRET_KEY", "secret", None,
         "Seed for the at-rest secret-encryption key.", scope="server")
register("ROOM_TPU_TELEMETRY_TOKEN", "secret", None,
         "Bearer token for heartbeat telemetry (unset disables "
         "telemetry).", scope="server")
register("ROOM_TPU_TELEMETRY_URL", "str", None,
         "Heartbeat telemetry endpoint URL.", scope="server")
register("ROOM_TPU_UPDATE_SOURCE_URL", "str", None,
         "Self-update metadata endpoint.", scope="server")
register("ROOM_TPU_UPDATE_SOURCE_TOKEN", "secret", None,
         "Bearer token for the self-update endpoint.", scope="server")
register("ROOM_TPU_UPDATE_GITHUB_REPO", "str", None,
         "GitHub repo ('owner/name') for release-API self-updates.",
         scope="server")
register("ROOM_TPU_EMAIL_OUTBOX", "path", None,
         "File-outbox dir for the email transport (also the test "
         "seam).", scope="test-seam")
register("ROOM_TPU_SMTP_HOST", "str", None,
         "SMTP relay host for outbound email.", scope="server")
register("ROOM_TPU_SMTP_PORT", "int", "587",
         "SMTP relay port.", scope="server")
register("ROOM_TPU_SMTP_USER", "str", None,
         "SMTP auth user (unset = unauthenticated relay).",
         scope="server")
register("ROOM_TPU_SMTP_PASS", "secret", "",
         "SMTP auth password.", scope="server")
register("ROOM_TPU_SMTP_FROM", "str", "clerk@room-tpu.local",
         "From address for outbound email.", scope="server")
register("ROOM_TPU_TELEGRAM_BOT", "str", "",
         "Telegram bot username for contact deep links.",
         scope="server")
register("ROOM_TPU_NPM", "path", None,
         "npm binary override for provider-auth installs.",
         scope="server")
register("ROOM_TPU_PROVIDER_AUTH_MAX_LINES", "int", "300",
         "Captured output lines kept per provider-auth session.",
         scope="server")
register("ROOM_TPU_PROVIDER_AUTH_TIMEOUT_S", "float", "900",
         "Idle timeout for an interactive provider-auth session.",
         scope="server")
register("ROOM_TPU_PROVIDER_AUTH_TTL_S", "float", "7200",
         "Hard lifetime cap for a provider-auth session.",
         scope="server")
register_dynamic("ROOM_TPU_RPC_{CHAIN}", "str", None,
                 "JSON-RPC endpoint override per wallet chain (e.g. "
                 "ROOM_TPU_RPC_BASE).",
                 scope="server")

# ---- swarm runtime (docs/swarm_recovery.md) ----
register("ROOM_TPU_LOOP_MAX_RESTARTS", "int", "3",
         "Loop-thread restarts inside the window before a worker is "
         "marked unhealthy.", scope="swarm")
register("ROOM_TPU_LOOP_RESTART_WINDOW_S", "float", "300",
         "Sliding window for the loop restart budget.", scope="swarm")
register("ROOM_TPU_LOOP_HANG_S", "float", "1800",
         "Heartbeat age after which a running agent loop counts as "
         "hung.", scope="swarm")
register("ROOM_TPU_REPLAY_WINDOW_S", "float", "21600",
         "How long a recovery-flagged journal effect stays skippable.",
         scope="swarm")
register("ROOM_TPU_JOURNAL_PRUNE_H", "float", "72",
         "Terminal journal rows older than this many hours are "
         "pruned.", scope="swarm")

# ---- bench / tuning harness (bench.py, scripts/) ----
register("ROOM_TPU_BENCH_TINY", "bool", "0",
         "CPU smoke profile for the bench harness.", scope="bench")
register("ROOM_TPU_BENCH_CPU_PROXY", "bool", "0",
         "CPU-proxy bench tier: tiny model on the virtual mesh with "
         "real relative deltas.", scope="bench")
register("ROOM_TPU_BENCH_WATCHDOG_S", "float", "1500",
         "Bench phase watchdog budget.", scope="bench")
register("ROOM_TPU_BENCH_PHASES", "path", None,
         "Per-phase JSONL output path (default ./BENCH_PHASES.jsonl).",
         scope="bench")
register("ROOM_TPU_BENCH_BATCH", "int", "32",
         "Decode batch rows for bench runs.", scope="bench")
register("ROOM_TPU_BENCH_GREEDY", "bool", "0",
         "Force greedy sampling in bench decode phases.",
         scope="bench")
register("ROOM_TPU_BENCH_CTX", "list", None,
         "Comma-separated prefill context lengths for the prefill "
         "phase.", scope="bench")
register("ROOM_TPU_BENCH_BG_CTX", "int", None,
         "Background prefill length for the scheduler stall A/B.",
         scope="bench")
register("ROOM_TPU_BENCH_CHUNK_PAGES", "int", None,
         "Chunk width for the scheduler bench phase.", scope="bench")
register("ROOM_TPU_BENCH_SPEC", "bool", "1",
         "Run the speculative-decode bench phase.", scope="bench")
register("ROOM_TPU_BENCH_PREFILL", "bool", "1",
         "Run the prefill bench phase.", scope="bench")
register("ROOM_TPU_BENCH_LATENCY", "bool", "1",
         "Run the latency bench phase.", scope="bench")
register("ROOM_TPU_BENCH_OFFLOAD", "bool", "1",
         "Run the KV-offload bench phase.", scope="bench")
register("ROOM_TPU_BENCH_RESTART", "bool", "1",
         "Run the warm-restart bench phase.", scope="bench")
register("ROOM_TPU_BENCH_PIPELINE", "bool", "1",
         "Run the decode-pipeline steps=1 vs steps=4 A/B phase.",
         scope="bench")
register("ROOM_TPU_BENCH_SCHED", "bool", "1",
         "Run the scheduler bench phase.", scope="bench")
register("ROOM_TPU_BENCH_KVQ", "bool", "1",
         "Run the int8-KV bench variant.", scope="bench")
register("ROOM_TPU_BENCH_RAGGED", "bool", "1",
         "Run the ragged_kernel split-vs-unified fused-window A/B "
         "phase.", scope="bench")
register("ROOM_TPU_BENCH_FLEET", "bool", "1",
         "Run the fleet_failover bench phase (TTFT after a replica "
         "kill, zero-token-loss check, sessions re-homed).",
         scope="bench")
register("ROOM_TPU_BENCH_DISAGG", "bool", "1",
         "Run the disagg bench phase (role-split fleet vs mixed "
         "baseline under a 2k-token prompt burst + prefix-store "
         "resume re-prefill delta).", scope="bench")
register("ROOM_TPU_BENCH_SPEC_PIPELINE", "bool", "1",
         "Run the spec_pipeline phase: spec-off vs in-window spec-on "
         "A/B on repetitive traffic at full window depth "
         "(tokens_per_forward, host_stall_ms_per_tok, zero "
         "spec-induced flushes).", scope="bench")
register("ROOM_TPU_BENCH_TRACE", "bool", "1",
         "Run the turnscope phases: trace-on-vs-off overhead A/B "
         "(p50 turn latency budget <= 5%) and the per-class SLO "
         "attribution pass (docs/observability.md).", scope="bench")
register("ROOM_TPU_BENCH_TPU_FALLBACK", "bool", "1",
         "Re-exec the bench as the CPU-proxy profile when the TPU "
         "tunnel is unreachable (instead of the watchdog 0.0 "
         "headline).", scope="bench")
register("ROOM_TPU_BENCH_TPU_PROBE_S", "float", "120",
         "Bounded wait for the TPU-reachability probe before the "
         "CPU-proxy fallback.", scope="bench")
register("ROOM_TPU_PEAK_TFLOPS", "float", "197",
         "Accelerator peak TFLOPs for roofline normalization.",
         scope="bench")
register("ROOM_TPU_CHIP_LOCK", "path", None,
         "Lock-file path serializing real-chip bench runs.",
         scope="bench")
register("ROOM_TPU_CHIP_LOCK_WAIT_S", "float", "300",
         "How long a bench run waits on the chip lock.",
         scope="bench")
register("ROOM_TPU_TUNE_GRID", "str", None,
         "Parameter grid for scripts/tpu_tune.py "
         "(e.g. 'chunk=8,16;batch=8').", scope="bench")
