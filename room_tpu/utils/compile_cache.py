"""Persistent XLA compilation cache wiring.

Cold compiles dominated the failed bench rounds (BENCH_r01–r05 all died
inside the compile watchdog), and every ServingEngine keeps a private
in-process jit cache that starts empty — so engine and bench startup
both point jax at one on-disk cache. A warm cache turns the second
process's compiles into disk hits, which is also what keeps the tier-1
suite inside its timeout window.

Env:
    ROOM_TPU_JAX_CACHE   cache directory (default /tmp/room_tpu_jax_cache;
                         "0"/"off" disables). JAX_COMPILATION_CACHE_DIR
                         is honored as the fallback spelling so existing
                         deployments keep their location.
"""

from __future__ import annotations

import os
import threading
from typing import Optional

from . import knobs, locks

_lock = locks.make_lock("compile_cache")
_configured: Optional[tuple[Optional[str], int]] = None


def cache_dir_from_env() -> Optional[str]:
    raw = knobs.get_str(
        "ROOM_TPU_JAX_CACHE",
        default=os.environ.get("JAX_COMPILATION_CACHE_DIR",
                               "/tmp/room_tpu_jax_cache"),
    ).strip()
    if raw.lower() in ("", "0", "off", "none"):
        return None
    return raw


def enable_compile_cache() -> tuple[Optional[str], int]:
    """Point jax at the persistent compilation cache (idempotent,
    best-effort: a read-only filesystem or an old jax must never stop
    an engine from constructing). Returns ``(dir, preexisting)`` —
    ``dir`` is None when disabled or wiring failed, ``preexisting`` is
    the number of cache entries that already existed, i.e. > 0 means
    this process starts WARM and its big compiles should be disk hits."""
    global _configured
    with _lock:
        if _configured is not None:
            return _configured
        explicit = knobs.is_set("ROOM_TPU_JAX_CACHE")
        path = cache_dir_from_env()
        result: tuple[Optional[str], int] = (None, 0)
        try:
            import jax

            current = getattr(
                jax.config, "jax_compilation_cache_dir", None
            )
            # a host application that configured the cache
            # PROGRAMMATICALLY (jax.config.update at startup) wins:
            # rerouting its warm cache from inside a constructor would
            # be a rude side effect. But jax also ingests
            # JAX_COMPILATION_CACHE_DIR into jax.config on import —
            # that is this module's documented fallback spelling, not a
            # host decision, so an explicit ROOM_TPU_JAX_CACHE (a path
            # or "0" to disable) still overrides it.
            from_env = current == os.environ.get(
                "JAX_COMPILATION_CACHE_DIR"
            )
            if current and (not from_env or not explicit):
                try:
                    pre = sum(
                        1 for n in os.listdir(current)
                        if not n.startswith(".")
                    )
                except OSError:
                    pre = 0
                _configured = (current, pre)
                return _configured

            if path is None:
                if current:
                    jax.config.update("jax_compilation_cache_dir", None)
            else:
                os.makedirs(path, exist_ok=True)
                pre = sum(
                    1 for n in os.listdir(path)
                    if not n.startswith(".")
                )
                jax.config.update("jax_compilation_cache_dir", path)
                jax.config.update(
                    "jax_persistent_cache_min_compile_time_secs", 0.5
                )
                result = (path, pre)
        except Exception:
            result = (None, 0)
        _configured = result
        return result
