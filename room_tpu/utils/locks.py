"""Central registry for every shared lock in the process — the
concurrency twin of the ``ROOM_TPU_*`` knob registry (docs/static_analysis.md).

Registering here is what makes a lock *exist* to the tooling: the
registry carries the lock's name, the source binding (module, class,
attribute) the static lockmap pass uses to resolve ``with x._lock:``
sites, a one-line doc, the kind (``lock``/``rlock``), and the variable
spellings (``hints``) other modules use when they touch the lock
directly (``fleet._lock`` from disagg.py, ``eng._lock`` from
fleet.py). Two consumers:

- **static** — ``room_tpu/analysis/lockmap.py`` extracts the
  whole-program lock-acquisition graph over these names and fails CI
  on cycles (rule ``lock-order-cycle``), unresolvable acquisition
  sites (``lock-unresolved``), and guarded-state violations.
- **runtime** — ``make_lock``/``make_rlock`` return a plain
  ``threading`` primitive normally, or a ``room_tpu.utils.lockdep``
  instrumented witness when ``ROOM_TPU_LOCKDEP`` is armed (the
  chaos/fleet/disagg CI tiers run armed, so the crash-storm suites
  double as race witnesses).

``multi_instance=True`` documents locks that exist once per object of
a many-instance class (engine replicas, fleet session records): a
same-name nesting edge for those is cross-*instance* by design and is
exempt from the static self-deadlock rule (the runtime witness still
catches a same-*instance* re-acquire).

This module is stdlib-only so the lint gate runs without jax.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass

__all__ = [
    "LockDecl", "LOCK_REGISTRY", "register_lock", "make_lock",
    "make_rlock", "lookup", "all_locks",
]


@dataclass(frozen=True)
class LockDecl:
    """One registered shared lock.

    ``module`` is the repo-relative path of the module that creates
    the lock; ``cls`` the owning class (``""`` = module-level global);
    ``attr`` the attribute / global name. ``hints`` are the variable
    spellings that denote the owning object at foreign acquisition
    sites (``"fleet"``, ``"eng"``, ``"rec"``) — the lockmap resolver
    matches ``<hint>.<attr>``.
    """

    name: str
    doc: str
    module: str
    cls: str = ""
    attr: str = "_lock"
    kind: str = "lock"          # "lock" | "rlock"
    hints: tuple = ()
    multi_instance: bool = False


LOCK_REGISTRY: dict[str, LockDecl] = {}

_KINDS = ("lock", "rlock")


def register_lock(
    name: str,
    doc: str,
    *,
    module: str,
    cls: str = "",
    attr: str = "_lock",
    kind: str = "lock",
    hints: tuple = (),
    multi_instance: bool = False,
) -> LockDecl:
    if name in LOCK_REGISTRY:
        raise ValueError(f"lock {name!r} registered twice")
    if kind not in _KINDS:
        raise ValueError(f"lock {name!r}: unknown kind {kind!r}")
    if not doc.strip():
        raise ValueError(f"lock {name!r} registered without a doc line")
    if not module.endswith(".py"):
        raise ValueError(f"lock {name!r}: module must be a repo-relative "
                         f".py path, got {module!r}")
    decl = LockDecl(name, doc, module, cls, attr, kind, tuple(hints),
                    multi_instance)
    LOCK_REGISTRY[name] = decl
    return decl


def lookup(name: str) -> LockDecl:
    decl = LOCK_REGISTRY.get(name)
    if decl is None:
        raise KeyError(
            f"unregistered lock {name!r}: add it to "
            "room_tpu/utils/locks.py (lockmap rule lock-unresolved)"
        )
    return decl


def all_locks() -> dict[str, LockDecl]:
    return dict(LOCK_REGISTRY)


def _lockdep_armed() -> bool:
    from . import knobs

    return knobs.get_bool("ROOM_TPU_LOCKDEP")


def make_lock(name: str):
    """A ``threading.Lock`` for a registered name — or the lockdep
    witness wrapper when ``ROOM_TPU_LOCKDEP`` is armed."""
    decl = lookup(name)
    if decl.kind != "lock":
        raise ValueError(f"lock {name!r} is registered as {decl.kind}; "
                         "use make_rlock")
    if _lockdep_armed():
        from . import lockdep

        return lockdep.LockdepLock(name, threading.Lock(), "lock")
    return threading.Lock()


def make_rlock(name: str):
    """A ``threading.RLock`` for a registered name (lockdep-wrapped
    when armed; reentrant re-acquires record no ordering edge)."""
    decl = lookup(name)
    if decl.kind != "rlock":
        raise ValueError(f"lock {name!r} is registered as {decl.kind}; "
                         "use make_lock")
    if _lockdep_armed():
        from . import lockdep

        return lockdep.LockdepLock(name, threading.RLock(), "rlock")
    return threading.RLock()


# ---------------------------------------------------------------------------
# The registry. Grouped by subsystem, mirroring docs/knobs.md's layout.
# Order in this file is documentation only — the sanctioned acquisition
# ORDER is the static graph (python -m room_tpu.analysis --graph).
# ---------------------------------------------------------------------------

# ---- serving: engine + KV (docs/serving.md) ----
register_lock(
    "engine", "ServingEngine state: sessions, queues, slot tables, "
    "stats — the serving hot-path lock.",
    module="room_tpu/serving/engine.py", cls="ServingEngine",
    attr="_lock", hints=("eng", "engine", "self.engine", "h.engine"),
    multi_instance=True,
)
register_lock(
    "engine_pressure", "Pool-pressure deque behind degradation_level() "
    "— its own lock, never nested with the engine lock.",
    module="room_tpu/serving/engine.py", cls="ServingEngine",
    attr="_pressure_lock", multi_instance=True,
)
register_lock(
    "kv_page_table", "PageTable free-list + per-session block tables.",
    module="room_tpu/serving/kv_pages.py", cls="PageTable",
    attr="_lock", multi_instance=True,
)
register_lock(
    "kv_offload", "TieredKVStore host/disk tier maps and counters "
    "(engine thread mutates; HTTP stats() snapshots).",
    module="room_tpu/serving/kv_offload.py", cls="TieredKVStore",
    attr="_lock", hints=("store", "self._offload"), multi_instance=True,
)
register_lock(
    "scheduler", "RequestScheduler per-class EDF queues + chunk "
    "budgets.",
    module="room_tpu/serving/scheduler.py", cls="RequestScheduler",
    attr="_lock", multi_instance=True,
)
register_lock(
    "prefix_store", "SharedPrefixStore byte accounting + LRU index "
    "over the content-addressed prefix tier.",
    module="room_tpu/serving/prefix_store.py", cls="SharedPrefixStore",
    attr="_lock", multi_instance=True,
)
register_lock(
    "embed_host", "Process-wide embed-service host singleton build.",
    module="room_tpu/serving/embed_service.py", attr="_host_lock",
)
register_lock(
    "embed_index", "DeviceEmbedIndex row/id maps + device buffer "
    "snapshot.",
    module="room_tpu/serving/embed_service.py", cls="DeviceEmbedIndex",
    attr="_lock", multi_instance=True,
)

# ---- serving: fleet + disaggregation (docs/fleet.md, docs/disagg.md) ----
register_lock(
    "fleet", "EngineFleet routing table: session records, replica "
    "states, fleet stats, disagg ship bookkeeping.",
    module="room_tpu/serving/fleet.py", cls="EngineFleet",
    attr="_lock", hints=("fleet", "self.fleet"),
)
register_lock(
    "fleet_mirror", "Fleet-wide history-mirror token accounting "
    "(per-token hot path takes the per-record lock, not this).",
    module="room_tpu/serving/fleet.py", cls="EngineFleet",
    attr="_mirror_lock", hints=("fleet", "self.fleet"),
)
register_lock(
    "fleet_record", "Per-session router record: history mirror "
    "append + routing fields (one per live session).",
    module="room_tpu/serving/fleet.py", cls="_SessionRecord",
    attr="lock", hints=("rec", "r"), multi_instance=True,
)

# ---- serving: pod fault tolerance (docs/podnet.md) ----
register_lock(
    "podnet_membership", "PodMembership member table + failure-"
    "detector state transitions.",
    module="room_tpu/serving/podnet.py", cls="PodMembership",
    attr="_lock", hints=("membership", "self.membership"),
    multi_instance=True,
)
register_lock(
    "podnet_breaker", "One per-peer wire circuit breaker's "
    "state/counters (one per peer address).",
    module="room_tpu/serving/podnet.py", cls="CircuitBreaker",
    attr="_lock", hints=("breaker",), multi_instance=True,
)
register_lock(
    "podnet_breakers", "Process-wide peer-address -> CircuitBreaker "
    "registry build.",
    module="room_tpu/serving/podnet.py", attr="_breakers_lock",
)
register_lock(
    "pod_mirror_journal", "MirrorJournal append buffers, line "
    "counters, and file-handle swap (one per router shard; a "
    "single-shard fleet has exactly one).",
    module="room_tpu/serving/podnet.py", cls="MirrorJournal",
    attr="_lock", hints=("journal",), multi_instance=True,
)
register_lock(
    "placement_map", "PlacementMap epoch + shard-redirect table "
    "(room-id -> router shard; one per fleet, replicated to pod "
    "peers over control frames).",
    module="room_tpu/serving/podnet.py", cls="PlacementMap",
    attr="_lock", hints=("placement", "self.placement"),
    multi_instance=True,
)
register_lock(
    "kv_wire_server", "KVWireServer payload sequence counter + "
    "receive stats (per-connection handler threads share them).",
    module="room_tpu/parallel/multihost.py", cls="KVWireServer",
    attr="_lock", multi_instance=True,
)

# ---- serving: faults + trace (docs/chaos.md, docs/observability.md) ----
register_lock(
    "faults", "Armed fault-point table + firing counters.",
    module="room_tpu/serving/faults.py", attr="_lock",
)
register_lock(
    "trace_seq", "Per-session turn-sequence counter for correlation "
    "ids.",
    module="room_tpu/serving/trace.py", attr="_seq_lock",
)
register_lock(
    "trace_finish", "TurnTrace finish/emit critical section.",
    module="room_tpu/serving/trace.py", attr="_finish_lock",
)
register_lock(
    "trace_recorder", "FlightRecorder rings (recent / violations / "
    "events).",
    module="room_tpu/serving/trace.py", cls="FlightRecorder",
    attr="_lock",
)

# ---- core: swarm runtime (docs/swarm_recovery.md) ----
register_lock(
    "telemetry", "In-process resilience counters + latency "
    "histograms.",
    module="room_tpu/core/telemetry.py", attr="_counters_lock",
)
register_lock(
    "agent_registry", "LoopDomain registry: running loops + launched "
    "rooms (one domain per swarm shard; classic mode has exactly "
    "one).",
    module="room_tpu/core/agent_loop.py", cls="LoopDomain",
    attr="_registry_lock", hints=("dom", "domain", "self.domain"),
    multi_instance=True,
)
register_lock(
    "agent_supervision", "LoopDomain crash-strike history + "
    "unhealthy-worker roster for supervise_loops (one per domain).",
    module="room_tpu/core/agent_loop.py", cls="LoopDomain",
    attr="_supervision_lock", hints=("dom", "domain", "self.domain"),
    multi_instance=True,
)
register_lock(
    "event_bus", "EventBus subscriber lists (the global bus plus one "
    "segment per swarm shard).",
    module="room_tpu/core/events.py", cls="EventBus", attr="_lock",
    hints=("bus", "self.bus"), multi_instance=True,
)
register_lock(
    "task_slots", "Per-room concurrent task-run slot pool.",
    module="room_tpu/core/task_runner.py", cls="_SlotPool",
    attr="_lock",
)
register_lock(
    "cycle_logs", "In-memory cycle log ring buffer.",
    module="room_tpu/core/cycle_logs.py", cls="CycleLogBuffer",
    attr="_lock",
)
register_lock(
    "supervisor", "Tracked child-process table.",
    module="room_tpu/core/supervisor.py", attr="_lock",
)
register_lock(
    "web_sessions", "Web-automation session table.",
    module="room_tpu/core/web_tools.py", attr="_sessions_lock",
)

# ---- swarm shards (docs/swarmshard.md) ----
register_lock(
    "swarm_router", "SwarmRouter shard table: shard states, adopted "
    "db handles, cross-shard dispatch counters.",
    module="room_tpu/swarm/shard.py", cls="SwarmRouter", attr="_lock",
    hints=("router", "self.router"),
)
register_lock(
    "swarm_default", "Process-default SwarmRouter singleton build.",
    module="room_tpu/swarm/shard.py", attr="_default_router_lock",
)
register_lock(
    "swarm_dispatch", "Per-shard cross-shard dispatch serialization: "
    "journaled_once's dedup-check -> intent -> commit sequence is "
    "check-then-act, so concurrent redeliveries of one idempotency "
    "key must queue (one lock per shard file).",
    module="room_tpu/swarm/shard.py", cls="SwarmShard",
    attr="_dispatch_lock",
    hints=("shard", "shard_src", "shard_dst", "self.shards"),
    multi_instance=True,
)
register_lock(
    "swarm_proc", "ProcSupervisor child table: per-shard child "
    "state/pid/address, restart ledger, last heartbeat stats "
    "(docs/swarmshard.md process mode).",
    module="room_tpu/swarm/procshard.py", cls="ProcSupervisor",
    attr="_lock", hints=("proc", "self.proc"),
)
register_lock(
    "swarm_proc_default", "Process-default ProcSupervisor singleton "
    "build.",
    module="room_tpu/swarm/procshard.py",
    attr="_default_proc_lock",
)
register_lock(
    "swarm_proc_child", "Shard child process: serializes "
    "journaled_once's check-then-act dedup for xshard frames landing "
    "on the child's own (or adopted) file — the in-process "
    "swarm_dispatch lock's cross-process twin.",
    module="room_tpu/swarm/procshard.py", cls="ShardChild",
    attr="_dispatch_lock", multi_instance=True,
)

# ---- db ----
register_lock(
    "db", "SQLite connection serialization (reentrant: transaction "
    "helpers nest).",
    module="room_tpu/db/database.py", cls="Database", attr="_lock",
    kind="rlock", multi_instance=True,
)
register_lock(
    "db_default", "Process-default Database singleton build.",
    module="room_tpu/db/database.py", attr="_default_lock",
)

# ---- providers (docs/lifecycle.md) ----
register_lock(
    "model_hosts", "Process-wide ModelHost registry build/teardown.",
    module="room_tpu/providers/tpu.py", attr="_hosts_lock",
)
register_lock(
    "model_host", "One ModelHost's engine build / drain / restore "
    "serialization.",
    module="room_tpu/providers/tpu.py", cls="ModelHost", attr="_lock",
    hints=("host",), multi_instance=True,
)

# ---- server ----
register_lock(
    "lifecycle", "Server lifecycle phase + drain summary fields.",
    module="room_tpu/server/runtime.py", attr="_lifecycle_lock",
)
register_lock(
    "runtime_pending", "ServerRuntime pending-notification queue.",
    module="room_tpu/server/runtime.py", cls="ServerRuntime",
    attr="_pending_lock",
)
register_lock(
    "http_rate_limiter", "Per-client HTTP rate-limit window.",
    module="room_tpu/server/http.py", cls="_RateLimiter", attr="_lock",
)
register_lock(
    "webhooks", "Registered webhook table.",
    module="room_tpu/server/webhooks.py", attr="_lock",
)
register_lock(
    "updater", "UpdateChecker cached release state.",
    module="room_tpu/server/updater.py", cls="UpdateChecker",
    attr="_lock",
)
register_lock(
    "tpu_manager", "TPU runtime singleton build.",
    module="room_tpu/server/tpu_manager.py", attr="_lock",
)
register_lock(
    "commentary", "CommentaryEngine recent-line ring.",
    module="room_tpu/server/commentary.py", cls="CommentaryEngine",
    attr="_lock",
)
register_lock(
    "ws_hub", "WebSocketHub client set + channel subscriptions.",
    module="room_tpu/server/ws.py", cls="WebSocketHub", attr="_lock",
)
register_lock(
    "provider_auth", "ProviderAuthManager session map.",
    module="room_tpu/server/provider_auth.py", cls="ProviderAuthManager",
    attr="_lock",
)
register_lock(
    "provider_auth_manager", "Process-default ProviderAuthManager "
    "singleton build.",
    module="room_tpu/server/provider_auth.py", attr="_manager_lock",
)

# ---- utils ----
register_lock(
    "native_lib", "Lazy native-library dlopen singleton.",
    module="room_tpu/utils/native.py", attr="_lock",
)
register_lock(
    "compile_cache", "One-shot XLA compile-cache enablement.",
    module="room_tpu/utils/compile_cache.py", attr="_lock",
)
register_lock(
    "http_profiler", "HttpProfiler slow-request ring.",
    module="room_tpu/utils/profiling.py", cls="HttpProfiler",
    attr="_lock",
)
register_lock(
    "device_profiler", "DeviceProfiler capture state (one capture at "
    "a time).",
    module="room_tpu/utils/profiling.py", cls="DeviceProfiler",
    attr="_lock",
)
register_lock(
    "step_timer", "StepTimer rolling duration window.",
    module="room_tpu/utils/profiling.py", cls="StepTimer", attr="_lock",
    multi_instance=True,
)
