"""Tracing / profiling (SURVEY.md §5):

- HTTP endpoint latency profiling, opt-in via ROOM_TPU_PROFILE_HTTP=1
  (reference: QUOROOM_PROFILE_HTTP, src/server/index.ts:289-320):
  per-endpoint count/mean/p95 with slow-request marks and path
  normalization (ids collapsed to :id).
- Device traces: jax.profiler wrappers writing TensorBoard-format
  traces (the reference had nothing on this axis; the TPU engine
  does) — the inline ``device_trace`` context manager, plus the
  on-demand ``device_profiler`` capture that POST /api/tpu/profile
  triggers against a live serving process (docs/observability.md).
"""

from __future__ import annotations

import logging
import os
import re
import threading
import time
from contextlib import contextmanager
from . import knobs, locks
from typing import Iterator, Optional

log = logging.getLogger(__name__)

SLOW_MS = knobs.get_float("ROOM_TPU_PROFILE_SLOW_MS")

_ID_SEG = re.compile(r"/\d+")
# opaque ids/secrets: webhook tokens, session ids, uuids — any long
# url-safe segment collapses so secrets never become profiler keys
_OPAQUE_SEG = re.compile(r"/[A-Za-z0-9_\-]{10,}")
MAX_KEYS = 512


def http_profiling_enabled() -> bool:
    return knobs.get_bool("ROOM_TPU_PROFILE_HTTP")


def normalize_path(path: str) -> str:
    path = _ID_SEG.sub("/:id", path)
    return _OPAQUE_SEG.sub("/:token", path)


class HttpProfiler:
    def __init__(self) -> None:
        self._stats: dict[str, list[float]] = {}
        self._lock = locks.make_lock("http_profiler")

    def record(self, method: str, path: str, ms: float) -> None:
        key = f"{method} {normalize_path(path)}"
        with self._lock:
            if key not in self._stats and len(self._stats) >= MAX_KEYS:
                return  # bounded cardinality (records run pre-auth)
            samples = self._stats.setdefault(key, [])
            samples.append(ms)
            del samples[:-500]
        if ms >= SLOW_MS:
            # the server's logging path (the engine/fleet idiom), not
            # a bare print: slow-request marks must land wherever the
            # deployment routes its logs
            log.warning("slow http request: %s %.0fms", key, ms)

    def reset(self) -> None:
        with self._lock:
            self._stats.clear()

    def snapshot(self) -> dict[str, dict]:
        with self._lock:
            stats = {k: list(v) for k, v in self._stats.items()}
        out = {}
        for key, samples in stats.items():
            s = sorted(samples)
            out[key] = {
                "count": len(s),
                "mean_ms": round(sum(s) / len(s), 2),
                "p95_ms": round(s[int(0.95 * (len(s) - 1))], 2),
            }
        return out


http_profiler = HttpProfiler()


@contextmanager
def device_trace(name: str = "room-tpu") -> Iterator[None]:
    """jax.profiler trace scope writing to ROOM_TPU_TRACE_DIR (or the
    data dir); open the output with TensorBoard or xprof."""
    import jax

    base = knobs.get_str("ROOM_TPU_TRACE_DIR")
    if not base:
        from ..server.auth import data_dir

        base = os.path.join(data_dir(), "traces")
    os.makedirs(base, exist_ok=True)
    jax.profiler.start_trace(base)
    try:
        with jax.profiler.TraceAnnotation(name):
            yield
    finally:
        jax.profiler.stop_trace()


@contextmanager
def annotate(name: str) -> Iterator[None]:
    """Lightweight annotation visible in device traces."""
    import jax

    with jax.profiler.TraceAnnotation(name):
        yield


def _trace_base_dir() -> str:
    base = knobs.get_str("ROOM_TPU_TRACE_DIR")
    if not base:
        from ..server.auth import data_dir

        base = os.path.join(data_dir(), "traces")
    return base


class DeviceProfiler:
    """On-demand jax.profiler capture against a LIVE serving process —
    what POST /api/tpu/profile triggers (docs/observability.md). One
    capture at a time: jax.profiler is a process-global singleton, so
    a second start would corrupt the first. The capture runs on its
    own daemon thread (start_trace/stop_trace bracket whatever the
    engine threads dispatch in between) and writes a timestamped
    TensorBoard trace dir under ROOM_TPU_TRACE_DIR."""

    def __init__(self) -> None:
        self._lock = locks.make_lock("device_profiler")
        self._thread: Optional[threading.Thread] = None
        self._state: dict = {"running": False}
        self._seq = 0

    def start(self, duration_s: float) -> dict:
        """Begin a bounded capture; raises RuntimeError if one is
        already running. Returns {dir, duration_s}."""
        duration_s = min(
            max(0.01, duration_s),
            max(0.01, knobs.get_float("ROOM_TPU_PROFILE_MAX_S")),
        )
        with self._lock:
            self._seq += 1
            seq = self._seq
        # the capture counter disambiguates back-to-back captures
        # landing in the same wall-clock second (interleaved traces in
        # one dir corrupt the TensorBoard view)
        out_dir = os.path.join(
            _trace_base_dir(),
            f"capture-{time.strftime('%Y%m%d-%H%M%S')}-{seq}",
        )
        with self._lock:
            if self._state.get("running"):
                raise RuntimeError(
                    "a device-trace capture is already running "
                    f"(dir {self._state.get('dir')})"
                )
            self._state = {
                "running": True, "dir": out_dir,
                "duration_s": duration_s, "error": None,
            }
            self._thread = threading.Thread(
                target=self._run, args=(out_dir, duration_s),
                daemon=True, name="device-profile",
            )
            self._thread.start()
        return {"dir": out_dir, "duration_s": duration_s}

    def _run(self, out_dir: str, duration_s: float) -> None:
        try:
            import jax

            os.makedirs(out_dir, exist_ok=True)
            jax.profiler.start_trace(out_dir)
            try:
                time.sleep(duration_s)
            finally:
                jax.profiler.stop_trace()
        except Exception as e:   # noqa: BLE001 — reported via status
            log.warning("device-trace capture failed: %s", e)
            with self._lock:
                self._state["error"] = f"{type(e).__name__}: {e}"
        finally:
            with self._lock:
                self._state["running"] = False
            try:
                from ..serving import trace as trace_mod

                trace_mod.note_event("profile_capture", {
                    "dir": out_dir, "duration_s": duration_s,
                })
            except Exception:
                pass

    def status(self) -> dict:
        with self._lock:
            return dict(self._state)


device_profiler = DeviceProfiler()


class StepTimer:
    """Host-side timing for engine phases (prefill/decode), feeding the
    per-batch decode metrics the engine exposes in stats()."""

    def __init__(self) -> None:
        self.totals: dict[str, float] = {}
        self.counts: dict[str, int] = {}
        self._lock = locks.make_lock("step_timer")

    @contextmanager
    def phase(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            with self._lock:
                self.totals[name] = self.totals.get(name, 0.0) + dt
                self.counts[name] = self.counts.get(name, 0) + 1

    def snapshot(self) -> dict[str, dict]:
        with self._lock:
            return {
                name: {
                    "count": self.counts[name],
                    "total_s": round(self.totals[name], 3),
                    "mean_ms": round(
                        1000 * self.totals[name] / self.counts[name], 2
                    ),
                }
                for name in self.totals
            }
