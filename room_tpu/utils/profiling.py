"""Tracing / profiling (SURVEY.md §5):

- HTTP endpoint latency profiling, opt-in via ROOM_TPU_PROFILE_HTTP=1
  (reference: QUOROOM_PROFILE_HTTP, src/server/index.ts:289-320):
  per-endpoint count/mean/p95 with slow-request marks and path
  normalization (ids collapsed to :id).
- Device traces: jax.profiler wrapper writing TensorBoard-format traces
  (the reference had nothing on this axis; the TPU engine does).
"""

from __future__ import annotations

import os
import re
import threading
import time
from contextlib import contextmanager
from . import knobs
from typing import Iterator

SLOW_MS = knobs.get_float("ROOM_TPU_PROFILE_SLOW_MS")

_ID_SEG = re.compile(r"/\d+")
# opaque ids/secrets: webhook tokens, session ids, uuids — any long
# url-safe segment collapses so secrets never become profiler keys
_OPAQUE_SEG = re.compile(r"/[A-Za-z0-9_\-]{10,}")
MAX_KEYS = 512


def http_profiling_enabled() -> bool:
    return knobs.get_bool("ROOM_TPU_PROFILE_HTTP")


def normalize_path(path: str) -> str:
    path = _ID_SEG.sub("/:id", path)
    return _OPAQUE_SEG.sub("/:token", path)


class HttpProfiler:
    def __init__(self) -> None:
        self._stats: dict[str, list[float]] = {}
        self._lock = threading.Lock()

    def record(self, method: str, path: str, ms: float) -> None:
        key = f"{method} {normalize_path(path)}"
        with self._lock:
            if key not in self._stats and len(self._stats) >= MAX_KEYS:
                return  # bounded cardinality (records run pre-auth)
            samples = self._stats.setdefault(key, [])
            samples.append(ms)
            del samples[:-500]
        if ms >= SLOW_MS:
            print(f"[http-prof] SLOW {key} {ms:.0f}ms", flush=True)

    def reset(self) -> None:
        with self._lock:
            self._stats.clear()

    def snapshot(self) -> dict[str, dict]:
        with self._lock:
            stats = {k: list(v) for k, v in self._stats.items()}
        out = {}
        for key, samples in stats.items():
            s = sorted(samples)
            out[key] = {
                "count": len(s),
                "mean_ms": round(sum(s) / len(s), 2),
                "p95_ms": round(s[int(0.95 * (len(s) - 1))], 2),
            }
        return out


http_profiler = HttpProfiler()


@contextmanager
def device_trace(name: str = "room-tpu") -> Iterator[None]:
    """jax.profiler trace scope writing to ROOM_TPU_TRACE_DIR (or the
    data dir); open the output with TensorBoard or xprof."""
    import jax

    base = knobs.get_str("ROOM_TPU_TRACE_DIR")
    if not base:
        from ..server.auth import data_dir

        base = os.path.join(data_dir(), "traces")
    os.makedirs(base, exist_ok=True)
    jax.profiler.start_trace(base)
    try:
        with jax.profiler.TraceAnnotation(name):
            yield
    finally:
        jax.profiler.stop_trace()


@contextmanager
def annotate(name: str) -> Iterator[None]:
    """Lightweight annotation visible in device traces."""
    import jax

    with jax.profiler.TraceAnnotation(name):
        yield


class StepTimer:
    """Host-side timing for engine phases (prefill/decode), feeding the
    per-batch decode metrics the engine exposes in stats()."""

    def __init__(self) -> None:
        self.totals: dict[str, float] = {}
        self.counts: dict[str, int] = {}
        self._lock = threading.Lock()

    @contextmanager
    def phase(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            with self._lock:
                self.totals[name] = self.totals.get(name, 0.0) + dt
                self.counts[name] = self.counts.get(name, 0) + 1

    def snapshot(self) -> dict[str, dict]:
        with self._lock:
            return {
                name: {
                    "count": self.counts[name],
                    "total_s": round(self.totals[name], 3),
                    "mean_ms": round(
                        1000 * self.totals[name] / self.counts[name], 2
                    ),
                }
                for name in self.totals
            }
