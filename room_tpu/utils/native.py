"""ctypes bindings for the native vector-search core (native/vecsearch.cpp
— the sqlite-vec replacement). Builds lazily via make on first use;
everything degrades to numpy when the toolchain or artifact is missing."""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np
from . import locks

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(__file__))), "native"
)
_LIB_PATH = os.path.join(_NATIVE_DIR, "libvecsearch.so")

_lib: Optional[ctypes.CDLL] = None
_tried = False
_lock = locks.make_lock("native_lib")


def _bind(path: str) -> Optional[ctypes.CDLL]:
    try:
        lib = ctypes.CDLL(path)
    except OSError:
        return None
    lib.topk_cosine.restype = ctypes.c_int
    lib.topk_cosine.argtypes = [
        ctypes.POINTER(ctypes.c_float), ctypes.c_int64,
        ctypes.c_int64, ctypes.POINTER(ctypes.c_float),
        ctypes.c_int, ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_float),
    ]
    return lib


def _build_async() -> None:
    """Compile in the background; callers use numpy until ready (the
    build must never block a request path)."""
    global _lib

    def build():
        global _lib
        try:
            subprocess.run(
                ["make", "-C", _NATIVE_DIR],
                capture_output=True, timeout=120, check=True,
            )
        except (OSError, subprocess.SubprocessError):
            return
        lib = _bind(_LIB_PATH)
        with _lock:
            _lib = lib

    threading.Thread(target=build, daemon=True,
                     name="vecsearch-build").start()


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _lib is not None:
            return _lib
        if _tried:
            return None
        _tried = True
        if os.path.exists(_LIB_PATH):
            _lib = _bind(_LIB_PATH)
            return _lib
    _build_async()
    return None


def native_available(wait_s: float = 0.0) -> bool:
    """True when the native library is bound. With wait_s, polls for a
    background build to finish (tests use this; request paths don't)."""
    import time

    deadline = time.monotonic() + wait_s
    while True:
        if _load() is not None:
            return True
        if time.monotonic() >= deadline:
            return False
        time.sleep(0.05)


def topk_cosine(
    matrix: np.ndarray, query: np.ndarray, k: int
) -> tuple[np.ndarray, np.ndarray]:
    """(indices, scores) of the k most cosine-similar rows, descending.
    Native when available, numpy otherwise — identical results."""
    matrix = np.ascontiguousarray(matrix, dtype=np.float32)
    query = np.ascontiguousarray(query, dtype=np.float32)
    n = matrix.shape[0]
    if n == 0 or k <= 0:
        return np.zeros(0, np.int32), np.zeros(0, np.float32)

    lib = _load()
    if lib is not None:
        k_eff = min(k, n)
        out_idx = np.zeros(k_eff, np.int32)
        out_score = np.zeros(k_eff, np.float32)
        got = lib.topk_cosine(
            matrix.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            n, matrix.shape[1],
            query.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            k_eff,
            out_idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            out_score.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        )
        return out_idx[:got], out_score[:got]

    qn = query / (np.linalg.norm(query) + 1e-9)
    mn = matrix / (
        np.linalg.norm(matrix, axis=1, keepdims=True) + 1e-9
    )
    sims = mn @ qn
    order = np.argsort(-sims)[:k]
    return order.astype(np.int32), sims[order].astype(np.float32)
