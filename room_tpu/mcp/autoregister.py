"""MCP auto-registration: patch the configs of installed AI clients so
their agents can immediately drive this engine (reference:
src/server/index.ts:729-864 — Claude Code, Claude Desktop, Cursor,
Windsurf get JSON `mcpServers` entries; Codex gets a TOML section;
Claude Code additionally gets the tools auto-approved).

Only EXISTING config files are patched (a missing file means the client
isn't installed — registration must not scatter config files around) and
failures are silent: registration can never break server startup.
"""

from __future__ import annotations

import json
import os
import re
import sys
from typing import Optional

SERVER_NAME = "room_tpu"


def _mcp_entry(db_path: str, source: str) -> dict:
    return {
        "command": sys.executable,
        "args": ["-m", "room_tpu", "mcp"],
        "env": {"ROOM_TPU_DB_PATH": db_path,
                "ROOM_TPU_SOURCE": source},
    }


def patch_mcp_config(config_path: str, entry: dict) -> bool:
    try:
        if not os.path.exists(config_path):
            return False
        try:
            with open(config_path) as f:
                config = json.load(f)
            if not isinstance(config, dict):
                return False
        except (json.JSONDecodeError, OSError):
            # unparseable (possibly mid-write by the client): touching
            # it risks destroying the user's whole config — leave it
            # alone (deliberate deviation from the reference, which
            # rewrites)
            return False
        servers = config.get("mcpServers")
        if not isinstance(servers, dict):
            servers = {}
        servers[SERVER_NAME] = entry
        config["mcpServers"] = servers
        with open(config_path, "w") as f:
            json.dump(config, f, indent=2)
            f.write("\n")
        return True
    except OSError:
        return False


def patch_codex_config(config_path: str, db_path: str) -> bool:
    """Codex stores MCP servers in TOML; replace any existing
    [mcp_servers.room_tpu] section line-based, then append ours."""
    try:
        if not os.path.exists(config_path):
            return False
        with open(config_path) as f:
            raw = f.read()
        filtered: list[str] = []
        in_section = False
        for line in raw.split("\n"):
            if re.match(rf"^\[mcp_servers\.{SERVER_NAME}[\].]", line):
                in_section = True
                continue
            if in_section and line.startswith("["):
                in_section = False
            if not in_section:
                filtered.append(line)
        content = "\n".join(filtered).rstrip()
        content += (
            f"\n\n[mcp_servers.{SERVER_NAME}]\n"
            f"command = '{sys.executable}'\n"
            f"args = ['-m', 'room_tpu', 'mcp']\n\n"
            f"[mcp_servers.{SERVER_NAME}.env]\n"
            f"ROOM_TPU_DB_PATH = '{db_path}'\n"
            f'ROOM_TPU_SOURCE = "codex"\n'
        )
        with open(config_path, "w") as f:
            f.write(content)
        return True
    except OSError:
        return False


def patch_claude_code_permissions(home: str) -> bool:
    """Auto-approve our MCP tools in ~/.claude/settings.json so headless
    queen sessions don't stall on permission prompts."""
    try:
        settings_path = os.path.join(home, ".claude", "settings.json")
        if not os.path.exists(settings_path):
            return False
        try:
            with open(settings_path) as f:
                settings = json.load(f)
            if not isinstance(settings, dict):
                settings = {}
        except (json.JSONDecodeError, OSError):
            settings = {}
        perms = settings.get("permissions")
        if not isinstance(perms, dict):
            perms = {}
        allow = perms.get("allow")
        allow = list(allow) if isinstance(allow, list) else []
        pattern = f"mcp__{SERVER_NAME}__*"
        if pattern in allow:
            return False
        allow.append(pattern)
        perms["allow"] = allow
        settings["permissions"] = perms
        with open(settings_path, "w") as f:
            json.dump(settings, f, indent=2)
            f.write("\n")
        return True
    except OSError:
        return False


def register_mcp_globally(
    db_path: str, home: Optional[str] = None
) -> dict[str, bool]:
    """Patch every known client; returns {client: patched} for the
    status surface. Never raises."""
    out: dict[str, bool] = {}
    try:
        home = home or os.path.expanduser("~")
        out["claude-code"] = patch_mcp_config(
            os.path.join(home, ".claude.json"),
            _mcp_entry(db_path, "claude-code"),
        )
        out["claude-code-permissions"] = \
            patch_claude_code_permissions(home)
        if sys.platform == "win32":  # pragma: no cover
            desktop = os.path.join(
                home, "AppData", "Roaming", "Claude",
                "claude_desktop_config.json",
            )
        elif sys.platform == "darwin":  # pragma: no cover
            desktop = os.path.join(
                home, "Library", "Application Support", "Claude",
                "claude_desktop_config.json",
            )
        else:
            desktop = os.path.join(
                home, ".config", "Claude", "claude_desktop_config.json"
            )
        out["claude-desktop"] = patch_mcp_config(
            desktop, _mcp_entry(db_path, "claude-desktop")
        )
        out["cursor"] = patch_mcp_config(
            os.path.join(home, ".cursor", "mcp.json"),
            _mcp_entry(db_path, "cursor"),
        )
        out["windsurf"] = patch_mcp_config(
            os.path.join(home, ".codeium", "windsurf",
                         "mcp_config.json"),
            _mcp_entry(db_path, "windsurf"),
        )
        out["codex"] = patch_codex_config(
            os.path.join(home, ".codex", "config.toml"), db_path
        )
    except Exception:
        pass
    return out
