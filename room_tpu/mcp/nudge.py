"""Cross-process agent wake-up (reference: src/mcp/nudge.ts): the MCP
process shares the SQLite file with the API server but runs its own
process, so waking an agent goes over local HTTP using the api.port /
api.token files the server wrote."""

from __future__ import annotations

import json
import os
import urllib.request
from typing import Optional

from ..server.auth import data_dir


def _read(name: str) -> Optional[str]:
    try:
        with open(os.path.join(data_dir(), name)) as f:
            return f.read().strip()
    except OSError:
        return None


def nudge_worker(worker_id: int, cold_start: bool = False) -> bool:
    """Fire-and-forget POST /api/workers/:id/start."""
    port, token = _read("api.port"), _read("api.token")
    if not port or not token:
        return False
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/api/workers/{worker_id}/start",
            data=json.dumps({"coldStart": cold_start}).encode(),
            headers={
                "Authorization": f"Bearer {token}",
                "Content-Type": "application/json",
            },
        )
        with urllib.request.urlopen(req, timeout=5):
            return True
    except OSError:
        return False
