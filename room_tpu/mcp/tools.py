"""MCP tool catalog (reference: src/mcp/tools/ — 19 modules, ~40
``quoroom_*`` tools; here ``room_*`` tools over the same engine).

Every tool is a (name, description, json-schema, handler) tuple; handlers
take (db, args) and return text. The registry is data, so the stdio
server and tests iterate it uniformly."""

from __future__ import annotations

import json
import platform
import shutil
from typing import Any, Callable

from ..core import (
    escalations as escalations_mod,
    goals as goals_mod,
    memory as memory_mod,
    messages as messages_mod,
    quorum as quorum_mod,
    rooms as rooms_mod,
    selfmod as selfmod_mod,
    skills as skills_mod,
    task_runner,
    wallet as wallet_mod,
    workers as workers_mod,
)
from ..core.cron import validate_cron
from ..db import Database
from .nudge import nudge_worker

Tool = tuple[str, str, dict, Callable[[Database, dict], str]]
TOOLS: list[Tool] = []


def tool(name: str, description: str, schema: dict | None = None):
    def wrap(fn):
        TOOLS.append((
            name, description,
            {"type": "object", "properties": schema or {},
             "required": [k for k, v in (schema or {}).items()
                          if v.pop("required", False)]},
            fn,
        ))
        return fn

    return wrap


def _j(data: Any) -> str:
    return json.dumps(data, indent=1, default=str)


# ---- rooms ----

@tool("room_list", "List all rooms with status and goals.")
def room_list(db, args):
    return _j([
        {"id": r["id"], "name": r["name"], "status": r["status"],
         "goal": r["goal"]}
        for r in rooms_mod.list_rooms(db)
    ])


@tool(
    "room_create", "Create a room with its queen (and wallet).",
    {"name": {"type": "string", "required": True},
     "goal": {"type": "string"}},
)
def room_create(db, args):
    room = rooms_mod.create_room(db, args["name"], goal=args.get("goal"))
    return f"room #{room['id']} created with queen #{room['queen_worker_id']}"


@tool(
    "room_status", "Aggregate status for one room.",
    {"room_id": {"type": "integer", "required": True}},
)
def room_status(db, args):
    st = rooms_mod.get_room_status(db, int(args["room_id"]))
    return _j(st) if st else "room not found"


@tool(
    "room_start", "Start a room's agent loops (via the server).",
    {"room_id": {"type": "integer", "required": True}},
)
def room_start(db, args):
    room = rooms_mod.get_room(db, int(args["room_id"]))
    if room is None:
        return "room not found"
    if nudge_worker(room["queen_worker_id"], cold_start=True):
        return f"room #{room['id']} queen nudged"
    return "server not reachable — is `room-tpu serve` running?"


# ---- workers ----

@tool(
    "worker_list", "List a room's workers.",
    {"room_id": {"type": "integer", "required": True}},
)
def worker_list(db, args):
    return _j(workers_mod.list_room_workers(db, int(args["room_id"])))


@tool(
    "worker_create", "Add a worker to a room.",
    {"room_id": {"type": "integer", "required": True},
     "name": {"type": "string", "required": True},
     "role": {"type": "string"},
     "system_prompt": {"type": "string"}},
)
def worker_create(db, args):
    wid = workers_mod.create_worker(
        db, args["name"], args.get("system_prompt", ""),
        room_id=int(args["room_id"]), role=args.get("role"),
    )
    return f"worker #{wid} created"


@tool(
    "worker_nudge", "Wake a worker's agent loop now.",
    {"worker_id": {"type": "integer", "required": True}},
)
def worker_nudge(db, args):
    okay = nudge_worker(int(args["worker_id"]))
    return "nudged" if okay else "server not reachable"


# ---- goals ----

@tool(
    "goal_tree", "The room's goal tree.",
    {"room_id": {"type": "integer", "required": True}},
)
def goal_tree(db, args):
    return _j(goals_mod.get_goal_tree(db, int(args["room_id"])))


@tool(
    "goal_create", "Create a goal (optionally under a parent/worker).",
    {"room_id": {"type": "integer", "required": True},
     "description": {"type": "string", "required": True},
     "parent_goal_id": {"type": "integer"},
     "worker_id": {"type": "integer"}},
)
def goal_create(db, args):
    gid = goals_mod.create_goal(
        db, int(args["room_id"]), args["description"],
        parent_goal_id=args.get("parent_goal_id"),
        assigned_worker_id=args.get("worker_id"),
    )
    return f"goal #{gid} created"


@tool(
    "goal_complete", "Mark a goal complete.",
    {"goal_id": {"type": "integer", "required": True}},
)
def goal_complete(db, args):
    goals_mod.complete_goal(db, int(args["goal_id"]))
    return f"goal #{args['goal_id']} completed"


# ---- memory ----

@tool(
    "memory_remember", "Store a durable fact in semantic memory.",
    {"name": {"type": "string", "required": True},
     "content": {"type": "string", "required": True},
     "category": {"type": "string"},
     "room_id": {"type": "integer"}},
)
def memory_remember(db, args):
    eid = memory_mod.remember(
        db, args["name"], args["content"],
        category=args.get("category"), room_id=args.get("room_id"),
    )
    return f"remembered as entity #{eid}"


@tool(
    "memory_recall", "Hybrid search over memory.",
    {"query": {"type": "string", "required": True},
     "room_id": {"type": "integer"}},
)
def memory_recall(db, args):
    hits = memory_mod.hybrid_search(
        db, args["query"], room_id=args.get("room_id")
    )
    if not hits:
        return "no memories found"
    return "\n".join(
        f"- #{h['entity_id']} {h['name']}: "
        f"{'; '.join(h['observations'][-2:])}"
        for h in hits
    )


@tool(
    "memory_forget", "Delete a memory entity.",
    {"entity_id": {"type": "integer", "required": True}},
)
def memory_forget(db, args):
    okay = memory_mod.delete_entity(db, int(args["entity_id"]))
    return "forgotten" if okay else "entity not found"


# ---- quorum ----

@tool(
    "quorum_decisions", "Open decisions for a room.",
    {"room_id": {"type": "integer", "required": True}},
)
def quorum_decisions(db, args):
    return _j(quorum_mod.pending_decisions(db, int(args["room_id"])))


@tool(
    "quorum_vote", "Cast a worker's vote on a ballot.",
    {"decision_id": {"type": "integer", "required": True},
     "worker_id": {"type": "integer", "required": True},
     "vote": {"type": "string", "required": True},
     "reasoning": {"type": "string"}},
)
def quorum_vote(db, args):
    try:
        d = quorum_mod.vote(
            db, int(args["decision_id"]), int(args["worker_id"]),
            args["vote"], args.get("reasoning"),
        )
    except quorum_mod.QuorumError as e:
        return str(e)
    return f"vote recorded; decision is {d['status']}"


@tool(
    "quorum_keeper_vote", "Cast the keeper's vote.",
    {"decision_id": {"type": "integer", "required": True},
     "vote": {"type": "string", "required": True}},
)
def quorum_keeper_vote(db, args):
    try:
        d = quorum_mod.keeper_vote(db, int(args["decision_id"]),
                                   args["vote"])
    except quorum_mod.QuorumError as e:
        return str(e)
    return f"keeper vote recorded; decision is {d['status']}"


# ---- scheduler ----

@tool(
    "schedule_task",
    "Schedule a task. Use cron_expression for recurring (5-field cron) "
    "or scheduled_at (UTC ISO) for one-time.",
    {"name": {"type": "string", "required": True},
     "prompt": {"type": "string", "required": True},
     "cron_expression": {"type": "string"},
     "scheduled_at": {"type": "string"},
     "room_id": {"type": "integer"}},
)
def schedule_task(db, args):
    trigger = "cron" if args.get("cron_expression") else "once"
    try:
        tid = task_runner.create_task(
            db, args["name"], args["prompt"], trigger_type=trigger,
            cron_expression=args.get("cron_expression"),
            scheduled_at=args.get("scheduled_at"),
            room_id=args.get("room_id"),
        )
    except ValueError as e:
        return str(e)
    task = task_runner.get_task(db, tid)
    return (
        f"task #{tid} scheduled ({trigger}); webhook: "
        f"/api/hooks/task/{task['webhook_token']}"
    )


@tool("task_list", "List scheduled tasks.",
      {"room_id": {"type": "integer"}})
def task_list(db, args):
    return _j([
        {"id": t["id"], "name": t["name"], "status": t["status"],
         "trigger": t["trigger_type"], "cron": t["cron_expression"],
         "runs": t["run_count"]}
        for t in task_runner.list_tasks(db, args.get("room_id"))
    ])


@tool(
    "task_history", "Recent runs of a task.",
    {"task_id": {"type": "integer", "required": True}},
)
def task_history(db, args):
    return _j(db.query(
        "SELECT id, status, started_at, duration_ms, "
        "substr(COALESCE(result, error_message, ''), 1, 200) AS summary "
        "FROM task_runs WHERE task_id=? ORDER BY id DESC LIMIT 10",
        (int(args["task_id"]),),
    ))


@tool(
    "task_pause", "Pause a task.",
    {"task_id": {"type": "integer", "required": True}},
)
def task_pause(db, args):
    task_runner.pause_task(db, int(args["task_id"]))
    return f"task #{args['task_id']} paused"


@tool(
    "task_resume", "Resume a paused task.",
    {"task_id": {"type": "integer", "required": True}},
)
def task_resume(db, args):
    task_runner.resume_task(db, int(args["task_id"]))
    return f"task #{args['task_id']} resumed"


@tool(
    "cron_validate", "Validate a 5-field cron expression.",
    {"expression": {"type": "string", "required": True}},
)
def cron_validate(db, args):
    e = validate_cron(args["expression"])
    return e or "valid"


# ---- skills + self-mod ----

@tool("skill_list", "List skills.", {"room_id": {"type": "integer"}})
def skill_list(db, args):
    return _j([
        {"id": s["id"], "name": s["name"], "version": s["version"],
         "auto": bool(s["auto_activate"])}
        for s in skills_mod.list_skills(db, args.get("room_id"))
    ])


@tool(
    "skill_create", "Save a reusable skill.",
    {"name": {"type": "string", "required": True},
     "content": {"type": "string", "required": True},
     "room_id": {"type": "integer"},
     "activation_context": {"type": "string"}},
)
def skill_create(db, args):
    sid = skills_mod.create_skill(
        db, args["name"], args["content"], room_id=args.get("room_id"),
        activation_context=args.get("activation_context"),
    )
    return f"skill #{sid} saved"


@tool("selfmod_audit", "Self-modification audit log.",
      {"room_id": {"type": "integer"}})
def selfmod_audit(db, args):
    return _j(selfmod_mod.audit_log(db, args.get("room_id"))[:20])


@tool(
    "selfmod_revert", "Revert a self-modification by audit id.",
    {"audit_id": {"type": "integer", "required": True}},
)
def selfmod_revert(db, args):
    try:
        okay = selfmod_mod.revert_modification(db, int(args["audit_id"]))
    except selfmod_mod.SelfModError as e:
        return str(e)
    return "reverted" if okay else "nothing to revert"


# ---- inbox / messaging / escalations ----

@tool(
    "inbox_unread", "Unread inter-room messages for a room.",
    {"room_id": {"type": "integer", "required": True}},
)
def inbox_unread(db, args):
    return _j(messages_mod.unread_messages(db, int(args["room_id"])))


@tool(
    "message_send", "Send a message from one room to another.",
    {"from_room_id": {"type": "integer", "required": True},
     "to_room_id": {"type": "integer", "required": True},
     "subject": {"type": "string"},
     "body": {"type": "string", "required": True}},
)
def message_send(db, args):
    messages_mod.send_room_message(
        db, int(args["from_room_id"]), int(args["to_room_id"]),
        args.get("subject", ""), args["body"],
    )
    return "sent"


@tool("escalation_list", "Pending keeper escalations.",
      {"room_id": {"type": "integer"}})
def escalation_list(db, args):
    return _j(escalations_mod.pending_escalations(
        db, args.get("room_id")
    ))


@tool(
    "escalation_answer", "Answer an escalation as the keeper.",
    {"escalation_id": {"type": "integer", "required": True},
     "answer": {"type": "string", "required": True}},
)
def escalation_answer(db, args):
    escalations_mod.answer_escalation(
        db, int(args["escalation_id"]), args["answer"]
    )
    return "answered"


# ---- wallet / wip / settings / resources ----

@tool(
    "wallet_info", "Room wallet address + recent transactions.",
    {"room_id": {"type": "integer", "required": True}},
)
def wallet_info(db, args):
    w = wallet_mod.get_room_wallet(db, int(args["room_id"]))
    if w is None:
        return "no wallet"
    return _j({
        "address": w["address"], "chain": w["chain"],
        "transactions": wallet_mod.list_transactions(db, w["id"])[:5],
    })


@tool(
    "wip_save", "Save a worker's work-in-progress note.",
    {"worker_id": {"type": "integer", "required": True},
     "note": {"type": "string", "required": True}},
)
def wip_save(db, args):
    workers_mod.save_wip(db, int(args["worker_id"]), args["note"])
    return "WIP saved"


@tool("setting_get", "Read a settings key.",
      {"key": {"type": "string", "required": True}})
def setting_get(db, args):
    v = messages_mod.get_setting(db, args["key"])
    return v if v is not None else "(unset)"


@tool(
    "setting_set", "Write a settings key.",
    {"key": {"type": "string", "required": True},
     "value": {"type": "string", "required": True}},
)
def setting_set(db, args):
    messages_mod.set_setting(db, args["key"], args["value"])
    return f"{args['key']} updated"


@tool("system_resources", "Host CPU/memory/disk + TPU availability.")
def system_resources(db, args):
    disk = shutil.disk_usage("/")
    out = {
        "platform": platform.platform(),
        "cpus": __import__("os").cpu_count(),
        "disk_free_gb": round(disk.free / 1e9, 1),
    }
    try:
        with open("/proc/meminfo") as f:
            mem = dict(
                line.split(":")[:2] for line in f.read().splitlines()
                if ":" in line
            )
        out["mem_total"] = mem.get("MemTotal", "?").strip()
        out["mem_available"] = mem.get("MemAvailable", "?").strip()
    except OSError:
        pass
    return _j(out)


# ---- templates / watches / identity / web / payments ----

@tool("template_list", "Available room and worker templates.")
def template_list(db, args):
    from ..core.templates import ROOM_TEMPLATES, WORKER_TEMPLATES

    return _j({
        "rooms": [
            {"key": t.key, "name": t.name, "goal": t.goal}
            for t in ROOM_TEMPLATES.values()
        ],
        "workers": [
            {"key": t.key, "name": t.name, "role": t.role,
             "description": t.description}
            for t in WORKER_TEMPLATES.values()
        ],
    })


@tool(
    "template_instantiate", "Create a room from a template.",
    {"template": {"type": "string", "required": True},
     "name": {"type": "string"}},
)
def template_instantiate(db, args):
    from ..core.templates import instantiate_room_template

    try:
        room = instantiate_room_template(
            db, args["template"], name=args.get("name")
        )
    except KeyError as e:
        return str(e.args[0])  # str(KeyError) wraps in quotes
    return f"room #{room['id']} '{room['name']}' created from template"


@tool(
    "watch_create",
    "Watch a file/directory; when it changes, run the action prompt as "
    "a one-time task.",
    {"path": {"type": "string", "required": True},
     "action_prompt": {"type": "string", "required": True},
     "room_id": {"type": "integer"}},
)
def watch_create(db, args):
    from ..core.watches import create_watch

    try:
        wid = create_watch(
            db, args["path"], args["action_prompt"],
            room_id=args.get("room_id"),
        )
    except ValueError as e:
        return str(e)
    return f"watch #{wid} created"


@tool("watch_list", "List file watches.", {"room_id": {"type": "integer"}})
def watch_list(db, args):
    from ..core.watches import list_watches

    return _j(list_watches(db, args.get("room_id")))


@tool(
    "identity_info", "Room's on-chain identity status (ERC-8004).",
    {"room_id": {"type": "integer", "required": True}},
)
def identity_info(db, args):
    from ..core.identity import get_identity

    ident = get_identity(db, int(args["room_id"]))
    return _j(ident) if ident else "room has no wallet"


@tool(
    "web_fetch", "Fetch a URL and return readable text.",
    {"url": {"type": "string", "required": True}},
)
def mcp_web_fetch(db, args):
    from ..core.web_tools import web_fetch

    return web_fetch(args["url"])


@tool(
    "web_search", "Search the web (returns titles/urls/snippets).",
    {"query": {"type": "string", "required": True}},
)
def mcp_web_search(db, args):
    from ..core.web_tools import web_search

    return web_search(args["query"])


@tool(
    "payment_audit", "Audit a room's wallet transaction history.",
    {"room_id": {"type": "integer", "required": True}},
)
def payment_audit(db, args):
    from ..core import wallet as wallet_mod

    w = wallet_mod.get_room_wallet(db, int(args["room_id"]))
    if w is None:
        return "room has no wallet"
    txs = wallet_mod.list_transactions(db, w["id"])
    total_out = sum(
        float(t["amount"]) for t in txs
        if t["type"] == "send" and t["status"] == "confirmed"
    )
    return _j({
        "address": w["address"],
        "transaction_count": len(txs),
        "confirmed_outbound_total": total_out,
        "transactions": txs[:20],
    })
