"""MCP stdio server (reference: src/mcp/server.ts): JSON-RPC 2.0 over
stdin/stdout implementing the Model Context Protocol tool surface —
initialize, tools/list, tools/call — against the shared SQLite file
(ROOM_TPU_DB_PATH), in its own process alongside the API server."""

from __future__ import annotations

import json
import sys
from typing import Any, Optional, TextIO

from .. import __version__
from ..db import Database
from ..utils import knobs
from .tools import TOOLS

PROTOCOL_VERSION = "2024-11-05"


def _tool_index():
    return {name: (schema, fn) for name, _, schema, fn in TOOLS}


def tools_list_payload() -> list[dict]:
    return [
        {"name": name, "description": desc, "inputSchema": schema}
        for name, desc, schema, _ in TOOLS
    ]


class McpServer:
    def __init__(self, db: Optional[Database] = None) -> None:
        if db is None:
            path = knobs.get_str("ROOM_TPU_DB_PATH")
            if not path:
                from ..db.database import default_db_path

                path = default_db_path()
            db = Database(path)
        self.db = db
        self._tools = _tool_index()

    def handle(self, message: dict) -> Optional[dict]:
        """Process one JSON-RPC message; returns the response (None for
        notifications)."""
        msg_id = message.get("id")
        method = message.get("method", "")
        params = message.get("params") or {}

        if method == "initialize":
            return self._result(msg_id, {
                "protocolVersion": PROTOCOL_VERSION,
                "capabilities": {"tools": {}},
                "serverInfo": {"name": "room-tpu",
                               "version": __version__},
            })
        if method == "notifications/initialized":
            return None
        if method == "ping":
            return self._result(msg_id, {})
        if method == "tools/list":
            return self._result(msg_id, {"tools": tools_list_payload()})
        if method == "tools/call":
            name = params.get("name", "")
            args = params.get("arguments") or {}
            entry = self._tools.get(name)
            if entry is None:
                return self._error(msg_id, -32602,
                                   f"unknown tool {name!r}")
            schema, fn = entry
            missing = [
                k for k in schema.get("required", []) if k not in args
            ]
            if missing:
                return self._result(msg_id, {
                    "content": [{"type": "text",
                                 "text": f"missing required arguments: "
                                         f"{missing}"}],
                    "isError": True,
                })
            try:
                text = fn(self.db, args)
                return self._result(msg_id, {
                    "content": [{"type": "text", "text": text}],
                })
            except Exception as e:
                return self._result(msg_id, {
                    "content": [{"type": "text",
                                 "text": f"{type(e).__name__}: {e}"}],
                    "isError": True,
                })
        if msg_id is None:
            return None  # unknown notification
        return self._error(msg_id, -32601, f"unknown method {method!r}")

    @staticmethod
    def _result(msg_id: Any, result: dict) -> dict:
        return {"jsonrpc": "2.0", "id": msg_id, "result": result}

    @staticmethod
    def _error(msg_id: Any, code: int, message: str) -> dict:
        return {
            "jsonrpc": "2.0", "id": msg_id,
            "error": {"code": code, "message": message},
        }

    def serve(self, stdin: TextIO, stdout: TextIO) -> int:
        """Line-delimited JSON-RPC loop until EOF."""
        for line in stdin:
            line = line.strip()
            if not line:
                continue
            try:
                message = json.loads(line)
            except json.JSONDecodeError:
                continue
            response = self.handle(message)
            if response is not None:
                stdout.write(json.dumps(response) + "\n")
                stdout.flush()
        return 0


def run_stdio_server() -> int:
    server = McpServer()
    try:
        return server.serve(sys.stdin, sys.stdout)
    finally:
        server.db.close()
