from .step import TrainState, init_train_state, make_train_step

__all__ = ["TrainState", "init_train_state", "make_train_step"]
