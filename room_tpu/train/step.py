"""Training step for the decoder family.

The reference never trains (inference-only engine), but the framework
supports fine-tuning its served models: causal-LM loss, optax AdamW,
gradients and optimizer state sharded with the same PartitionSpec rules as
the parameters (optimizer moments inherit the param specs). Remat is
applied per-layer via jax.checkpoint to trade FLOPs for HBM.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax

from ..models import qwen3
from ..models.config import DecoderConfig

Params = dict[str, Any]


@jax.tree_util.register_dataclass
@dataclass
class TrainState:
    params: Params
    opt_state: Any
    step: jax.Array


def init_train_state(
    cfg: DecoderConfig,
    key: jax.Array,
    learning_rate: float = 1e-4,
    weight_decay: float = 0.01,
) -> tuple[TrainState, optax.GradientTransformation]:
    params = qwen3.init_params(cfg, key)
    tx = optax.adamw(learning_rate, weight_decay=weight_decay)
    opt_state = tx.init(params)
    return TrainState(params, opt_state, jnp.zeros((), jnp.int32)), tx


def causal_lm_loss(
    params: Params, cfg: DecoderConfig, tokens: jax.Array,
    loss_mask: jax.Array,
) -> jax.Array:
    """Next-token cross-entropy in fp32. tokens [B, S]; loss_mask [B, S]
    marks positions whose *prediction* counts (shifted internally)."""
    logits, _ = qwen3.forward(params, cfg, tokens)
    logits = logits[:, :-1].astype(jnp.float32)
    targets = tokens[:, 1:]
    mask = loss_mask[:, 1:].astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return (nll * mask).sum() / jnp.maximum(mask.sum(), 1.0)


def make_train_step(
    cfg: DecoderConfig, tx: optax.GradientTransformation
) -> Callable:
    """Returns train_step(state, tokens, loss_mask) -> (state, loss),
    suitable for jit with donated state."""

    def train_step(state: TrainState, tokens, loss_mask):
        loss, grads = jax.value_and_grad(causal_lm_loss)(
            state.params, cfg, tokens, loss_mask
        )
        updates, new_opt = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        return TrainState(new_params, new_opt, state.step + 1), loss

    return train_step
