"""`python -m room_tpu <cmd>` — the spawn form MCP auto-registration
writes into client configs (reference ships a server.js path instead)."""

from .cli.main import main

raise SystemExit(main())
