"""roomlint checker 3 — fault-point coverage, and 4 — FaultError
dispatch discipline.

Checker 3 cross-checks three surfaces that must stay in lockstep:

- ``room_tpu/serving/faults.py`` ``FAULT_POINTS`` (the registry of
  injectable failure modes),
- the test suite (EVERY test file counts, not just ``test_chaos_*`` —
  ``decode_window`` lives in ``test_decode_pipeline.py`` and
  ``shutdown_io`` in ``test_lifecycle.py``; the mapping is discovered
  by scanning ``tests/`` for ``faults.inject("<point>"...)`` arms),
- the ``docs/chaos.md`` fault table.

Rules: ``fault-point-untested`` (no test arms it),
``fault-point-undocumented`` (no chaos.md row),
``fault-point-unknown`` (code/tests/docs name a point the registry
does not define).

Checker 4 (FaultError dispatch) lives in ``dispatch_checker``.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Optional

from .common import SourceCache, SourceFile, Violation

FAULTS_MODULE = os.path.join("room_tpu", "serving", "faults.py")

# inject("x"...) / maybe_fail("x") / maybe_delay("x") / is_active("x")
_ARM_RE = re.compile(
    r"(?:inject|maybe_fail|maybe_delay|is_active|fired)\(\s*"
    r"['\"]([a-z_]+)['\"]"
)
# entries inside a ROOM_TPU_FAULTS env spec string: name[:k=v...]
_ENV_SPEC_RE = re.compile(r"ROOM_TPU_FAULTS[^\n]*?['\"]([a-z_:,;=.0-9 ]+)['\"]")
_DOC_ROW_RE = re.compile(r"^\| `([a-z_]+)` \|")


def load_fault_points(repo_root: str,
                      cache: Optional[SourceCache] = None
                      ) -> tuple[str, ...]:
    """Parse FAULT_POINTS out of faults.py without importing the
    serving package (which drags in jax)."""
    path = os.path.join(repo_root, FAULTS_MODULE)
    if cache is None:
        cache = SourceCache(repo_root)
    tree = cache.tree(FAULTS_MODULE)
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and \
                        tgt.id == "FAULT_POINTS":
                    return tuple(ast.literal_eval(node.value))
    raise RuntimeError(f"FAULT_POINTS not found in {path}")


def _points_mentioned(text: str) -> set[str]:
    found = set(_ARM_RE.findall(text))
    for spec in _ENV_SPEC_RE.findall(text):
        for part in spec.split(";"):
            name = part.strip().partition(":")[0].strip()
            if name:
                found.add(name)
    return found


def check_coverage(
    repo_root: str,
    tests_dir: str = "tests",
    doc_path: str = os.path.join("docs", "chaos.md"),
    cache: Optional[SourceCache] = None,
) -> list[Violation]:
    if cache is None:
        cache = SourceCache(repo_root)
    points = load_fault_points(repo_root, cache)
    out: list[Violation] = []

    # ---- test mapping: point -> test files that arm it ---------------
    tested: dict[str, list[str]] = {p: [] for p in points}
    unknown_in_tests: dict[str, str] = {}
    tests_abs = os.path.join(repo_root, tests_dir)
    for dirpath, dirnames, filenames in os.walk(tests_abs):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"
                       and d != "fixtures"]
        for fname in sorted(filenames):
            if not (fname.startswith("test_") and fname.endswith(".py")):
                continue
            fpath = os.path.join(dirpath, fname)
            text = cache.text(fpath)
            rel = os.path.relpath(fpath, repo_root)
            for name in _points_mentioned(text):
                if name in tested:
                    tested[name].append(rel)
                elif name not in ("no_such_point",):
                    # tests deliberately probing unknown-point errors
                    # name themselves no_such_point
                    unknown_in_tests.setdefault(name, rel)
    for name, files in tested.items():
        if not files:
            out.append(Violation(
                "fault-point-untested", FAULTS_MODULE, 1,
                f"fault point {name!r} is never armed by any test "
                f"under {tests_dir}/ — every FAULT_POINTS entry needs "
                "a recovery test",
            ))
    for name, rel in sorted(unknown_in_tests.items()):
        out.append(Violation(
            "fault-point-unknown", rel, 1,
            f"test arms unknown fault point {name!r} "
            f"(known: {', '.join(points)})",
        ))

    # ---- docs/chaos.md fault table -----------------------------------
    doc_abs = os.path.join(repo_root, doc_path)
    documented: dict[str, int] = {}
    if os.path.exists(doc_abs):
        for lineno, line in enumerate(
                cache.text(doc_abs).split("\n"), 1):
            m = _DOC_ROW_RE.match(line)
            if m:
                documented[m.group(1)] = lineno
    for name in points:
        if name not in documented:
            out.append(Violation(
                "fault-point-undocumented", doc_path, 1,
                f"fault point {name!r} missing from the {doc_path} "
                "fault table",
            ))
    for name, lineno in documented.items():
        if name not in points:
            out.append(Violation(
                "fault-point-unknown", doc_path, lineno,
                f"{doc_path} documents fault point {name!r} but "
                "faults.FAULT_POINTS does not define it",
            ))
    return out


def check_arm_sites(src: SourceFile, points: tuple[str, ...]
                    ) -> list[Violation]:
    """Library-side arm/check sites naming an unknown point (a typo'd
    maybe_fail would make a fault path silently untestable)."""
    out: list[Violation] = []
    if os.path.normpath(src.path).endswith(FAULTS_MODULE):
        return out
    for node in ast.walk(src.tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in ("maybe_fail", "maybe_delay",
                                       "inject", "is_active", "fired")
                and node.args):
            continue
        arg = node.args[0]
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str) \
                and arg.value not in points:
            v = src.violation(
                "fault-point-unknown", node,
                f"arms unknown fault point {arg.value!r}",
            )
            if v:
                out.append(v)
    return out
