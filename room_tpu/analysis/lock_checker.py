"""roomlint checker 2 — lock / stats / host-sync discipline.

The engine's counter invariant: ``self._stats[...]`` mutates ONLY
inside ``_bump`` (which takes the engine lock); every other mutation
races the ``stats()`` snapshot. And the decode pipeline's reason to
exist (docs/serving.md; arXiv 2407.09111 on host-sync overhead) dies
the moment someone blocks on the device inside the engine lock or
inside the dispatch window, so the sync primitives are flagged there.

Rules:

``stats-outside-bump``
    Assignment / augmented assignment to a ``self._stats[...]``
    subscript in any function not named ``_bump``.
``sync-under-lock``
    A blocking host-device sync call (``block_until_ready``,
    ``jax.device_get``, ``np.asarray`` / ``numpy.asarray``) lexically
    inside a ``with self._lock:`` (or ``*_lock``) block.
``sync-in-dispatch-window``
    The same sync calls inside a function marked
    ``# roomlint: region=dispatch-window`` — code that runs between
    dispatching a decode window and draining it, where a host sync
    serializes the pipeline. The drain itself is the one sanctioned
    materialization point and is simply not marked.
"""

from __future__ import annotations

import ast

from .common import SourceFile, Violation

_SYNC_ATTRS = ("block_until_ready", "device_get")
_ASARRAY_MODULES = ("np", "numpy", "jnp")


def _sync_call_name(node: ast.AST) -> str:
    """Non-empty description when the node is a blocking-sync call."""
    if not isinstance(node, ast.Call):
        return ""
    fn = node.func
    if isinstance(fn, ast.Attribute):
        if fn.attr in _SYNC_ATTRS:
            return ast.unparse(fn)
        if fn.attr == "asarray" and isinstance(fn.value, ast.Name) \
                and fn.value.id in _ASARRAY_MODULES:
            # jnp.asarray is device-side and lazy; only numpy blocks
            if fn.value.id == "jnp":
                return ""
            return ast.unparse(fn)
    return ""


def _is_lock_with(node: ast.With) -> bool:
    for item in node.items:
        try:
            src = ast.unparse(item.context_expr)
        except Exception:
            continue
        if "_lock" in src and not src.startswith("open("):
            return True
    return False


def check_source(src: SourceFile) -> list[Violation]:
    out: list[Violation] = []

    # ---- stats-outside-bump ------------------------------------------
    for node in ast.walk(src.tree):
        targets: list[ast.expr] = []
        if isinstance(node, ast.AugAssign):
            targets = [node.target]
        elif isinstance(node, ast.Assign):
            targets = list(node.targets)
        for tgt in targets:
            if isinstance(tgt, ast.Subscript) and \
                    isinstance(tgt.value, ast.Attribute) and \
                    tgt.value.attr == "_stats" and \
                    isinstance(tgt.value.value, ast.Name) and \
                    tgt.value.value.id == "self":
                qual = src.qualname_at(node.lineno)
                if qual.split(".")[-1] == "_bump":
                    continue
                v = src.violation(
                    "stats-outside-bump", node,
                    "direct self._stats[...] mutation outside _bump "
                    "races the stats() snapshot — route through "
                    "self._bump()",
                )
                if v:
                    out.append(v)

    # ---- sync-under-lock ---------------------------------------------
    for node in ast.walk(src.tree):
        if isinstance(node, ast.With) and _is_lock_with(node):
            for inner in ast.walk(node):
                name = _sync_call_name(inner)
                if name:
                    v = src.violation(
                        "sync-under-lock", inner,
                        f"blocking host-device sync {name}() while "
                        "holding a lock stalls every reader thread",
                    )
                    if v:
                        out.append(v)

    # ---- sync-in-dispatch-window -------------------------------------
    for start, end, qual in src.region_functions("dispatch-window"):
        fn_node = next(
            (n for n in ast.walk(src.tree)
             if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
             and n.lineno == start), None,
        )
        if fn_node is None:
            continue
        for inner in ast.walk(fn_node):
            name = _sync_call_name(inner)
            if name:
                v = src.violation(
                    "sync-in-dispatch-window", inner,
                    f"blocking host-device sync {name}() between "
                    "dispatch and drain serializes the decode "
                    "pipeline (docs/serving.md)",
                )
                if v:
                    out.append(v)
    return out
