"""roomlint checker 7 — fault-point fuzz coverage.

Every ``faults.FAULT_POINTS`` entry must be reachable by the schedule
fuzzer (docs/chaosfuzz.md): either weighted in
``chaos/fuzz.py``'s ``FUZZ_WEIGHTS`` or explicitly excluded in
``FUZZ_EXCLUDED`` with a reason naming where the point IS covered.
Same lockstep pattern as the trace-coverage checker — a new fault
point cannot ship invisible to the fuzzer:

- ``fault-point-unfuzzed`` — a FAULT_POINTS entry in neither
  FUZZ_WEIGHTS nor FUZZ_EXCLUDED (the fuzzer would never compose it
  into a schedule, so its interactions go untested);
- ``fuzz-weight-unknown`` — a FUZZ_WEIGHTS / FUZZ_EXCLUDED key naming
  a point the registry does not define (a typo'd weight silently
  fuzzes nothing);
- ``fuzz-exclusion-overlap`` — a point both weighted and excluded
  (the exclusion reason is dead text and the point still fuzzes).

Both files are parsed with ``ast`` — no import of the chaos package.
"""

from __future__ import annotations

import ast
import os
from typing import Optional

from .common import SourceCache, Violation
from .fault_checker import load_fault_points

FUZZ_MODULE = os.path.join("room_tpu", "chaos", "fuzz.py")


def _load_literal(repo_root: str, name: str,
                  cache: Optional[SourceCache] = None) -> dict:
    """Parse a module-level dict literal out of fuzz.py without
    importing it."""
    if cache is None:
        cache = SourceCache(repo_root)
    tree = cache.tree(FUZZ_MODULE)
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and tgt.id == name:
                    return dict(ast.literal_eval(node.value))
    raise RuntimeError(f"{name} not found in {FUZZ_MODULE}")


def check_fuzz_coverage(
    repo_root: str, cache: Optional[SourceCache] = None
) -> list[Violation]:
    if cache is None:
        cache = SourceCache(repo_root)
    points = load_fault_points(repo_root, cache)
    out: list[Violation] = []
    try:
        weights = _load_literal(repo_root, "FUZZ_WEIGHTS", cache)
        excluded = _load_literal(repo_root, "FUZZ_EXCLUDED", cache)
    except (OSError, RuntimeError, SyntaxError, ValueError) as e:
        return [Violation(
            "fault-point-unfuzzed", FUZZ_MODULE, 1,
            f"cannot load fuzz weight tables: {e}",
        )]
    for name in points:
        if name not in weights and name not in excluded:
            out.append(Violation(
                "fault-point-unfuzzed", FUZZ_MODULE, 1,
                f"fault point {name!r} in neither FUZZ_WEIGHTS nor "
                "FUZZ_EXCLUDED — every fault point must be "
                "schedule-fuzzable or excluded with a reason naming "
                "its alternative coverage (docs/chaosfuzz.md)",
            ))
    for name in list(weights) + list(excluded):
        if name not in points:
            out.append(Violation(
                "fuzz-weight-unknown", FUZZ_MODULE, 1,
                f"fuzz table names unknown fault point {name!r} "
                f"(known: {', '.join(points)})",
            ))
    for name in weights:
        if name in excluded:
            out.append(Violation(
                "fuzz-exclusion-overlap", FUZZ_MODULE, 1,
                f"fault point {name!r} is both weighted and excluded "
                "— drop one: an excluded point must not fuzz",
            ))
    for name, reason in excluded.items():
        if not str(reason).strip():
            out.append(Violation(
                "fault-point-unfuzzed", FUZZ_MODULE, 1,
                f"FUZZ_EXCLUDED[{name!r}] has an empty reason — say "
                "where the point IS covered",
            ))
    return out
