"""roomlint checker 4 — FaultError dispatch discipline.

``FaultError.point`` names the fault point that fired; recovery paths
that scope differently per point (decode_window fails only the
window's turns; decode_step escalates to the crash supervisor) must
dispatch on it (faults.py docstring). Matching on message TEXT —
``"decode_window" in str(e)`` — works until anyone rewords the
message, then the recovery path silently widens or vanishes.

Rule ``fault-substring-dispatch``: inside an ``except`` handler, a
string-membership test whose literal is a fault-point name (or
contains "injected") against an expression derived from the caught
exception.
"""

from __future__ import annotations

import ast

from .common import SourceFile, Violation


def _mentions_exception(node: ast.AST, exc_names: set[str]) -> bool:
    for inner in ast.walk(node):
        if isinstance(inner, ast.Name) and inner.id in exc_names:
            return True
    return False


def check_dispatch(src: SourceFile, points: tuple[str, ...]
                   ) -> list[Violation]:
    out: list[Violation] = []
    point_set = set(points)

    class Visitor(ast.NodeVisitor):
        def visit_ExceptHandler(self, node: ast.ExceptHandler) -> None:
            exc_names = {node.name} if node.name else set()
            for inner in ast.walk(node):
                if not (isinstance(inner, ast.Compare)
                        and len(inner.ops) == 1
                        and isinstance(inner.ops[0], (ast.In, ast.NotIn))
                        and isinstance(inner.left, ast.Constant)
                        and isinstance(inner.left.value, str)):
                    continue
                lit = inner.left.value
                if not (lit in point_set or "injected" in lit):
                    continue
                cmp = inner.comparators[0]
                texty = "str(" in ast.unparse(cmp) or \
                    _mentions_exception(cmp, exc_names)
                if texty:
                    v = src.violation(
                        "fault-substring-dispatch", inner,
                        f"dispatching on fault message substring "
                        f"{lit!r}; match the typed FaultError.point "
                        "attribute instead "
                        "(getattr(e, 'point', None))",
                    )
                    if v:
                        out.append(v)
            self.generic_visit(node)

    Visitor().visit(src.tree)
    return out
