"""roomlint checker 5 — fault-point trace/telemetry coverage.

Every ``faults.FAULT_POINTS`` entry must be visible to the
observability layer (docs/observability.md): a firing emits both a
telemetry counter and a flight-recorder trace event, routed through
``serving/trace.py``'s ``FAULT_EVENTS`` mapping. This cross-check
keeps the three surfaces in lockstep — the same pattern as the
fault-point coverage checker (tests + chaos.md table):

- ``fault-point-untraced`` — a FAULT_POINTS entry missing from
  ``trace.FAULT_EVENTS`` (a new fault point would fire invisibly:
  no span event, no counter name for dashboards to alert on);
- ``fault-trace-unknown`` — a FAULT_EVENTS key naming a point the
  registry does not define (a typo'd mapping silently never fires);
- ``fault-point-unwired`` — ``faults.should_fire`` no longer routes
  firings through BOTH ``_telemetry_count`` and ``_trace_event``
  (the central wiring the per-point mapping relies on).

Both files are parsed with ``ast`` — no import of the serving package
(which drags jax), so the lint gate stays stdlib-only.
"""

from __future__ import annotations

import ast
import os
from typing import Optional

from .common import SourceCache, Violation
from .fault_checker import FAULTS_MODULE, load_fault_points

TRACE_MODULE = os.path.join("room_tpu", "serving", "trace.py")


def load_fault_events(repo_root: str,
                      cache: Optional[SourceCache] = None
                      ) -> dict[str, str]:
    """Parse FAULT_EVENTS out of trace.py without importing it."""
    path = os.path.join(repo_root, TRACE_MODULE)
    if cache is None:
        cache = SourceCache(repo_root)
    tree = cache.tree(TRACE_MODULE)
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and \
                        tgt.id == "FAULT_EVENTS":
                    return dict(ast.literal_eval(node.value))
    raise RuntimeError(f"FAULT_EVENTS not found in {path}")


def _should_fire_calls(repo_root: str,
                       cache: Optional[SourceCache] = None) -> set[str]:
    """Function names called inside faults.should_fire (the central
    firing path every armed point funnels through)."""
    if cache is None:
        cache = SourceCache(repo_root)
    tree = cache.tree(FAULTS_MODULE)
    called: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and \
                node.name == "should_fire":
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call) and \
                        isinstance(sub.func, ast.Name):
                    called.add(sub.func.id)
    return called


def check_fault_trace_coverage(
    repo_root: str, cache: Optional[SourceCache] = None
) -> list[Violation]:
    if cache is None:
        cache = SourceCache(repo_root)
    points = load_fault_points(repo_root, cache)
    out: list[Violation] = []
    try:
        events = load_fault_events(repo_root, cache)
    except (OSError, RuntimeError, SyntaxError) as e:
        return [Violation(
            "fault-point-untraced", TRACE_MODULE, 1,
            f"cannot load trace.FAULT_EVENTS: {e}",
        )]
    for name in points:
        if name not in events:
            out.append(Violation(
                "fault-point-untraced", TRACE_MODULE, 1,
                f"fault point {name!r} missing from trace.FAULT_EVENTS"
                " — every FAULT_POINTS entry must map to the trace "
                "event / telemetry counter its firing emits",
            ))
    for name in events:
        if name not in points:
            out.append(Violation(
                "fault-trace-unknown", TRACE_MODULE, 1,
                f"trace.FAULT_EVENTS maps unknown fault point "
                f"{name!r} (known: {', '.join(points)})",
            ))
    called = _should_fire_calls(repo_root, cache)
    for fn in ("_telemetry_count", "_trace_event"):
        if fn not in called:
            out.append(Violation(
                "fault-point-unwired", FAULTS_MODULE, 1,
                f"faults.should_fire no longer calls {fn} — firings "
                "must reach both the telemetry counter and the "
                "flight recorder",
            ))
    return out
