"""roomlint checker 1 — knob discipline.

Rules:

``knob-raw-env-read``
    A raw ``os.environ`` / ``os.getenv`` read of a ``ROOM_TPU_*`` name
    (including f-string-built names) anywhere under ``room_tpu/``
    except the registry module itself. All knob reads go through
    ``room_tpu.utils.knobs``.
``knob-unregistered``
    A ``knobs.get_*`` / ``knobs.is_set`` / ``knobs.get_dynamic`` call
    whose literal name/pattern is not in the registry.
``knob-undocumented`` / ``knob-doc-drift`` / ``knob-unknown-doc``
    Registry vs generated ``docs/knobs.md`` cross-check: a registered
    knob missing from the doc, a default cell that disagrees with the
    registry (hand-edit drift), or a documented knob the registry does
    not know. ``--write-docs`` regenerates the file from the registry.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Optional

from .common import SourceFile, Violation

# the one module allowed to touch os.environ for ROOM_TPU_* names
REGISTRY_MODULE = os.path.join("room_tpu", "utils", "knobs.py")

_GETTERS = (
    "get_raw", "get_str", "get_int", "get_float", "get_bool",
    "is_set", "resolve_default",
)


def _is_environ(node: ast.AST) -> bool:
    if isinstance(node, ast.Attribute) and node.attr == "environ":
        return True
    return isinstance(node, ast.Name) and node.id == "environ"


def _env_name_literal(node: ast.AST) -> Optional[str]:
    """The ROOM_TPU_* name an expression mentions, if any: a str
    constant, an f-string with a ROOM_TPU_ chunk, or a concat/format
    whose source text carries the prefix."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value if node.value.startswith("ROOM_TPU_") else None
    if isinstance(node, ast.JoinedStr):
        for part in node.values:
            if isinstance(part, ast.Constant) and \
                    isinstance(part.value, str) and \
                    "ROOM_TPU_" in part.value:
                return part.value.strip("_") + "{...}"
    try:
        src = ast.unparse(node)
    except Exception:
        return None
    return src if "ROOM_TPU_" in src else None


def _knobs_registry():
    from room_tpu.utils.knobs import DYNAMIC, REGISTRY

    return REGISTRY, DYNAMIC


def check_source(src: SourceFile) -> list[Violation]:
    out: list[Violation] = []
    if os.path.normpath(src.path).endswith(REGISTRY_MODULE):
        return out
    registry, dynamic = _knobs_registry()

    for node in ast.walk(src.tree):
        # -- raw environ reads -------------------------------------
        hit_name: Optional[str] = None
        if isinstance(node, ast.Call):
            fn = node.func
            if isinstance(fn, ast.Attribute) and \
                    fn.attr in ("get", "pop", "setdefault") and \
                    _is_environ(fn.value) and node.args:
                hit_name = _env_name_literal(node.args[0])
            elif isinstance(fn, ast.Attribute) and \
                    fn.attr == "getenv" and node.args:
                hit_name = _env_name_literal(node.args[0])
        elif isinstance(node, ast.Subscript) and \
                _is_environ(node.value):
            hit_name = _env_name_literal(node.slice)
        elif isinstance(node, ast.Compare) and \
                len(node.ops) == 1 and \
                isinstance(node.ops[0], (ast.In, ast.NotIn)) and \
                _is_environ(node.comparators[0]):
            hit_name = _env_name_literal(node.left)
        if hit_name is not None:
            v = src.violation(
                "knob-raw-env-read", node,
                f"raw environ read of {hit_name!r}; go through "
                "room_tpu.utils.knobs",
            )
            if v:
                out.append(v)
            continue

        # -- registry lookups with unknown names --------------------
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                isinstance(node.func.value, ast.Name) and \
                node.func.value.id == "knobs" and node.args:
            name = node.args[0]
            if not (isinstance(name, ast.Constant)
                    and isinstance(name.value, str)):
                continue
            if node.func.attr in _GETTERS and \
                    name.value.startswith("ROOM_TPU_") and \
                    name.value not in registry:
                v = src.violation(
                    "knob-unregistered", node,
                    f"knob {name.value!r} is not registered in "
                    "room_tpu/utils/knobs.py",
                )
                if v:
                    out.append(v)
            elif node.func.attr == "get_dynamic" and \
                    name.value not in dynamic:
                v = src.violation(
                    "knob-unregistered", node,
                    f"dynamic knob family {name.value!r} is not "
                    "registered in room_tpu/utils/knobs.py",
                )
                if v:
                    out.append(v)
    return out


# ---- registry <-> docs/knobs.md cross-check ---------------------------

_DOC_ROW = re.compile(r"^\| `([A-Z0-9_{}]+)` \| \S+ \| (.*?) \|")


def _doc_default_cell(default: Optional[str]) -> str:
    if default is None:
        return "_unset_"
    return f"`{default}`" if default != "" else "`\"\"`"


def check_docs(doc_path: str) -> list[Violation]:
    registry, dynamic = _knobs_registry()
    known = dict(registry)
    known.update(dynamic)
    out: list[Violation] = []
    if not os.path.exists(doc_path):
        return [Violation(
            "knob-undocumented", doc_path, 1,
            "docs/knobs.md missing — run "
            "`python -m room_tpu.analysis --write-docs`",
        )]
    documented: dict[str, tuple[int, str]] = {}
    with open(doc_path, encoding="utf-8") as fh:
        for lineno, line in enumerate(fh, 1):
            m = _DOC_ROW.match(line)
            if m:
                documented[m.group(1)] = (lineno, m.group(2))
    for name, knob in known.items():
        if name not in documented:
            out.append(Violation(
                "knob-undocumented", doc_path, 1,
                f"registered knob {name} missing from docs/knobs.md "
                "(regenerate with --write-docs)",
            ))
            continue
        lineno, cell = documented[name]
        if cell != _doc_default_cell(knob.default):
            out.append(Violation(
                "knob-doc-drift", doc_path, lineno,
                f"{name}: documented default {cell} != registry "
                f"default {_doc_default_cell(knob.default)} "
                "(regenerate with --write-docs)",
            ))
    for name, (lineno, _) in documented.items():
        if name not in known:
            out.append(Violation(
                "knob-unknown-doc", doc_path, lineno,
                f"docs/knobs.md documents {name} but the registry "
                "does not know it",
            ))
    return out
