"""Generator for ``docs/knobs.md`` — the knob reference is BUILT from
``room_tpu.utils.knobs``, never hand-edited. ``python -m
room_tpu.analysis --write-docs`` regenerates it; the CI lint job
regenerates and diffs, so a registry change that forgets the doc (or a
hand edit that drifts from the registry) fails the gate.
"""

from __future__ import annotations

from typing import Optional

_HEADER = """\
# ROOM_TPU_* configuration knobs

<!-- GENERATED FILE — do not edit by hand.
     Source of truth: room_tpu/utils/knobs.py.
     Regenerate: python -m room_tpu.analysis --write-docs -->

Every environment knob the engine, server, swarm runtime, and bench
harness read. Reads go through the central registry
(`room_tpu.utils.knobs`) — a raw `os.environ` read of this namespace
is a roomlint violation (`docs/static_analysis.md`).

A knob with a **provider default** follows the provider-on /
library-off convention: library constructors apply the plain default,
the production deployment path (`providers/tpu.ModelHost`) applies the
provider default. `ROOM_TPU_SPEC_TOKENS`, `ROOM_TPU_OFFLOAD`, and
`ROOM_TPU_LIFECYCLE` share this split.

Names containing `{...}` are dynamic families: the placeholder is
filled at runtime (e.g. `ROOM_TPU_MESH_{MODEL}` ->
`ROOM_TPU_MESH_QWEN3_30B_MOE`).
"""

_SCOPE_TITLES = (
    ("library", "Library (engine / serving / core)"),
    ("provider", "Provider deployment path"),
    ("server", "Server / HTTP / cloud"),
    ("swarm", "Swarm runtime"),
    ("bench", "Bench + tuning harness"),
    ("test-seam", "Test seams"),
)


def _default_cell(default: Optional[str]) -> str:
    if default is None:
        return "_unset_"
    return f"`{default}`" if default != "" else "`\"\"`"


def render() -> str:
    from room_tpu.utils.knobs import all_knobs

    knobs = all_knobs()
    lines = [_HEADER]
    for scope, title in _SCOPE_TITLES:
        rows = [k for k in knobs.values() if k.scope == scope]
        if not rows:
            continue
        lines.append(f"\n## {title}\n")
        lines.append("| knob | type | default | provider default | "
                     "description |")
        lines.append("|---|---|---|---|---|")
        for k in sorted(rows, key=lambda k: k.name):
            prov = _default_cell(k.provider_default) \
                if k.provider_default is not None else ""
            doc = k.doc
            if k.choices:
                opts = " \\| ".join(c if c else '""' for c in k.choices)
                doc = f"{doc} ({opts})"
            lines.append(
                f"| `{k.name}` | {k.type} | {_default_cell(k.default)} "
                f"| {prov} | {doc} |"
            )
    lines.append("")
    lines.append(f"\n_{len(knobs)} knobs registered._")
    lines.append("")
    return "\n".join(lines)


def write(path: str) -> None:
    with open(path, "w", encoding="utf-8") as fh:
        fh.write(render())


def is_fresh(path: str) -> bool:
    try:
        return open(path, encoding="utf-8").read() == render()
    except OSError:
        return False
