"""CLI for roomlint: ``python -m room_tpu.analysis`` (docs/static_analysis.md).

Exit codes: 0 clean, 1 unsuppressed violations (or stale docs with
--check-docs), 2 usage errors.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from . import KNOBS_DOC, knobs_doc, run_checks


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m room_tpu.analysis",
        description="roomlint — in-tree static analysis "
                    "(knob/lock/fault/dispatch discipline)",
    )
    ap.add_argument("paths", nargs="*",
                    help="files or dirs to scan (default: room_tpu/)")
    ap.add_argument("--repo-root", default=None,
                    help="repo root (default: cwd, or the checkout "
                         "containing this package)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output")
    ap.add_argument("--suppress", default=None,
                    help="suppression file (default: .roomlint.suppress)")
    ap.add_argument("--no-cross-checks", action="store_true",
                    help="skip repo-level fault-coverage and knob-docs "
                         "passes (per-file rules only)")
    ap.add_argument("--write-docs", action="store_true",
                    help="regenerate docs/knobs.md from the registry "
                         "and exit")
    ap.add_argument("--check-docs", action="store_true",
                    help="exit 1 if docs/knobs.md is stale w.r.t. the "
                         "registry")
    ap.add_argument("--graph", action="store_true",
                    help="emit the whole-program lock-acquisition "
                         "graph as DOT on stdout and exit")
    args = ap.parse_args(argv)

    root = args.repo_root
    if root is None:
        here = os.getcwd()
        if os.path.isdir(os.path.join(here, "room_tpu")):
            root = here
        else:
            root = os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__))
            ))
    doc_path = os.path.join(root, KNOBS_DOC)

    if args.graph:
        from . import DEFAULT_SCAN_ROOTS, lockmap
        from .common import iter_py_files

        facts = lockmap.collect_facts(iter_py_files(
            args.paths or DEFAULT_SCAN_ROOTS, root))
        sys.stdout.write(lockmap.render_dot(facts))
        return 0

    if args.write_docs:
        knobs_doc.write(doc_path)
        print(f"wrote {doc_path}")
        return 0
    if args.check_docs:
        if knobs_doc.is_fresh(doc_path):
            print("docs/knobs.md is in sync with the registry")
            return 0
        print("docs/knobs.md is STALE — run "
              "`python -m room_tpu.analysis --write-docs` and commit",
              file=sys.stderr)
        return 1

    active, suppressed = run_checks(
        root,
        roots=args.paths or None,
        suppress_path=args.suppress,
        cross_checks=not args.no_cross_checks,
    )
    if args.json:
        print(json.dumps({
            "violations": [vars(v) for v in active],
            "suppressed": len(suppressed),
        }, indent=2))
    else:
        for v in active:
            print(v.render())
        tail = (f"roomlint: {len(active)} violation(s), "
                f"{len(suppressed)} suppressed")
        print(tail if active else
              f"roomlint: clean ({len(suppressed)} suppressed)")
    return 1 if active else 0


if __name__ == "__main__":
    sys.exit(main())
