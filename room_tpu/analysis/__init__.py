"""roomlint — the in-tree static-analysis suite (docs/static_analysis.md).

Seven AST-based checkers keep the invariants that used to live in
review comments machine-enforced on every PR:

1. **knob discipline** (`knob_checker`) — every ``ROOM_TPU_*`` env
   read goes through the `room_tpu.utils.knobs` registry, and the
   registry and the generated ``docs/knobs.md`` agree.
2. **lock/stats + host-sync discipline** (`lock_checker`) —
   ``self._stats`` mutates only via ``_bump``; no blocking
   host-device sync under a lock or inside the dispatch window.
3. **fault-point coverage** (`fault_checker.check_coverage`) — every
   ``faults.FAULT_POINTS`` entry is chaos-tested somewhere under
   ``tests/`` and documented in the ``docs/chaos.md`` fault table.
4. **FaultError dispatch** (`fault_checker.check_dispatch`) — recovery
   code matches the typed ``FaultError.point``, never message text.
5. **fault trace/telemetry coverage**
   (`trace_checker.check_fault_trace_coverage`) — every fault point
   maps to a flight-recorder trace event + telemetry counter in
   ``serving/trace.py``'s FAULT_EVENTS, and ``faults.should_fire``
   stays wired through both (docs/observability.md).
6. **fault fuzz coverage**
   (`chaosfuzz_checker.check_fuzz_coverage`) — every fault point is
   either weighted into the schedule fuzzer's ``FUZZ_WEIGHTS``
   (``room_tpu/chaos/fuzz.py``) or listed in ``FUZZ_EXCLUDED`` with a
   reason naming its alternative coverage, never both
   (docs/chaosfuzz.md).
7. **lockmap — whole-program concurrency** (`lockmap`) — every lock
   acquisition resolves to the central named-lock registry
   (``room_tpu/utils/locks.py``), the acquisition graph (lexical
   nesting + one call level deep) stays cycle-free, guarded fields
   stay accessed under their inferred guard, and no blocking call
   (socket/file I/O, timeout-less join/get/wait) runs under a lock.
   ``--graph`` exports the graph as DOT; the runtime twin is the
   ``ROOM_TPU_LOCKDEP`` witness in ``room_tpu/utils/lockdep.py``.

Run: ``python -m room_tpu.analysis`` (or ``make lint``). Exit 0 =
no unsuppressed violations. Intentional violations live in
``.roomlint.suppress`` with a reason, or as inline
``# roomlint: allow[rule]`` comments.

This package imports nothing heavier than ``room_tpu.utils.knobs``
(stdlib-only), so the lint gate runs without jax.
"""

from __future__ import annotations

import os
from typing import Iterable, Optional

from . import (
    chaosfuzz_checker, dispatch_checker, fault_checker, knob_checker,
    knobs_doc, lock_checker, lockmap, trace_checker,
)
from .common import (
    SourceCache, SourceFile, Violation, apply_suppressions,
    iter_py_files, load_suppressions,
)

__all__ = [
    "Violation", "SourceFile", "SourceCache", "run_checks",
    "DEFAULT_SCAN_ROOTS", "SUPPRESS_FILE", "KNOBS_DOC",
]

# the tree the per-file checkers walk by default; tests/ is only read
# by the fault-coverage cross-check (fixtures with seeded violations
# live under tests/fixtures/ and must not fail the real gate)
DEFAULT_SCAN_ROOTS = ("room_tpu",)
SUPPRESS_FILE = ".roomlint.suppress"
KNOBS_DOC = os.path.join("docs", "knobs.md")


def check_file(src: SourceFile, fault_points: tuple[str, ...]
               ) -> list[Violation]:
    """All per-file checkers against one parsed source file."""
    out: list[Violation] = []
    out += knob_checker.check_source(src)
    out += lock_checker.check_source(src)
    out += fault_checker.check_arm_sites(src, fault_points)
    out += dispatch_checker.check_dispatch(src, fault_points)
    return out


def run_checks(
    repo_root: str,
    roots: Optional[Iterable[str]] = None,
    suppress_path: Optional[str] = None,
    cross_checks: bool = True,
) -> tuple[list[Violation], list[Violation]]:
    """Run the suite; returns (active, suppressed) violations.

    ``roots=None`` scans DEFAULT_SCAN_ROOTS. ``cross_checks`` adds the
    repo-level passes (fault coverage vs tests+docs, the lockmap
    whole-program concurrency pass, knob docs freshness) on top of
    the per-file walks. One SourceCache backs every pass, so each
    file is read and ``ast.parse``d exactly once per run.
    """
    cache = SourceCache(repo_root)
    fault_points = fault_checker.load_fault_points(repo_root, cache)
    violations: list[Violation] = []
    for src in iter_py_files(roots or DEFAULT_SCAN_ROOTS, repo_root,
                             cache):
        violations += check_file(src, fault_points)
    if cross_checks:
        violations += fault_checker.check_coverage(
            repo_root, cache=cache
        )
        violations += trace_checker.check_fault_trace_coverage(
            repo_root, cache
        )
        violations += chaosfuzz_checker.check_fuzz_coverage(
            repo_root, cache
        )
        # whole-program concurrency pass: always over the full tree
        # (a partial graph would under-report cycles)
        violations += lockmap.check_whole_program(
            repo_root, DEFAULT_SCAN_ROOTS, cache
        )
        violations += knob_checker.check_docs(
            os.path.join(repo_root, KNOBS_DOC)
        )
    spath = suppress_path if suppress_path is not None else \
        os.path.join(repo_root, SUPPRESS_FILE)
    entries = load_suppressions(spath)
    return apply_suppressions(
        violations, entries, os.path.relpath(spath, repo_root)
    )
