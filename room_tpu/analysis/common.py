"""Shared scaffolding for roomlint checkers: parsed-source handling,
violation records, inline allows, and the suppression file.

Suppression surfaces (docs/static_analysis.md):

- inline, on the flagged line::

      mode = os.environ.get("ROOM_TPU_X")  # roomlint: allow[knob-raw-env-read]

- the repo-level file ``.roomlint.suppress``: one violation class per
  line, ``<rule> <path> <qualname-or-*>``, with an explanation after
  ``#``. Entries that match nothing are themselves reported
  (``suppression-unused``) so the file can only shrink, never rot.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Iterable, Optional

_ALLOW_RE = re.compile(r"roomlint:\s*allow\[([a-z0-9-]+)\]")
_REGION_RE = re.compile(r"roomlint:\s*region=([a-z0-9-]+)")


@dataclass
class Violation:
    rule: str
    path: str
    line: int
    message: str
    qualname: str = ""

    def render(self) -> str:
        where = f"{self.path}:{self.line}"
        if self.qualname:
            where += f" ({self.qualname})"
        return f"{where}: {self.rule}: {self.message}"


@dataclass
class SuppressEntry:
    rule: str
    path: str
    qualname: str
    reason: str
    lineno: int
    hits: int = 0

    def matches(self, v: Violation) -> bool:
        if self.rule != v.rule:
            return False
        if os.path.normpath(self.path) != os.path.normpath(v.path):
            return False
        return self.qualname == "*" or self.qualname == v.qualname


class SourceFile:
    """One parsed Python file plus the line-level metadata checkers
    need: enclosing-function qualnames, inline allows, region marks."""

    def __init__(self, path: str, text: Optional[str] = None,
                 rel: Optional[str] = None) -> None:
        self.path = rel if rel is not None else path
        self.text = text if text is not None else \
            open(path, encoding="utf-8").read()
        self.lines = self.text.split("\n")
        self.tree = ast.parse(self.text, filename=path)
        # (start, end, qualname) per function, innermost-last
        self._funcs: list[tuple[int, int, str]] = []
        self._regions: dict[str, str] = {}  # qualname -> region name
        self._index_functions()

    def _index_functions(self) -> None:
        def walk(node: ast.AST, prefix: str) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    qual = f"{prefix}{child.name}"
                    end = getattr(child, "end_lineno", child.lineno)
                    self._funcs.append((child.lineno, end, qual))
                    marker = self._region_marker(child)
                    if marker:
                        self._regions[qual] = marker
                    walk(child, qual + ".")
                elif isinstance(child, ast.ClassDef):
                    walk(child, f"{prefix}{child.name}.")
                else:
                    walk(child, prefix)

        walk(self.tree, "")
        # innermost function should win the qualname lookup
        self._funcs.sort(key=lambda t: (t[0], -t[1]))

    def _region_marker(self, fn: ast.AST) -> Optional[str]:
        """A ``# roomlint: region=<name>`` comment on the def line, or
        on either of the two lines above it (decorators/comments)."""
        for ln in range(max(1, fn.lineno - 2), fn.lineno + 1):
            m = _REGION_RE.search(self.lines[ln - 1])
            if m:
                return m.group(1)
        return None

    def qualname_at(self, line: int) -> str:
        best = ""
        for start, end, qual in self._funcs:
            if start <= line <= end:
                best = qual  # list is ordered, innermost overrides
        return best

    def region_functions(self, region: str) -> list[tuple[int, int, str]]:
        return [
            (s, e, q) for s, e, q in self._funcs
            if self._regions.get(q) == region
        ]

    def inline_allowed(self, line: int, rule: str) -> bool:
        if 1 <= line <= len(self.lines):
            for m in _ALLOW_RE.finditer(self.lines[line - 1]):
                if m.group(1) == rule:
                    return True
        return False

    def violation(self, rule: str, node_or_line, message: str
                  ) -> Optional[Violation]:
        """Build a Violation unless an inline allow covers the line."""
        line = node_or_line if isinstance(node_or_line, int) else \
            getattr(node_or_line, "lineno", 0)
        if self.inline_allowed(line, rule):
            return None
        return Violation(rule, self.path, line, message,
                         self.qualname_at(line))


class SourceCache:
    """One ``ast.parse`` (and one disk read) per file per run.

    Every checker used to re-read and re-parse what it needed —
    ``load_fault_points`` alone parsed ``faults.py`` three times per
    run (run_checks, the trace cross-check, ``_should_fire_calls``),
    and the whole-program lockmap pass would have doubled the tree
    walk. All paths now funnel through one cache keyed on the
    absolute path; ``tests/test_analysis.py`` pins the
    exactly-once-per-file property."""

    def __init__(self, repo_root: str) -> None:
        self.repo_root = repo_root
        self._sources: dict[str, Optional[SourceFile]] = {}
        self._texts: dict[str, str] = {}

    def _abs(self, path: str) -> str:
        return path if os.path.isabs(path) else \
            os.path.join(self.repo_root, path)

    def text(self, path: str) -> str:
        """Raw file text (for regex-only passes: test mapping, doc
        tables)."""
        p = self._abs(path)
        if p not in self._texts:
            src = self._sources.get(p)
            if src is not None:
                self._texts[p] = src.text
            else:
                self._texts[p] = open(p, encoding="utf-8").read()
        return self._texts[p]

    def source(self, path: str) -> Optional[SourceFile]:
        """Parsed SourceFile, or None on a syntax error (the ruff
        tier owns those)."""
        p = self._abs(path)
        if p not in self._sources:
            rel = os.path.relpath(p, self.repo_root)
            try:
                self._sources[p] = SourceFile(
                    p, text=self.text(path), rel=rel
                )
            except SyntaxError:
                self._sources[p] = None
        return self._sources[p]

    def tree(self, path: str) -> ast.AST:
        """The parsed AST for checkers that only need the tree."""
        src = self.source(path)
        if src is None:
            raise SyntaxError(f"unparsable: {path}")
        return src.tree


def iter_py_paths(roots: Iterable[str], repo_root: str
                  ) -> Iterable[str]:
    """Absolute paths of every .py under the given roots (files or
    dirs), deduped and sorted."""
    seen = set()
    for root in roots:
        absroot = os.path.join(repo_root, root) \
            if not os.path.isabs(root) else root
        if os.path.isfile(absroot):
            paths = [absroot]
        else:
            paths = []
            for dirpath, dirnames, filenames in os.walk(absroot):
                dirnames[:] = [d for d in dirnames
                               if d != "__pycache__"]
                paths.extend(
                    os.path.join(dirpath, f)
                    for f in sorted(filenames) if f.endswith(".py")
                )
        for p in sorted(paths):
            if p not in seen:
                seen.add(p)
                yield p


def iter_py_files(roots: Iterable[str], repo_root: str,
                  cache: Optional[SourceCache] = None
                  ) -> Iterable[SourceFile]:
    """Yield SourceFile for every .py under the given roots (files or
    dirs), with paths reported relative to the repo root. With a
    cache, each file is parsed at most once per run across all
    passes."""
    if cache is None:
        cache = SourceCache(repo_root)
    for p in iter_py_paths(roots, repo_root):
        src = cache.source(p)
        if src is not None:
            yield src


def load_suppressions(path: str) -> list[SuppressEntry]:
    entries: list[SuppressEntry] = []
    if not os.path.exists(path):
        return entries
    with open(path, encoding="utf-8") as fh:
        for lineno, raw in enumerate(fh, 1):
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            body, _, reason = line.partition("#")
            parts = body.split()
            if len(parts) != 3:
                raise ValueError(
                    f"{path}:{lineno}: suppression lines are "
                    f"'<rule> <path> <qualname-or-*>  # reason', "
                    f"got {line!r}"
                )
            if not reason.strip():
                raise ValueError(
                    f"{path}:{lineno}: suppression for {parts[0]} "
                    "needs a '# reason'"
                )
            entries.append(SuppressEntry(
                parts[0], parts[1], parts[2], reason.strip(), lineno
            ))
    return entries


def apply_suppressions(
    violations: list[Violation],
    entries: list[SuppressEntry],
    suppress_path: str,
) -> tuple[list[Violation], list[Violation]]:
    """Split into (active, suppressed); unused entries come back as
    ``suppression-unused`` violations so stale lines fail the gate."""
    active: list[Violation] = []
    suppressed: list[Violation] = []
    for v in violations:
        hit = next((e for e in entries if e.matches(v)), None)
        if hit is not None:
            hit.hits += 1
            suppressed.append(v)
        else:
            active.append(v)
    for e in entries:
        if e.hits == 0:
            active.append(Violation(
                "suppression-unused", suppress_path, e.lineno,
                f"suppression '{e.rule} {e.path} {e.qualname}' "
                "matched nothing — delete it",
            ))
    return active, suppressed
