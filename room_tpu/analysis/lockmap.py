"""roomlint checker 6 — lockmap: whole-program concurrency analysis.

The serving stack holds ~40 registered locks (``room_tpu/utils/locks.py``)
across ~30 modules; every threaded PR since the fleet landed has
burned review passes hand-finding lock-order inversions, unguarded
shared state, and blocking calls held under a lock. This pass makes
those review comments machine-checked:

1. **Lock-acquisition graph** — every ``with <lock>:`` site resolves
   to a registry name (via the registered (module, class, attr)
   binding, the registry's ``hints`` spellings for foreign
   acquisitions like ``fleet._lock``, attribute-type inference for
   ``self._store._lock``, or an explicit ``# lockmap: name=<name>``
   pin). Nesting — lexical, and one call-graph level deep (calls made
   under a held lock into functions that acquire locks of their own) —
   contributes directed edges. Rules:

   - ``lock-order-cycle``: the named graph has a cycle — two threads
     walking the cycle from different entry points deadlock.
   - ``lock-self-nest``: a *same-instance* re-acquire of a
     non-reentrant lock (lexical self-nesting, a ``self.method()``
     call under the lock into a method that takes it again, or any
     self-edge on a module-global lock) — guaranteed deadlock.
   - ``lock-unresolved``: a lock-looking ``with`` site the registry
     cannot name. Register the lock or pin the site.

2. **Guarded-state inference** — a field written under the same named
   lock at >= 2 sites is *guarded* by it. Elsewhere:

   - ``lock-guarded-write``: a write to a guarded field without the
     guard held (TOCTOU / lost-update window);
   - ``lock-guarded-iter``: direct iteration over a guarded container
     without the guard held (dict-changed-size crash on the GIL's
     honor system). Plain loads stay legal — the tree's documented
     convention is that single GIL-atomic reads are sanctioned
     snapshots.

   ``__init__``-time writes are construction (happens-before publish)
   and exempt, as are helpers named ``*_locked`` (the documented
   caller-holds-the-lock convention).

3. **Blocking-call-under-lock taxonomy** (``blocking-under-lock``) —
   the lock_checker's device-sync rule generalized to the classes the
   PR-review lists kept catching by hand: socket send/recv, file I/O
   (``open``, ``os.replace``/``fsync``, ``shutil`` copies, pathlib
   read/write), ``Thread.join()`` with no timeout, and timeout-less
   ``Queue.get()`` / ``Event.wait()`` / ``Condition.wait()`` — any of
   them lexically under a held lock stalls every thread queued on it.

``python -m room_tpu.analysis --graph`` renders the extracted graph
as DOT (docs/static_analysis.md has the docs pipeline). The runtime
twin is ``room_tpu/utils/lockdep.py``; tests pin that lockdep's
observed edges are a subset of this pass's static graph.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Iterable, Optional

from .common import SourceCache, SourceFile, Violation, iter_py_files

_PIN_RE = re.compile(r"lockmap:\s*name=([a-z0-9_]+)")

# function-name suffix documenting the caller-holds-the-lock contract
_LOCKED_SUFFIX = "_locked"

# mutating container methods that count as writes for guard inference
_MUTATORS = (
    "append", "appendleft", "add", "remove", "discard", "pop",
    "popleft", "popitem", "clear", "update", "extend", "insert",
    "setdefault", "sort",
)

_ITER_METHODS = ("items", "values", "keys")


def _locks_registry():
    from room_tpu.utils.locks import LOCK_REGISTRY

    return LOCK_REGISTRY


# ---------------------------------------------------------------------------
# per-file fact extraction
# ---------------------------------------------------------------------------

@dataclass
class _Acquire:
    name: Optional[str]        # resolved registry name, None = unresolved
    expr: str                  # source spelling of the lock expression
    line: int
    qual: str                  # enclosing function qualname
    held: tuple                # resolved names held when acquiring
    base_kind: str             # "self" | "global" | "foreign"


@dataclass
class _CallSite:
    line: int
    qual: str
    held: tuple                # resolved names held at the call
    callee: Optional[tuple]    # (module, qualname) if resolved
    same_instance: bool        # self.method() / same-module global fn


@dataclass
class _AttrEvent:
    cls: str                   # owning class (resolved, maybe foreign)
    attr: str
    line: int
    qual: str
    held: tuple
    kind: str                  # "write" | "iter"


@dataclass
class _FileFacts:
    src: SourceFile
    module: str                # repo-relative path, forward slashes
    acquires: list = field(default_factory=list)
    calls: list = field(default_factory=list)
    attr_events: list = field(default_factory=list)
    blocking: list = field(default_factory=list)  # (desc, node, held)
    # function qualname -> set of resolved lock names acquired directly
    fn_locks: dict = field(default_factory=dict)
    # class -> attr -> ClassName (self.attr = ClassName(...))
    attr_types: dict = field(default_factory=dict)
    # import alias -> dotted module name
    imports: dict = field(default_factory=dict)
    classes: set = field(default_factory=set)


def _module_rel(src: SourceFile) -> str:
    return src.path.replace(os.sep, "/")


def _is_lockish(expr: ast.AST) -> Optional[tuple]:
    """(base_src, attr_or_name) when the with-item expression looks
    like a lock acquisition; base_src '' for a bare name."""
    if isinstance(expr, ast.Name):
        if "lock" in expr.id.lower():
            return ("", expr.id)
        return None
    if isinstance(expr, ast.Attribute):
        if "lock" not in expr.attr.lower():
            return None
        try:
            base = ast.unparse(expr.value)
        except Exception:
            return None
        return (base, expr.attr)
    return None


def _dotted_module_to_rel(dotted: str) -> Optional[str]:
    """'room_tpu.serving.trace' -> 'room_tpu/serving/trace.py'."""
    if not dotted.startswith("room_tpu"):
        return None
    return dotted.replace(".", "/") + ".py"


def _collect_imports(facts: _FileFacts) -> None:
    """alias -> dotted room_tpu module, resolving relative imports
    against the file's package path."""
    pkg_parts = facts.module.split("/")[:-1]   # e.g. room_tpu/serving
    for node in ast.walk(facts.src.tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name.startswith("room_tpu"):
                    facts.imports[a.asname or a.name.split(".")[0]] = \
                        a.name
        elif isinstance(node, ast.ImportFrom):
            if node.level:
                base = pkg_parts[:len(pkg_parts) - (node.level - 1)]
                prefix = ".".join(base)
                if node.module:
                    prefix = f"{prefix}.{node.module}" if prefix \
                        else node.module
            elif node.module and node.module.startswith("room_tpu"):
                prefix = node.module
            else:
                continue
            for a in node.names:
                facts.imports[a.asname or a.name] = \
                    f"{prefix}.{a.name}"


def _collect_attr_types(facts: _FileFacts) -> None:
    """Per class: self.X = ClassName(...) / self.X: ClassName."""
    tree = facts.src.tree
    for cls_node in ast.walk(tree):
        if not isinstance(cls_node, ast.ClassDef):
            continue
        facts.classes.add(cls_node.name)
        types = facts.attr_types.setdefault(cls_node.name, {})
        aliases: list[tuple] = []   # self.X = self.Y
        for node in ast.walk(cls_node):
            tgt = val = ann = None
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                tgt, val = node.targets[0], node.value
            elif isinstance(node, ast.AnnAssign):
                tgt, val, ann = node.target, node.value, \
                    node.annotation
            if not (isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"):
                continue
            cname = None
            if isinstance(val, ast.Call):
                fn = val.func
                if isinstance(fn, ast.Name):
                    cname = fn.id
                elif isinstance(fn, ast.Attribute):
                    cname = fn.attr
            elif isinstance(val, ast.Attribute) and \
                    isinstance(val.value, ast.Name) and \
                    val.value.id == "self":
                # alias: self._queue = self.scheduler — resolve to the
                # aliased attribute's type once the walk completes
                aliases.append((tgt.attr, val.attr))
            if (not cname or not cname[:1].isupper()) and \
                    ann is not None:
                # Optional["TieredKVStore"] and friends: the first
                # class-looking name inside the annotation
                for sub in ast.walk(ann):
                    cand = None
                    if isinstance(sub, ast.Name):
                        cand = sub.id
                    elif isinstance(sub, ast.Constant) and \
                            isinstance(sub.value, str):
                        cand = sub.value
                    if cand and cand[:1].isupper() and cand not in (
                            "Optional", "List", "Dict", "Set",
                            "Tuple", "Any", "Callable", "Union",
                            "Iterable", "Sequence", "Mapping"):
                        cname = cand
                        break
            if cname and cname[:1].isupper():
                types.setdefault(tgt.attr, cname)
        for _ in range(3):   # short alias chains converge fast
            for dst, src_attr in aliases:
                if src_attr in types:
                    types.setdefault(dst, types[src_attr])


class _Resolver:
    """Whole-program lock + callee + attribute-owner resolution over
    the registry and the scanned files' class index."""

    def __init__(self, registry: dict, facts_by_module: dict) -> None:
        self.registry = registry
        self.facts_by_module = facts_by_module
        # class name -> module (only when unambiguous tree-wide)
        self.class_index: dict[str, Optional[str]] = {}
        for mod, facts in facts_by_module.items():
            for cname in facts.classes:
                if cname in self.class_index and \
                        self.class_index[cname] != mod:
                    self.class_index[cname] = None   # ambiguous
                else:
                    self.class_index[cname] = mod
        # hint spelling -> decl (unambiguous only)
        self.hint_index: dict[tuple, Optional[object]] = {}
        for decl in registry.values():
            for hint in decl.hints:
                key = (hint, decl.attr)
                if key in self.hint_index and \
                        self.hint_index[key] is not decl:
                    self.hint_index[key] = None
                else:
                    self.hint_index[key] = decl
        # attr -> decls (for unique-attr fallback)
        self.attr_index: dict[str, list] = {}
        for decl in registry.values():
            self.attr_index.setdefault(decl.attr, []).append(decl)

    def decl_by_binding(self, module: str, cls: str, attr: str):
        for decl in self.registry.values():
            if decl.module == module and decl.cls == cls \
                    and decl.attr == attr:
                return decl
        return None

    def owner_class(self, facts: _FileFacts, cls_ctx: str,
                    base: str) -> Optional[tuple]:
        """(module, ClassName) the expression `base` denotes, via
        self, attribute-type inference, or registry hints."""
        if base == "self" and cls_ctx:
            return (facts.module, cls_ctx)
        if base.startswith("self.") and cls_ctx:
            attr = base.split(".", 1)[1]
            cname = facts.attr_types.get(cls_ctx, {}).get(attr)
            if cname:
                mod = self.class_index.get(cname)
                if mod:
                    return (mod, cname)
        return None

    def resolve_lock(self, facts: _FileFacts, cls_ctx: str,
                     base: str, attr: str) -> Optional[object]:
        """The LockDecl a with-site acquires, or None."""
        # bare module-global name
        if base == "":
            for decl in self.attr_index.get(attr, []):
                if decl.cls == "" and decl.module == facts.module:
                    return decl
            cands = [d for d in self.attr_index.get(attr, [])
                     if d.cls == ""]
            return cands[0] if len(cands) == 1 else None
        # exact owner (self.X, or inferred object type)
        owner = self.owner_class(facts, cls_ctx, base)
        if owner is not None:
            mod, cname = owner
            decl = self.decl_by_binding(mod, cname, attr)
            if decl is not None:
                return decl
            # class defined elsewhere than its registration (rare) —
            # fall through to hint/unique resolution
        # registered hint spellings (fleet._lock, rec.lock, ...)
        hint = self.hint_index.get((base, attr)) or \
            self.hint_index.get((base.split(".")[-1], attr))
        if hint is not None:
            return hint
        # unique attribute name tree-wide
        cands = self.attr_index.get(attr, [])
        if len(cands) == 1:
            return cands[0]
        return None

    def resolve_callee(self, facts: _FileFacts, cls_ctx: str,
                       call: ast.Call) -> tuple:
        """((module, qualname) or None, same_instance bool)."""
        fn = call.func
        if isinstance(fn, ast.Name):
            # same-module function, or imported function
            if fn.id in facts.imports:
                dotted = facts.imports[fn.id]
                mod = _dotted_module_to_rel(
                    ".".join(dotted.split(".")[:-1]))
                if mod in self.facts_by_module:
                    return ((mod, dotted.split(".")[-1]), False)
                return (None, False)
            return ((facts.module, fn.id), True)
        if not isinstance(fn, ast.Attribute):
            return (None, False)
        meth = fn.attr
        try:
            base = ast.unparse(fn.value)
        except Exception:
            return (None, False)
        if base == "self" and cls_ctx:
            return ((facts.module, f"{cls_ctx}.{meth}"), True)
        # imported module alias: trace_mod.note_event(...)
        if isinstance(fn.value, ast.Name) and base in facts.imports:
            dotted = facts.imports[base]
            mod = _dotted_module_to_rel(dotted)
            if mod in self.facts_by_module:
                return ((mod, meth), False)
            return (None, False)
        owner = self.owner_class(facts, cls_ctx, base)
        if owner is not None:
            mod, cname = owner
            return ((mod, f"{cname}.{meth}"), False)
        # registry hints name the class for conventional spellings
        for decl in self.registry.values():
            last = base.split(".")[-1]
            if decl.cls and (base in decl.hints or last in decl.hints):
                return ((decl.module, f"{decl.cls}.{meth}"), False)
        return (None, False)

    def owner_for_attr_event(self, facts: _FileFacts, cls_ctx: str,
                             base: str) -> Optional[tuple]:
        """(module, class) owning `base.attr` state, for guard
        inference — self and hint spellings only (inferred foreign
        object types are too weak a signal for a gate)."""
        if base == "self" and cls_ctx:
            return (facts.module, cls_ctx)
        for decl in self.registry.values():
            last = base.split(".")[-1]
            if decl.cls and (base in decl.hints or last in decl.hints):
                return (decl.module, decl.cls)
        return None


# ---- blocking-call taxonomy -------------------------------------------

def _is_none_const(node: Optional[ast.AST]) -> bool:
    return isinstance(node, ast.Constant) and node.value is None


def _is_true_const(node: Optional[ast.AST]) -> bool:
    return isinstance(node, ast.Constant) and node.value is True


def _timeout_bounds(node: ast.Call, positional_idx: Optional[int]
                    ) -> bool:
    """True when the call carries a timeout that actually bounds it —
    ``timeout=None`` (keyword or positional) blocks forever and does
    NOT count."""
    for kw in node.keywords:
        if kw.arg == "timeout":
            return not _is_none_const(kw.value)
    if positional_idx is not None and len(node.args) > positional_idx:
        return not _is_none_const(node.args[positional_idx])
    return False


def _blocking_call(node: ast.AST) -> Optional[str]:
    """Human description when the node is a blocking call from the
    under-a-lock taxonomy (docs/static_analysis.md)."""
    if not isinstance(node, ast.Call):
        return None
    fn = node.func
    kwargs = {k.arg for k in node.keywords}
    if isinstance(fn, ast.Name):
        if fn.id == "open":
            return "file I/O open()"
        if fn.id == "create_connection":
            return "socket create_connection()"
        return None
    if not isinstance(fn, ast.Attribute):
        return None
    base = ""
    if isinstance(fn.value, ast.Name):
        base = fn.value.id
    attr = fn.attr
    if attr in ("sendall", "recv", "recv_into", "accept"):
        return f"socket {attr}()"
    if base == "socket" and attr == "create_connection":
        return "socket create_connection()"
    if base == "os" and attr in ("replace", "fsync", "rename"):
        return f"file I/O os.{attr}()"
    if base == "shutil" and attr in (
            "copy", "copy2", "copyfile", "copyfileobj", "copytree",
            "move", "rmtree"):
        return f"file I/O shutil.{attr}()"
    if attr in ("read_bytes", "write_bytes", "read_text",
                "write_text"):
        return f"file I/O .{attr}()"
    if attr == "join":
        # Thread.join(timeout=...); str.join takes a real iterable,
        # so join() / join(None) / join(timeout=None) are thread-like
        args_blocking = not node.args or (
            len(node.args) == 1 and _is_none_const(node.args[0]))
        if args_blocking and not _timeout_bounds(node, 0):
            return "Thread.join() without timeout"
        return None
    if attr == "get":
        # Queue.get(block=True, timeout=None); dict.get needs a real
        # key, so the queue-like spellings are zero-arg, block=True
        # (positional or keyword), and any timeout=None
        block_true = (
            (not node.args and ("block" not in kwargs
                                or any(kw.arg == "block"
                                       and _is_true_const(kw.value)
                                       for kw in node.keywords)))
            or (node.args and _is_true_const(node.args[0]))
        )
        known_kwargs = kwargs <= {"block", "timeout"}
        if block_true and known_kwargs and len(node.args) <= 2 and \
                not _timeout_bounds(node, 1):
            return "Queue.get() without timeout"
        return None
    if attr == "wait":
        # Event/Condition/Popen .wait(timeout=...); wait(None) and
        # wait(timeout=None) block exactly like wait()
        args_blocking = not node.args or (
            len(node.args) == 1 and _is_none_const(node.args[0]))
        if args_blocking and not _timeout_bounds(node, 0):
            return ".wait() without timeout"
        return None
    return None


# ---------------------------------------------------------------------------
# extraction visitor
# ---------------------------------------------------------------------------

class _FunctionWalker(ast.NodeVisitor):
    """Walks one module; tracks the enclosing class/function and the
    lexical stack of held (resolved) locks."""

    def __init__(self, facts: _FileFacts, resolver: _Resolver) -> None:
        self.facts = facts
        self.resolver = resolver
        self.cls_stack: list[str] = []
        self.fn_stack: list[str] = []
        self.held: list = []       # resolved names (None = unresolved)

    # -- context maintenance --

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.cls_stack.append(node.name)
        self.generic_visit(node)
        self.cls_stack.pop()

    def _visit_fn(self, node) -> None:
        self.fn_stack.append(node.name)
        outer_held, self.held = self.held, []
        self.generic_visit(node)
        self.held = outer_held
        self.fn_stack.pop()

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn

    @property
    def _cls(self) -> str:
        return self.cls_stack[-1] if self.cls_stack else ""

    @property
    def _qual(self) -> str:
        parts = list(self.cls_stack) + list(self.fn_stack)
        return ".".join(parts)

    def _held_names(self) -> tuple:
        return tuple(h for h in self.held if h)

    # -- the interesting nodes --

    def visit_With(self, node: ast.With) -> None:
        acquired = 0
        for item in node.items:
            lockish = _is_lockish(item.context_expr)
            if lockish is None:
                continue
            base, attr = lockish
            pin = _PIN_RE.search(
                self.facts.src.lines[node.lineno - 1]
                if node.lineno <= len(self.facts.src.lines) else ""
            )
            decl = None
            if pin:
                decl = self.resolver.registry.get(pin.group(1))
            if decl is None:
                decl = self.resolver.resolve_lock(
                    self.facts, self._cls, base, attr)
            name = decl.name if decl is not None else None
            base_kind = "self" if base == "self" else (
                "global" if base == "" else "foreign")
            self.facts.acquires.append(_Acquire(
                name, f"{base}.{attr}" if base else attr,
                node.lineno, self._qual, self._held_names(), base_kind,
            ))
            if self.fn_stack:
                self.facts.fn_locks.setdefault(
                    self._qual, []).append(
                        (name, base_kind, node.lineno))
            self.held.append(name)
            acquired += 1
        self.generic_visit(node)
        for _ in range(acquired):
            self.held.pop()

    def visit_Call(self, node: ast.Call) -> None:
        held = self._held_names()
        if held:
            desc = _blocking_call(node)
            if desc:
                self.facts.blocking.append((desc, node, held))
            callee, same_inst = self.resolver.resolve_callee(
                self.facts, self._cls, node)
            if callee is not None:
                self.facts.calls.append(_CallSite(
                    node.lineno, self._qual, held, callee, same_inst,
                ))
        # attribute mutator calls count as writes
        fn = node.func
        if isinstance(fn, ast.Attribute) and fn.attr in _MUTATORS \
                and isinstance(fn.value, ast.Attribute):
            self._attr_event(fn.value, "write", node.lineno)
        self.generic_visit(node)

    def visit_Assign(self, node: ast.Assign) -> None:
        for tgt in node.targets:
            self._maybe_write_target(tgt, node.lineno)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._maybe_write_target(node.target, node.lineno)
        self.generic_visit(node)

    def visit_Delete(self, node: ast.Delete) -> None:
        for tgt in node.targets:
            self._maybe_write_target(tgt, node.lineno)
        self.generic_visit(node)

    def visit_For(self, node: ast.For) -> None:
        self._maybe_iter(node.iter, node.lineno)
        self.generic_visit(node)

    def visit_comprehension_iter(self, node) -> None:
        for gen in node.generators:
            self._maybe_iter(gen.iter, node.lineno)
        self.generic_visit(node)

    visit_ListComp = visit_comprehension_iter
    visit_SetComp = visit_comprehension_iter
    visit_DictComp = visit_comprehension_iter
    visit_GeneratorExp = visit_comprehension_iter

    # -- attr event helpers --

    def _maybe_write_target(self, tgt: ast.AST, line: int) -> None:
        if isinstance(tgt, ast.Subscript):
            tgt = tgt.value
        if isinstance(tgt, ast.Attribute):
            self._attr_event(tgt, "write", line)
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            for el in tgt.elts:
                self._maybe_write_target(el, line)

    def _maybe_iter(self, expr: ast.AST, line: int) -> None:
        tgt = expr
        if isinstance(tgt, ast.Call) and \
                isinstance(tgt.func, ast.Attribute) and \
                tgt.func.attr in _ITER_METHODS and not tgt.args:
            tgt = tgt.func.value
        if isinstance(tgt, ast.Attribute):
            self._attr_event(tgt, "iter", line)

    def _attr_event(self, attr_node: ast.Attribute, kind: str,
                    line: int) -> None:
        attr = attr_node.attr
        if attr.startswith("__") or "lock" in attr.lower():
            return
        try:
            base = ast.unparse(attr_node.value)
        except Exception:
            return
        owner = self.resolver.owner_for_attr_event(
            self.facts, self._cls, base)
        if owner is None:
            return
        self.facts.attr_events.append(_AttrEvent(
            f"{owner[0]}::{owner[1]}", attr, line, self._qual,
            self._held_names(), kind,
        ))


# ---------------------------------------------------------------------------
# whole-program passes
# ---------------------------------------------------------------------------

@dataclass
class LockGraph:
    # (a, b) -> list of witness strings "path:line (qual) [via]"
    edges: dict
    # name -> decl
    nodes: dict
    # self-edges that carry same-instance evidence: name -> witnesses
    self_nests: dict
    unresolved: list           # (facts, _Acquire)


def collect_facts(sources: Iterable[SourceFile]) -> dict:
    registry = _locks_registry()
    facts_by_module: dict[str, _FileFacts] = {}
    all_facts = []
    for src in sources:
        facts = _FileFacts(src, _module_rel(src))
        facts_by_module[facts.module] = facts
        all_facts.append(facts)
    # imports / class index / attr types must exist tree-wide BEFORE
    # the resolver snapshots them and the walk resolves foreign bases
    for facts in all_facts:
        _collect_imports(facts)
        _collect_attr_types(facts)
    resolver = _Resolver(registry, facts_by_module)
    for facts in all_facts:
        _FunctionWalker(facts, resolver).visit(facts.src.tree)
    return facts_by_module


def build_graph(facts_by_module: dict) -> LockGraph:
    registry = _locks_registry()
    edges: dict = {}
    self_nests: dict = {}
    unresolved = []

    def witness(facts, line, qual, via) -> str:
        where = f"{facts.module}:{line}"
        if qual:
            where += f" ({qual})"
        return f"{where} [{via}]"

    def add_edge(a: str, b: str, w: str, same_instance: bool) -> None:
        if a == b:
            decl = registry.get(a)
            multi = decl is not None and decl.multi_instance
            if same_instance or not multi:
                self_nests.setdefault(a, []).append(w)
            return
        edges.setdefault((a, b), []).append(w)

    # function qualname index -> direct acquisitions
    fn_index: dict[tuple, list] = {}
    for mod, facts in facts_by_module.items():
        for qual, entries in facts.fn_locks.items():
            fn_index[(mod, qual)] = entries

    for mod, facts in facts_by_module.items():
        for acq in facts.acquires:
            if acq.name is None:
                unresolved.append((facts, acq))
                continue
            for held in acq.held:
                add_edge(
                    held, acq.name,
                    witness(facts, acq.line, acq.qual, "nested"),
                    same_instance=(acq.base_kind in
                                   ("self", "global")),
                )
        for call in facts.calls:
            entries = fn_index.get(call.callee)
            if not entries:
                continue
            cmod, cqual = call.callee
            for (name, base_kind, line) in entries:
                if name is None:
                    continue
                for held in call.held:
                    add_edge(
                        held, name,
                        witness(facts, call.line, call.qual,
                                f"calls {cqual}"),
                        same_instance=(
                            call.same_instance
                            and base_kind in ("self", "global")
                        ),
                    )
    return LockGraph(edges, dict(registry), self_nests, unresolved)


def _find_cycles(edges: dict) -> list:
    """Strongly connected components with >1 node (Tarjan)."""
    graph: dict = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
    index: dict = {}
    low: dict = {}
    on_stack: dict = {}
    stack: list = []
    counter = [0]
    sccs: list = []

    def strongconnect(v):
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack[v] = True
        work = [(v, iter(sorted(graph.get(v, ()))))]
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack[w] = True
                    work.append((w, iter(sorted(graph.get(w, ())))))
                    advanced = True
                    break
                elif on_stack.get(w):
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack[w] = False
                    comp.append(w)
                    if w == node:
                        break
                if len(comp) > 1:
                    sccs.append(sorted(comp))

    for v in sorted(graph):
        if v not in index:
            strongconnect(v)
    return sccs


def check_lock_graph(facts_by_module: dict) -> list:
    graph = build_graph(facts_by_module)
    out: list[Violation] = []

    for facts, acq in graph.unresolved:
        v = facts.src.violation(
            "lock-unresolved", acq.line,
            f"cannot resolve lock acquisition '{acq.expr}' to a "
            "registered lock — register it in "
            "room_tpu/utils/locks.py or pin the site with "
            "'# lockmap: name=<name>'",
        )
        if v:
            out.append(v)

    for name, witnesses in sorted(graph.self_nests.items()):
        decl = graph.nodes.get(name)
        kind = decl.kind if decl else "lock"
        if kind == "rlock":
            continue   # reentrant by design
        first = witnesses[0]
        path, _, rest = first.partition(":")
        line = int(rest.split(" ")[0].split("[")[0] or 1)
        out.append(Violation(
            "lock-self-nest", path, line,
            f"non-reentrant lock '{name}' re-acquired while already "
            f"held by the same holder — guaranteed deadlock "
            f"({len(witnesses)} site(s); first: {first})",
        ))

    for cycle in _find_cycles(graph.edges):
        witnesses = []
        for i, a in enumerate(cycle):
            b = cycle[(i + 1) % len(cycle)]
            ws = graph.edges.get((a, b))
            if ws:
                witnesses.append(f"{a}->{b} at {ws[0]}")
        first_edge = None
        for (a, b), ws in sorted(graph.edges.items()):
            if a in cycle and b in cycle:
                first_edge = ws[0]
                break
        path, _, rest = (first_edge or "?:1").partition(":")
        try:
            line = int(rest.split(" ")[0].split("[")[0])
        except ValueError:
            line = 1
        out.append(Violation(
            "lock-order-cycle", path, line,
            "lock-order cycle {" + " -> ".join(cycle + [cycle[0]])
            + "}: threads entering at different points deadlock; "
            "witnesses: " + "; ".join(witnesses),
        ))
    return out


def check_guarded_state(facts_by_module: dict) -> list:
    """Guard inference + unguarded-access rules over every class's
    attribute events."""
    out: list[Violation] = []
    # (owner, attr) -> events
    by_field: dict = {}
    for facts in facts_by_module.values():
        for ev in facts.attr_events:
            by_field.setdefault((ev.cls, ev.attr), []).append(
                (facts, ev))

    for (owner, attr), events in sorted(by_field.items()):
        # guard inference: writes under a named lock, >= 2 distinct
        # sites agreeing on the same lock
        write_sites: dict = {}
        for facts, ev in events:
            if ev.kind != "write" or not ev.held:
                continue
            if ev.qual.split(".")[-1] in ("__init__", "__post_init__"):
                continue
            for name in ev.held:
                write_sites.setdefault(name, set()).add(
                    (facts.module, ev.line))
        guard = None
        best = 0
        for name in sorted(write_sites):
            if len(write_sites[name]) > best:
                guard, best = name, len(write_sites[name])
        if guard is None or best < 2:
            continue
        for facts, ev in events:
            fn_name = ev.qual.split(".")[-1]
            if fn_name in ("__init__", "__post_init__", "__new__"):
                continue
            if fn_name.endswith(_LOCKED_SUFFIX):
                continue   # documented caller-holds-the-lock helpers
            if guard in ev.held:
                continue
            if ev.kind == "write":
                v = facts.src.violation(
                    "lock-guarded-write", ev.line,
                    f"{owner.split('::')[-1]}.{attr} is guarded by "
                    f"lock '{guard}' ({best} locked write sites) but "
                    "written here without it — lost-update/TOCTOU "
                    "window",
                )
            else:
                v = facts.src.violation(
                    "lock-guarded-iter", ev.line,
                    f"iterating {owner.split('::')[-1]}.{attr} "
                    f"without its guard lock '{guard}' — a concurrent "
                    "mutation raises dict/list-changed-size mid-loop",
                )
            if v:
                out.append(v)
    return out


def check_blocking(facts_by_module: dict) -> list:
    out: list[Violation] = []
    for facts in facts_by_module.values():
        for desc, node, held in facts.blocking:
            v = facts.src.violation(
                "blocking-under-lock", node,
                f"blocking {desc} while holding lock(s) "
                f"{', '.join(repr(h) for h in held)} stalls every "
                "thread queued on them (docs/static_analysis.md "
                "taxonomy)",
            )
            if v:
                out.append(v)
    return out


def check_registry_drift(facts_by_module: dict) -> list:
    """A registered lock whose declared module (when scanned) has no
    ``make_lock("<name>")`` / ``make_rlock("<name>")`` creation site —
    the registry entry rotted away from the code. Modules outside the
    scan (fixture bindings in tests) are skipped."""
    out: list[Violation] = []
    registry = _locks_registry()
    for decl in sorted(registry.values(), key=lambda d: d.name):
        facts = facts_by_module.get(decl.module)
        if facts is None:
            continue
        text = facts.src.text
        if f'make_lock("{decl.name}")' in text or \
                f"make_lock('{decl.name}')" in text or \
                f'make_rlock("{decl.name}")' in text or \
                f"make_rlock('{decl.name}')" in text:
            continue
        out.append(Violation(
            "lock-registry-drift", decl.module, 1,
            f"registered lock {decl.name!r} has no "
            f"locks.make_{'r' if decl.kind == 'rlock' else ''}lock"
            f"({decl.name!r}) creation site in {decl.module} — "
            "update or delete the registration",
        ))
    return out


def check_whole_program(
    repo_root: str,
    roots: Iterable[str],
    cache: Optional[SourceCache] = None,
) -> list:
    """All lockmap rules over the tree (the roomlint cross-check
    entry point)."""
    if cache is None:
        cache = SourceCache(repo_root)
    facts = collect_facts(iter_py_files(roots, repo_root, cache))
    out = []
    out += check_lock_graph(facts)
    out += check_guarded_state(facts)
    out += check_blocking(facts)
    out += check_registry_drift(facts)
    return out


# ---------------------------------------------------------------------------
# DOT export (python -m room_tpu.analysis --graph)
# ---------------------------------------------------------------------------

def render_dot(facts_by_module: dict) -> str:
    """The acquisition graph in DOT: solid = lexical nesting, dashed
    = one-level call edge; node labels carry the owning module.
    Tooltips carry the witness site WITHOUT its line number — the
    checked-in render is freshness-gated in CI, and unrelated line
    drift in a witness file must not churn it (only a real edge
    change should)."""
    graph = build_graph(facts_by_module)
    used = set()
    for (a, b) in graph.edges:
        used.add(a)
        used.add(b)
    lines = [
        "digraph lockmap {",
        '  rankdir=LR;',
        '  node [shape=box, fontsize=10, fontname="monospace"];',
        '  edge [fontsize=8, fontname="monospace"];',
    ]
    for name in sorted(used):
        decl = graph.nodes.get(name)
        mod = decl.module.split("/")[-1] if decl else "?"
        lines.append(
            f'  "{name}" [label="{name}\\n{mod}"];'
        )
    for (a, b), ws in sorted(graph.edges.items()):
        via_call = all("[calls " in w for w in ws)
        style = ' style=dashed' if via_call else ""
        tip = re.sub(r":\d+", "", ws[0])
        lines.append(
            f'  "{a}" -> "{b}" [tooltip="{tip}"{style}];'
        )
    lines.append("}")
    return "\n".join(lines) + "\n"


def graph_edges(repo_root: str, roots: Iterable[str],
                cache: Optional[SourceCache] = None) -> set:
    """The static (a, b) edge set — the lockdep runtime witness's
    test oracle (observed edges must be a subset)."""
    if cache is None:
        cache = SourceCache(repo_root)
    facts = collect_facts(iter_py_files(roots, repo_root, cache))
    return set(build_graph(facts).edges)
