"""room_tpu — a TPU-native autonomous agent-swarm framework.

A ground-up rebuild of the capabilities of quoroom-ai/room (reference surveyed
in SURVEY.md) with the out-of-process inference path replaced by an in-tree
JAX/XLA/Pallas serving stack:

- ``room_tpu.core``     — rooms, queen/worker agent loops, quorum governance,
                          goals, skills, self-modification, memory.
- ``room_tpu.db``       — SQLite persistence (WAL, FTS5 hybrid search).
- ``room_tpu.models``   — JAX model definitions (Qwen3-MoE, Qwen2 dense,
                          384-d MiniLM-class embedder).
- ``room_tpu.ops``      — Pallas TPU kernels (ragged paged attention, et al.)
                          with XLA reference fallbacks.
- ``room_tpu.parallel`` — mesh construction, sharding rules, ring attention,
                          collective helpers (ICI/DCN aware).
- ``room_tpu.serving``  — continuous-batching inference engine: paged KV
                          cache, prefill/decode scheduler, sampling, sessions.
- ``room_tpu.providers``— model-provider registry (tpu:, echo:, openai:, ...).
- ``room_tpu.server``   — HTTP/WS API, auth/RBAC, event bus, runtime loops.
- ``room_tpu.mcp``      — MCP stdio tool server.
- ``room_tpu.cli``      — command-line entry points.
"""

__version__ = "0.2.0"
