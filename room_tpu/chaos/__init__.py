"""Composed-fault chaos harness (docs/chaosfuzz.md).

Two halves:

- :mod:`room_tpu.chaos.invariants` — the runtime **system-invariant
  witness**, lockdep's sibling (``ROOM_TPU_INVARIANTS``): cheap
  host-side checks of whole-system conservation laws (KV-page
  conservation, fence monotonicity, exactly-once xshard effects,
  single session ownership, ...) probed from existing seams — the
  engine step boundary, the fleet supervise tick, the swarm shard
  sweep, the clean-shutdown marker write.
- :mod:`room_tpu.chaos.fuzz` — the **schedule fuzzer**: a seeded PRNG
  composes weighted arm-windows over the fault-point registry
  (``serving/faults.py``) into versioned, replayable schedules, drives
  them against deterministic serving / swarm workloads with the
  witness armed, and delta-debugs a failing schedule down to a locally
  1-minimal reproducer. CLI: ``python -m room_tpu.chaos``.

This ``__init__`` stays import-light on purpose: ``invariants`` is
imported by the serving hot path (engine/fleet), so nothing here may
drag jax or the workload harnesses in. Import ``room_tpu.chaos.fuzz``
explicitly where the fuzzer is wanted.
"""

from . import invariants  # noqa: F401  (the witness is the light half)

__all__ = ["invariants"]
