"""Runtime system-invariant witness — lockdep's whole-system sibling
(docs/chaosfuzz.md).

Armed via ``ROOM_TPU_INVARIANTS`` (off by default, like
``ROOM_TPU_LOCKDEP``), existing seams probe a registry of cheap,
host-side conservation laws that 19 PRs of chaos suites previously
asserted only inside individual tests:

    kv_page_conservation   free + session-owned pages == pool total,
                           no page owned twice (PageTable.audit)
    slot_leak              every active slot's turn references a live
                           session (a released session must not keep
                           its slot)
    fence_monotonic        a session record's ownership fence never
                           moves backwards across probes
    single_ownership       a sid is resident on <= 1 serving replica
                           outside a tracked disagg ship
    mirror_offset_contiguity  a router-mirror journal buffer never
                           claims token offsets beyond the record's
                           mirror (offset bookkeeping corruption)
    thread_leak            a dead/buried replica's serve thread is
                           not still running
    xshard_idempotency     no idempotency key has two committed
                           xshard effect rows in one shard db
    drain_marker           the clean-shutdown marker is only written
                           when every engine's drain manifested

Probe seams: ``engine.step()`` boundary (cadence via
``ROOM_TPU_INVARIANTS_EVERY``), the fleet ``supervise`` tick, the
swarm shard ``supervise`` sweep, and ``lifecycle.write_clean_marker``.

Violations are ALWAYS recorded first — counter + bounded evidence ring
+ telemetry (``invariant.<name>``) + flight-recorder event — and THEN
strict mode (``ROOM_TPU_INVARIANTS_STRICT``, default on: CI posture)
raises :class:`InvariantViolation`; production arms with strict off
and reads ``invariant_violations`` off stats/health/metrics instead.
The fuzzer reads :func:`snapshot` either way, so a strict raise
swallowed by a crash supervisor still counts.

Every ``check_*`` function is a pure reader over duck-typed state
(tests feed seeded good/bad fakes); the ``probe_*`` wrappers add
cadence, recording, and the strict raise. State is process-global;
:func:`reset` exists for test isolation, exactly like lockdep.
"""

from __future__ import annotations

import sys
import threading
from typing import Optional

from ..utils import knobs

__all__ = [
    "INVARIANTS", "InvariantViolation", "enabled", "strict",
    "record", "snapshot", "reset",
    "check_kv_pages", "check_slots", "check_fences",
    "check_ownership", "check_mirror_buffers", "check_threads",
    "check_xshard", "check_drain",
    "probe_engine", "probe_fleet", "probe_swarm",
    "probe_drain_marker",
]

INVARIANTS = (
    "kv_page_conservation", "slot_leak", "fence_monotonic",
    "single_ownership", "mirror_offset_contiguity", "thread_leak",
    "xshard_idempotency", "drain_marker",
)

_MAX_EVIDENCE = 256   # bounded evidence ring; counters keep totals


class InvariantViolation(RuntimeError):
    """A system invariant the witness caught broken. Carries the
    recorded problem dicts (bounded) as ``problems``."""

    def __init__(self, message: str, problems: list) -> None:
        super().__init__(message)
        self.problems = problems


def enabled() -> bool:
    return knobs.get_bool("ROOM_TPU_INVARIANTS")


def strict() -> bool:
    return knobs.get_bool("ROOM_TPU_INVARIANTS_STRICT")


def _probe_every() -> int:
    try:
        return max(1, knobs.get_int("ROOM_TPU_INVARIANTS_EVERY"))
    except ValueError:
        return 1


# ---- global witness state (meta-locked, like lockdep) ----

_meta = threading.Lock()
_violation_count = 0
_counts: dict[str, int] = {}
_evidence: list[dict] = []
_probes = 0
# fence-monotonicity memory: (id(fleet), sid) -> highest fence seen
_fences: dict[tuple, int] = {}


def _telemetry_count(name: str) -> None:
    mod = sys.modules.get("room_tpu.core.telemetry")
    if mod is None:
        return
    try:
        mod.incr_counter(f"invariant.{name}")
    except Exception:
        pass


def _trace_event(problem: dict) -> None:
    mod = sys.modules.get("room_tpu.serving.trace")
    if mod is None:
        return
    try:
        mod.note_event("invariant.violation", problem)
    except Exception:
        pass


def _bound(detail: dict) -> dict:
    out = {}
    for k, v in detail.items():
        if isinstance(v, (int, float, bool, str, type(None))):
            out[k] = v if not isinstance(v, str) else v[:200]
        else:
            out[k] = repr(v)[:200]
    return out


def record(name: str, detail: dict) -> dict:
    """Record one violation: counter, bounded evidence, telemetry,
    flight recorder. Returns the recorded problem dict. Recording
    never raises — the strict raise is the PROBE's job, after every
    problem from the pass is safely on the books."""
    global _violation_count
    problem = {
        "invariant": name,
        "thread": threading.current_thread().name,
        **_bound(detail),
    }
    with _meta:
        _violation_count += 1
        _counts[name] = _counts.get(name, 0) + 1
        if len(_evidence) < _MAX_EVIDENCE:
            _evidence.append(problem)
    _telemetry_count(name)
    _trace_event(problem)
    return problem


def _finish(problems: list[dict]) -> list[dict]:
    """Record a probe pass's problems, then raise in strict mode."""
    recorded = [record(p.pop("invariant"), p) for p in problems]
    if recorded and strict():
        raise InvariantViolation(
            f"{len(recorded)} system-invariant violation(s): "
            + "; ".join(
                f"{p['invariant']}" for p in recorded[:4]
            ),
            recorded,
        )
    return recorded


# ---- checks: pure readers over duck-typed state ----

def check_kv_pages(page_table) -> list[dict]:
    """KV-page conservation: free + owned == pool total, no dupes,
    nothing out of range (``PageTable.audit``)."""
    audit = page_table.audit()
    if audit["balanced"]:
        return []
    return [{
        "invariant": "kv_page_conservation",
        **{k: audit[k] for k in (
            "n_pages", "free", "owned", "dupes", "out_of_range",
        )},
    }]


def check_slots(engine) -> list[dict]:
    """Slot leak: every active slot's turn must reference a session
    the engine still tracks (live or mid-stage)."""
    out = []
    staged = getattr(engine, "_staged_sids", set())
    for i, turn in enumerate(engine._active):
        if turn is None:
            continue
        sid = turn.session_id
        if sid not in engine.sessions and sid not in staged:
            out.append({
                "invariant": "slot_leak", "slot": i, "sid": sid,
            })
    return out


def check_fences(fleet) -> list[dict]:
    """Fence monotonicity: a record's ownership fence never moves
    backwards between probes (a rewind would re-admit a stale owner —
    the session-fork precursor the fence exists to refuse)."""
    out = []
    key0 = id(fleet)
    with _meta:
        for rec in list(fleet._records.values()):
            key = (key0, rec.sid)
            seen = _fences.get(key)
            if seen is not None and rec.fence < seen:
                out.append({
                    "invariant": "fence_monotonic", "sid": rec.sid,
                    "fence": rec.fence, "seen": seen,
                })
            else:
                _fences[key] = rec.fence
    return out


def check_ownership(fleet) -> list[dict]:
    """Single ownership: a sid's KV is resident on at most one
    serving replica, unless a tracked disagg ship is mid-flight or
    the record itself is mid-ship."""
    out = []
    inflight = getattr(fleet.disagg, "_inflight", {})
    holders: dict[str, list] = {}
    for h in fleet.replicas:
        if h.state != "serving":
            continue
        for sid in list(h.engine.sessions):
            if sid == "__null__" or sid.startswith("__prefix"):
                continue
            holders.setdefault(sid, []).append(h.rid)
    for sid, rids in holders.items():
        if len(rids) <= 1 or sid in inflight:
            continue
        rec = fleet._records.get(sid)
        if rec is not None and rec.ship_state is not None:
            continue
        out.append({
            "invariant": "single_ownership", "sid": sid,
            "replicas": ",".join(rids),
        })
    return out


def check_mirror_buffers(fleet) -> list[dict]:
    """Mirror/journal offset contiguity: a shard journal's pending
    token buffer may never claim offsets past the record's live
    mirror (``start + len > len(tokens)`` means the offset
    bookkeeping forked). Racing appends only GROW the mirror, so the
    comparison is safe from any thread."""
    out = []
    for shard in fleet._shards:
        journal = shard.journal
        if journal is None:
            continue
        for sid, (start, length) in \
                journal.pending_snapshot().items():
            rec = shard.records.get(sid)
            if rec is None or rec.mirror_dropped:
                continue
            have = len(rec.tokens)
            if start + length > have or start < 0:
                out.append({
                    "invariant": "mirror_offset_contiguity",
                    "sid": sid, "start": start, "pending": length,
                    "mirror": have, "shard": shard.shard_id,
                })
    return out


def check_threads(fleet) -> list[dict]:
    """Thread leak: a dead/buried replica whose serve thread is still
    alive after its re-home completed is leaked supervision."""
    out = []
    for h in fleet.replicas:
        if h.state == "dead" and h.rehomed_done and \
                h.thread is not None and h.thread.is_alive():
            out.append({
                "invariant": "thread_leak", "rid": h.rid,
                "thread": h.thread.name,
            })
    return out


def check_xshard(router) -> list[dict]:
    """Exactly-once xshard effects: no idempotency key may own two
    committed effect rows in one shard database. Probe I/O is
    best-effort — an armed ``db_io`` fault or a mid-kill handle must
    not crash the witness (the probe re-runs next sweep)."""
    out = []
    for db in router.all_dbs():
        try:
            rows = db.query(
                "SELECT idem_key, COUNT(*) AS n FROM cycle_journal "
                "WHERE kind='xshard' AND entry='effect' AND "
                "status='committed' GROUP BY idem_key "
                "HAVING COUNT(*) > 1"
            )
        except Exception:
            continue
        for row in rows:
            out.append({
                "invariant": "xshard_idempotency",
                "idem_key": row["idem_key"], "committed": row["n"],
            })
    return out


def check_drain(summaries) -> list[dict]:
    """Drain-marker honesty: a clean-shutdown marker write attests
    EVERY engine's drain landed its manifest; summaries saying
    otherwise mean the marker would paper over lost sessions."""
    out = []
    for name, s in (summaries or {}).items():
        if not (s or {}).get("manifest_written", False):
            out.append({
                "invariant": "drain_marker", "engine": name,
                "error": (s or {}).get("error"),
            })
    return out


# ---- probe seams ----

def probe_engine(engine) -> list[dict]:
    """Engine-step-boundary probe (the engine thread is the page
    table / session map's only mutator there). Cadence via
    ``ROOM_TPU_INVARIANTS_EVERY``."""
    if not enabled():
        return []
    tick = getattr(engine, "_sysinv_tick", 0) + 1
    engine._sysinv_tick = tick
    if tick % _probe_every():
        return []
    _count_probe()
    return _finish(check_kv_pages(engine.page_table)
                   + check_slots(engine))


def probe_fleet(fleet) -> list[dict]:
    """Fleet supervise-tick probe: fences, ownership, mirror
    buffers, thread leaks."""
    if not enabled():
        return []
    _count_probe()
    return _finish(
        check_fences(fleet) + check_ownership(fleet)
        + check_mirror_buffers(fleet) + check_threads(fleet)
    )


def probe_swarm(router) -> list[dict]:
    """Swarm shard-sweep probe: exactly-once xshard effects."""
    if not enabled():
        return []
    _count_probe()
    return _finish(check_xshard(router))


def probe_drain_marker(summaries) -> list[dict]:
    """Clean-shutdown-marker probe (lifecycle.write_clean_marker)."""
    if not enabled():
        return []
    _count_probe()
    return _finish(check_drain(summaries))


def _count_probe() -> None:
    global _probes
    with _meta:
        _probes += 1


# ---- inspection / test surface ----

def snapshot() -> dict:
    """Health/metrics surface: total + per-invariant counts and the
    bounded evidence ring — the shape ``stats()["invariants"]``,
    /api/tpu/health, and the fuzzer's outcome all read."""
    with _meta:
        return {
            "enabled": enabled(),
            "strict": strict(),
            "probes": _probes,
            "violations": _violation_count,
            "by_invariant": dict(_counts),
            "evidence": list(_evidence),
        }


def reset() -> None:
    """Drop all witnessed state (test / fuzz-run isolation)."""
    global _violation_count, _probes
    with _meta:
        _violation_count = 0
        _probes = 0
        _counts.clear()
        _evidence.clear()
        _fences.clear()
