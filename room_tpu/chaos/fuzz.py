"""Seeded composed-fault schedule fuzzer (docs/chaosfuzz.md).

A schedule is a versioned, replayable JSON document: a PRNG seed plus
the fully-resolved event list it generated — arm windows over the
fault-point registry (``serving/faults.py``), burst budgets,
probabilities, per-point RNG seeds, and at least one mid-schedule
kill whose adoption/failover machinery the run must survive. Two
deterministic single-threaded workloads drive the system under the
schedule with the invariant witness (``chaos/invariants.py``) armed:

- ``serving`` — an :class:`EngineFleet` (CPU-proxy tiny model, 3
  replicas, 2 router shards) under greedy session traffic, driven by
  ``run_until_idle`` between tick boundaries, finishing with a drain
  + clean-marker attempt;
- ``swarm`` — a :class:`SwarmRouter` storm (the ``swarm_storm`` bench
  shape): journaled cross-room messages + escalations with
  crash/adoption sweeps, ending in an exactly-once audit of every
  acknowledged delivery.

Same seed ⇒ byte-identical schedule JSON and identical outcome (the
workloads use only the schedule's RNG, seeded fault specs, and
zero-lease failover, so wall clock never reaches the outcome). On a
violation, :func:`shrink_schedule` greedy-delta-debugs the event list
to a locally 1-minimal failing schedule — the CI artifact.

``ROOM_TPU_CHAOSFUZZ_PLANT`` (test seam) deliberately breaks the
system mid-run — ``kv_leak`` steals a KV page once ``offload_io`` has
fired, ``double_effect`` double-commits an xshard journal row once
``db_io`` has — so the self-test can pin that the fuzzer finds real
bugs and shrinks them.

CLI: ``python -m room_tpu.chaos`` (see ``__main__.py``).
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import os
import random
import tempfile
from typing import Callable, Optional

from ..utils import knobs
from . import invariants

__all__ = [
    "SCHEDULE_VERSION", "FUZZ_WEIGHTS", "FUZZ_EXCLUDED",
    "SERVING_POINTS", "SWARM_POINTS", "KILL_POINTS",
    "generate_schedule", "schedule_json", "schedule_id",
    "save_schedule", "load_schedule", "run_schedule",
    "shrink_schedule", "active_schedule_info",
]

SCHEDULE_VERSION = 1

# Relative arm weights over the fault-point registry. roomlint checker
# 7 (analysis/chaosfuzz_checker.py) pins FUZZ_WEIGHTS + FUZZ_EXCLUDED
# == faults.FAULT_POINTS exactly, so a new fault point cannot ship
# invisible to the fuzzer.
FUZZ_WEIGHTS = {
    "kv_alloc": 3,
    "prefill_oom": 2,
    "prefill_chunk": 2,
    "decode_step": 2,
    "decode_window": 2,
    "decode_stall": 1,
    "tokenizer": 1,
    "engine_crash": 2,
    "provider_timeout": 1,
    "offload_io": 3,
    "shutdown_io": 2,
    "replica_crash": 3,
    "router_io": 2,
    "kv_wire": 1,
    "prefix_io": 1,
    "wire_partition": 1,
    "heartbeat_loss": 1,
    "mirror_journal_io": 2,
    "placement_io": 2,
    "router_shard_crash": 3,
    "db_io": 3,
    "cycle_crash": 1,
    "loop_hang": 1,
    "tool_exec": 1,
    "shard_crash": 3,
}

# Points the in-process fuzz harness structurally cannot reach, with
# the reason (the checker requires one). Everything here must be
# covered elsewhere — noted per entry.
FUZZ_EXCLUDED = {
    "client_disconnect": (
        "fires on the HTTP SSE stream seam; the fuzz workloads drive "
        "engines directly with no socket to abort (covered by "
        "tests/test_chaos_serving.py)"
    ),
    "shard_proc_kill": (
        "fires at the multi-process supervisor seam; the in-process "
        "fuzz harness has no shard child processes (covered by the "
        "swarm_storm_proc bench tier + tests/test_swarm_proc.py)"
    ),
    "shard_wire_io": (
        "parent->child control-wire frames exist only in process "
        "mode (covered by the swarm_storm_proc bench tier + "
        "tests/test_swarm_proc.py)"
    ),
}

# Workload partition: which armable points each harness exposes.
SERVING_POINTS = (
    "kv_alloc", "prefill_oom", "prefill_chunk", "decode_step",
    "decode_window", "decode_stall", "tokenizer", "engine_crash",
    "provider_timeout", "offload_io", "shutdown_io", "replica_crash",
    "router_io", "kv_wire", "prefix_io", "wire_partition",
    "heartbeat_loss", "mirror_journal_io", "placement_io",
    "router_shard_crash",
)
SWARM_POINTS = (
    "db_io", "cycle_crash", "loop_hang", "tool_exec", "shard_crash",
)
# one-shot hard kills whose failover/adoption the schedule must ride
KILL_POINTS = ("replica_crash", "router_shard_crash", "shard_crash")

_PROMPT = list(range(1, 20))
_CONT = [7, 7, 7]

# set for the duration of run_schedule so telemetry crash reports can
# attach the reproducer (core/telemetry.py resolves via sys.modules)
_active_schedule: Optional[dict] = None


def active_schedule_info() -> Optional[dict]:
    """{id, seed, workload} of the schedule currently running (crash
    report attachment), or None."""
    return _active_schedule


# ---- schedule generation ----

def _weighted_sample(rng: random.Random, pool: list, k: int) -> list:
    """k distinct points, weight-biased, deterministic."""
    pool = list(pool)
    out = []
    while pool and len(out) < k:
        weights = [FUZZ_WEIGHTS.get(p, 1) for p in pool]
        total = sum(weights)
        roll = rng.random() * total
        acc = 0.0
        for i, w in enumerate(weights):
            acc += w
            if roll < acc:
                out.append(pool.pop(i))
                break
    return out


def generate_schedule(
    seed: int,
    workload: str = "serving",
    ticks: Optional[int] = None,
) -> dict:
    """Resolve one seeded schedule: distinct-point arm windows with
    burst budgets and per-spec RNG seeds, exactly one mid-schedule
    kill, and a guaranteed >= 2-point overlap."""
    if workload not in ("serving", "swarm"):
        raise ValueError(f"unknown workload {workload!r}")
    if ticks is None:
        ticks = max(6, knobs.get_int("ROOM_TPU_CHAOSFUZZ_TICKS"))
    rng = random.Random(seed)
    points = SERVING_POINTS if workload == "serving" else SWARM_POINTS
    kills = [p for p in points if p in KILL_POINTS]
    pool = [p for p in points if p not in KILL_POINTS]
    n_events = rng.randint(3, min(8, len(pool)))
    events = []
    # the guaranteed kill+adoption, mid-schedule
    kill_at = rng.randint(max(1, ticks // 4), max(2, (3 * ticks) // 4))
    events.append({
        "point": rng.choice(kills), "at": kill_at, "dur": 1,
        "p": 1.0, "times": 1, "latency": 0.0,
        "seed": rng.getrandbits(32),
    })
    for point in _weighted_sample(rng, pool, n_events):
        at = rng.randint(1, max(1, ticks - 3))
        dur = rng.randint(2, max(3, ticks // 3))
        events.append({
            "point": point, "at": at, "dur": dur,
            "p": rng.choice([0.25, 0.5, 1.0]),
            "times": rng.choice([None, 2, 4]),
            "latency": 0.02 if point in ("decode_stall", "loop_hang")
            else 0.0,
            "seed": rng.getrandbits(32),
        })
    # guarantee a >= 2-point overlap somewhere in the schedule
    if len(events) >= 2 and not _has_overlap(events):
        events[1]["at"] = events[0]["at"]
    events.sort(key=lambda e: (e["at"], e["point"]))
    return {
        "version": SCHEDULE_VERSION,
        "workload": workload,
        "seed": seed,
        "ticks": ticks,
        "events": events,
    }


def _has_overlap(events: list) -> bool:
    spans = [(e["at"], e["at"] + e["dur"]) for e in events]
    for i, (a0, a1) in enumerate(spans):
        for b0, b1 in spans[i + 1:]:
            if a0 < b1 and b0 < a1:
                return True
    return False


def schedule_json(sched: dict) -> str:
    """Canonical serialization — byte-identical for equal schedules
    (sorted keys, fixed separators, trailing newline)."""
    return json.dumps(sched, sort_keys=True,
                      separators=(",", ":")) + "\n"


def schedule_id(sched: dict) -> str:
    return hashlib.sha1(
        schedule_json(sched).encode("utf-8")
    ).hexdigest()[:16]


def save_schedule(sched: dict, path: str) -> str:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        f.write(schedule_json(sched))
    return path


def load_schedule(path: str) -> dict:
    with open(path, "r", encoding="utf-8") as f:
        sched = json.load(f)
    version = sched.get("version")
    if version != SCHEDULE_VERSION:
        raise ValueError(
            f"schedule version {version!r} != {SCHEDULE_VERSION} "
            f"(regenerate with this tree's fuzzer)"
        )
    return sched


# ---- the arm/disarm tick driver ----

class _Arming:
    """Applies a schedule's arm windows at tick boundaries and folds
    each point's firing count into the outcome as it disarms."""

    def __init__(self, events: list) -> None:
        self.events = events
        self.fired: dict[str, int] = {}
        self._armed: set[str] = set()

    def apply(self, tick: int) -> None:
        from ..serving import faults

        for ev in self.events:
            if ev["at"] + ev["dur"] == tick and \
                    ev["point"] in self._armed:
                self._disarm(ev["point"])
        for ev in self.events:
            if ev["at"] == tick:
                faults.inject(
                    ev["point"], probability=ev["p"],
                    latency_s=ev["latency"], times=ev["times"],
                    seed=ev["seed"],
                )
                self._armed.add(ev["point"])

    def _disarm(self, point: str) -> None:
        from ..serving import faults

        self.fired[point] = self.fired.get(point, 0) \
            + faults.fired(point)
        faults.clear(point)
        self._armed.discard(point)

    def finish(self) -> dict[str, int]:
        for point in list(self._armed):
            self._disarm(point)
        return {k: v for k, v in sorted(self.fired.items()) if v}


@contextlib.contextmanager
def _env(pins: dict):
    saved = {k: os.environ.get(k) for k in pins}
    os.environ.update({k: str(v) for k, v in pins.items()})
    try:
        yield
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _plant() -> Optional[str]:
    return knobs.get_str("ROOM_TPU_CHAOSFUZZ_PLANT") or None


# ---- serving workload ----

_TINY = None


def _tiny_model():
    """Build (and cache) the CPU-proxy tiny model once per process."""
    global _TINY
    if _TINY is None:
        import jax

        from ..models import qwen3, tiny_moe

        cfg = tiny_moe()
        params = qwen3.init_params(cfg, jax.random.PRNGKey(0))
        _TINY = (cfg, params)
    return _TINY


def _run_serving(sched: dict) -> dict:
    from ..serving import SamplingParams, ServingEngine, faults
    from ..serving import lifecycle as lifecycle_mod
    from ..serving.fleet import EngineFleet

    cfg, params = _tiny_model()
    rng = random.Random(sched["seed"] ^ 0x5EED)
    arming = _Arming(sched["events"])
    plant = _plant()
    planted = False
    out = {
        "turns_ok": 0, "turns_failed": 0, "turns_shed": 0,
        "submit_errors": 0, "drive_errors": 0, "tokens": 0,
        "aborted": False,
    }
    tmp = tempfile.mkdtemp(prefix="room_tpu_fuzz_")
    pins = {
        "ROOM_TPU_PREFIX_CACHE_PAGES": "0",
        "ROOM_TPU_OFFLOAD_DIR": os.path.join(tmp, "spool"),
        "ROOM_TPU_LIFECYCLE_DIR": os.path.join(tmp, "lc"),
        "ROOM_TPU_ROUTER_SHARDS": "2",
        # zero-lease failover: adoption lands on the next supervise
        # tick, not after a wall-clock wait — outcome determinism
        "ROOM_TPU_ROUTER_LEASE_S": "0",
    }
    with _env(pins):
        fleet = EngineFleet(
            "tiny-moe",
            lambda i: ServingEngine(
                cfg, params, max_batch=4, page_size=8, n_pages=96,
                offload=True, stop_token_ids=[],
            ),
            3, auto_rebuild=False,
        )
        sids = [f"fz{i}" for i in range(6)]
        turns = []
        try:
            for tick in range(sched["ticks"]):
                arming.apply(tick)
                for _ in range(2):
                    sid = rng.choice(sids)
                    prompt = _PROMPT if rng.random() < 0.5 else _CONT
                    cls = rng.choice(
                        ["queen", "worker", "worker", "background"]
                    )
                    try:
                        turns.append(fleet.submit(
                            prompt, session_id=sid,
                            sampling=SamplingParams(
                                temperature=0.0, max_new_tokens=6,
                            ),
                            turn_class=cls,
                        ))
                    except Exception:
                        out["submit_errors"] += 1
                try:
                    fleet.run_until_idle(max_steps=20_000)
                except invariants.InvariantViolation:
                    out["aborted"] = True
                    break
                except Exception:
                    # same contract as the swarm sweep: an injected
                    # fault surfacing from the drive loop is storm
                    # damage the next tick retries, not a verdict
                    out["drive_errors"] += 1
                if plant == "kv_leak" and not planted and \
                        "offload_io" in faults.snapshot():
                    victim = next(
                        (h.engine for h in fleet.replicas
                         if h.state == "serving"), None,
                    )
                    if victim is not None and \
                            victim.page_table._free:
                        victim.page_table._free.pop()
                        planted = True
            # settle: disarm everything, then one clean pass so
            # in-flight work lands before the audit
            out["fired"] = arming.finish()
            if not out["aborted"]:
                try:
                    fleet.run_until_idle(max_steps=20_000)
                except invariants.InvariantViolation:
                    out["aborted"] = True
            for t in turns:
                if t.shed:
                    out["turns_shed"] += 1
                elif t.error:
                    out["turns_failed"] += 1
                elif t.done.is_set():
                    out["turns_ok"] += 1
                    out["tokens"] += len(t.new_tokens)
            # drained shutdown: exercises shutdown_io + the
            # drain-marker honesty seam exactly like server stop
            try:
                summary = fleet.drain(
                    os.path.join(tmp, "lc", "drain"),
                )
                out["drained_ok"] = bool(summary["manifest_written"])
                if out["drained_ok"]:
                    lifecycle_mod.write_clean_marker(
                        root=os.path.join(tmp, "lc"),
                        summaries=summary["replicas"],
                    )
            except invariants.InvariantViolation:
                out["aborted"] = True
                out["drained_ok"] = False
        finally:
            from ..serving import faults as faults_mod

            faults_mod.clear()
    return out


# ---- swarm workload ----

def _run_swarm(sched: dict) -> dict:
    from ..serving import faults
    from ..swarm.shard import SwarmRouter

    rng = random.Random(sched["seed"] ^ 0x5EED)
    arming = _Arming(sched["events"])
    plant = _plant()
    planted = False
    out = {
        "sends_acked": 0, "escalations_acked": 0, "unresolved": 0,
        "supervise_errors": 0,
        "messages_lost": 0, "messages_double": 0, "aborted": False,
    }
    tmp = tempfile.mkdtemp(prefix="room_tpu_fuzz_")
    router = SwarmRouter(n_shards=3, db_dir=tmp, lease_s=0.0)
    acked: list[tuple[int, str]] = []   # (to_room, subject) delivered
    pending: list[tuple[int, int, str]] = []
    try:
        rooms = [
            router.create_room(f"fuzz-{i}")["id"] for i in range(6)
        ]
        for tick in range(sched["ticks"]):
            arming.apply(tick)
            batch = pending
            pending = []
            for _ in range(3):
                src, dst = rng.sample(rooms, 2)
                batch.append((src, dst, f"m{tick}-{rng.randrange(1 << 20)}"))
            for src, dst, subject in batch:
                try:
                    router.send_message(src, dst, subject, "storm")
                    acked.append((dst, subject))
                    out["sends_acked"] += 1
                except Exception:
                    # retry next tick with IDENTICAL args: a half that
                    # already landed dedups on its journal key — the
                    # exactly-once contract under test
                    pending.append((src, dst, subject))
            try:
                router.escalate(rng.choice(rooms), f"q{tick}")
                out["escalations_acked"] += 1
            except Exception:
                pass
            if plant == "double_effect" and not planted and \
                    "db_io" in faults.snapshot():
                planted = _plant_double_effect(router)
            try:
                router.supervise()
            except invariants.InvariantViolation:
                out["aborted"] = True
                break
            except Exception:
                # an injected fault caught the supervise sweep itself
                # mid-I/O; the sweep is idempotent and re-runs next
                # tick — survivable storm damage, counted not fatal
                out["supervise_errors"] += 1
        out["fired"] = arming.finish()
        # audit with faults off: every acked delivery exists exactly
        # once (lost = exactly-once broken one way, double = the other)
        try:
            router.supervise()
        except invariants.InvariantViolation:
            out["aborted"] = True
        out["unresolved"] = len(pending)
        for dst, subject in acked:
            try:
                rows = router.db_for(dst).query(
                    "SELECT COUNT(*) AS n FROM room_messages WHERE "
                    "room_id=? AND direction='inbound' AND subject=?",
                    (dst, subject),
                )
                n = rows[0]["n"] if rows else 0
            except Exception:
                continue
            if n == 0:
                out["messages_lost"] += 1
            elif n > 1:
                out["messages_double"] += 1
    finally:
        faults.clear()
        router.close()
    return out


def _plant_double_effect(router) -> bool:
    """Test-seam bug: clone one committed xshard journal row under
    the SAME idempotency key — the double-commit the journal protocol
    exists to prevent. Detected by the xshard_idempotency probe."""
    from ..db.database import utc_now

    for db in router.all_dbs():
        try:
            row = db.query_one(
                "SELECT room_id, worker_id, idem_key, payload FROM "
                "cycle_journal WHERE kind='xshard' AND entry='effect' "
                "AND status='committed' ORDER BY id LIMIT 1"
            )
            if row is None:
                continue
            db.execute(
                "INSERT INTO cycle_journal(kind, ref_id, room_id, "
                "worker_id, entry, status, idem_key, payload, "
                "updated_at) VALUES ('xshard',0,?,?,'effect',"
                "'committed',?,?,?)",
                (row["room_id"], row["worker_id"], row["idem_key"],
                 row["payload"], utc_now()),
            )
            return True
        except Exception:
            continue
    return False


# ---- run + shrink ----

def run_schedule(sched: dict) -> dict:
    """Drive one schedule deterministically; returns the outcome dict
    (fault firings, workload counters, and the invariant witness's
    verdict). Clears fault + witness state before and after."""
    global _active_schedule
    from ..serving import faults

    faults.clear()
    invariants.reset()
    _active_schedule = {
        "id": schedule_id(sched),
        "seed": sched["seed"],
        "workload": sched["workload"],
    }
    try:
        if sched["workload"] == "swarm":
            out = _run_swarm(sched)
        else:
            out = _run_serving(sched)
    finally:
        faults.clear()
        _active_schedule = None
    snap = invariants.snapshot()
    out["schedule_id"] = schedule_id(sched)
    out["violations"] = snap["violations"]
    out["by_invariant"] = snap["by_invariant"]
    return out


def outcome_failed(out: dict) -> bool:
    """The fuzzer's verdict: any invariant violation, any lost or
    double-delivered acked message, or an aborted (strict-raise) run."""
    return bool(
        out.get("violations", 0)
        or out.get("messages_lost", 0)
        or out.get("messages_double", 0)
        or out.get("aborted")
    )


def shrink_schedule(
    sched: dict,
    fails: Optional[Callable[[dict], bool]] = None,
    max_runs: int = 64,
) -> dict:
    """Greedy delta-debugging over the event list: drop windows
    (halves, then singles) while the schedule still fails, down to a
    locally 1-minimal reproducer. ``fails`` defaults to re-running the
    schedule and checking :func:`outcome_failed`. Bounded by
    ``max_runs`` evaluations."""
    if fails is None:
        fails = lambda s: outcome_failed(run_schedule(s))  # noqa: E731
    events = list(sched["events"])
    runs = 0

    def mk(evs: list) -> dict:
        return {**sched, "events": evs}

    chunk = max(1, len(events) // 2)
    while chunk >= 1 and runs < max_runs:
        i = 0
        removed_any = False
        while i < len(events) and len(events) > 1 and runs < max_runs:
            cand = events[:i] + events[i + chunk:]
            if not cand:
                break
            runs += 1
            if fails(mk(cand)):
                events = cand
                removed_any = True
            else:
                i += chunk
        if chunk == 1 and not removed_any:
            break
        chunk = chunk // 2 if chunk > 1 else (1 if removed_any else 0)
    return mk(events)
