"""``python -m room_tpu.chaos`` — run seeded composed-fault schedules
against the fuzz workloads with the invariant witness strict-armed.

Quick CI tier::

    python -m room_tpu.chaos --seeds 11,23 --workload both \\
        --out chaosfuzz-artifacts

Replay a saved schedule (bug report / CI artifact)::

    python -m room_tpu.chaos --replay failing-schedule.json

Every run writes its schedule JSON first, so any failure is already
replayable before the workload starts. On failure the schedule is
shrunk to a locally 1-minimal reproducer and both the original and
shrunk schedules land in ``--out`` as CI artifacts; exit status 1.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _parse_seeds(text: str) -> list[int]:
    return [int(s) for s in text.split(",") if s.strip()]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m room_tpu.chaos",
        description="seeded composed-fault schedule fuzzer "
        "(docs/chaosfuzz.md)",
    )
    ap.add_argument("--seeds", type=_parse_seeds, default=None,
                    help="comma-separated schedule seeds")
    ap.add_argument("--seed", type=int, default=None,
                    help="single schedule seed")
    ap.add_argument("--workload", default="both",
                    choices=("serving", "swarm", "both"))
    ap.add_argument("--ticks", type=int, default=None,
                    help="schedule length (default: "
                    "ROOM_TPU_CHAOSFUZZ_TICKS)")
    ap.add_argument("--out", default="chaosfuzz-artifacts",
                    help="artifact dir for schedules + outcomes")
    ap.add_argument("--replay", default=None, metavar="FILE",
                    help="replay a saved schedule.json instead of "
                    "generating")
    ap.add_argument("--no-shrink", action="store_true",
                    help="skip shrinking failing schedules")
    args = ap.parse_args(argv)

    # arm the witness before any room_tpu module loads its knobs;
    # strict default ("1") makes violations abort the run. setdefault,
    # not a knobs.get_bool round trip: an explicit =0 from the caller
    # must win, and the knob layer has no "was it set?" probe.
    os.environ.setdefault("ROOM_TPU_INVARIANTS", "1")  # roomlint: allow[knob-raw-env-read]

    from . import fuzz, invariants

    if not invariants.enabled():
        print("warning: ROOM_TPU_INVARIANTS=0 — witness disarmed, "
              "runs can only fail on lost/double deliveries",
              file=sys.stderr)

    failures = 0
    if args.replay:
        sched = fuzz.load_schedule(args.replay)
        out = fuzz.run_schedule(sched)
        print(json.dumps(
            {"schedule": args.replay, "outcome": out}, sort_keys=True,
            indent=2,
        ))
        return 1 if fuzz.outcome_failed(out) else 0

    seeds = list(args.seeds or [])
    if args.seed is not None:
        seeds.append(args.seed)
    if not seeds:
        seeds = [11, 23]
    workloads = ["serving", "swarm"] if args.workload == "both" \
        else [args.workload]

    os.makedirs(args.out, exist_ok=True)
    for workload in workloads:
        for seed in seeds:
            sched = fuzz.generate_schedule(
                seed, workload=workload, ticks=args.ticks,
            )
            tag = f"{workload}-{seed}"
            # persisted BEFORE the run: a wedged/killed run is still
            # replayable from the artifact
            fuzz.save_schedule(
                sched, os.path.join(args.out, f"schedule-{tag}.json"),
            )
            out = fuzz.run_schedule(sched)
            ok = not fuzz.outcome_failed(out)
            points = sorted({e["point"] for e in sched["events"]})
            print(f"[{tag}] id={out['schedule_id']} "
                  f"{'ok' if ok else 'FAIL'} "
                  f"violations={out['violations']} "
                  f"points={','.join(points)}")
            if ok:
                continue
            failures += 1
            fuzz.save_schedule(
                sched,
                os.path.join(args.out, f"failing-schedule-{tag}.json"),
            )
            with open(os.path.join(args.out, f"outcome-{tag}.json"),
                      "w", encoding="utf-8") as f:
                json.dump(out, f, sort_keys=True, indent=2)
            if not args.no_shrink:
                small = fuzz.shrink_schedule(sched)
                fuzz.save_schedule(
                    small,
                    os.path.join(args.out, f"shrunk-{tag}.json"),
                )
                print(f"[{tag}] shrunk "
                      f"{len(sched['events'])} -> "
                      f"{len(small['events'])} events "
                      f"(shrunk-{tag}.json)")
    if failures:
        print(f"{failures} failing schedule(s); artifacts in "
              f"{args.out}/", file=sys.stderr)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
