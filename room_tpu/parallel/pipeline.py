"""Pipeline parallelism: GPipe-style microbatch schedule over a ``pp``
mesh axis via shard_map + ppermute (SURVEY §2.7 — the one parallelism
the reference family leaves to multi-process host plumbing; here it is
an XLA collective schedule over ICI).

Design: the transformer trunk's stacked layer parameters [L, ...] are
sharded over ``pp`` on the layer axis, so each device owns L/pp
consecutive layers. Microbatches march through the stages; at each of
the M + P - 1 schedule steps every device runs its local layer stack on
its resident microbatch, then activations rotate one stage forward with
a single `ppermute`. Embedding and the LM head stay outside the trunk
(replicated compute), so the pipelined forward composes with the same
qwen3 params pytree used everywhere else.

Bubble fraction is the textbook (P-1)/(M+P-1): choose M >= 4*P for
training-shaped batches. All shapes static; the schedule is a
`lax.scan` over step indices, jit/GSPMD-compatible.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models import qwen3
from ..models.config import DecoderConfig
from ..ops import rope_angles


def pipeline_spec(n_stages: int, devices=None) -> Mesh:
    devs = devices if devices is not None else jax.devices()
    if len(devs) < n_stages:
        raise ValueError(
            f"pipeline of {n_stages} stages needs {n_stages} devices, "
            f"have {len(devs)}"
        )
    return Mesh(np.array(devs[:n_stages]).reshape(n_stages), ("pp",))


def shard_params_for_pipeline(params, cfg: DecoderConfig, mesh: Mesh):
    """Layer-stacked tensors shard over pp on axis 0; embed/head/norm
    replicate."""
    pp = mesh.shape["pp"]
    if cfg.n_layers % pp != 0:
        raise ValueError(
            f"n_layers {cfg.n_layers} not divisible by pp={pp}"
        )
    layer_spec = jax.tree.map(
        lambda a: P(*(("pp",) + (None,) * (a.ndim - 1))),
        params["layers"],
    )
    out = dict(params)
    out["layers"] = jax.tree.map(
        lambda a, s: jax.device_put(a, NamedSharding(mesh, s)),
        params["layers"], layer_spec,
    )
    return out


def pipeline_forward(
    params,
    cfg: DecoderConfig,
    tokens: jax.Array,          # [B, S], B divisible by n_microbatches
    *,
    mesh: Mesh,
    n_microbatches: int,
) -> jax.Array:
    """Full forward with the trunk pipelined over the pp axis. Returns
    logits [B, S, V]; numerics match qwen3.forward (same params)."""
    b, s = tokens.shape
    m = n_microbatches
    if b % m != 0:
        raise ValueError(f"batch {b} not divisible by microbatches {m}")
    pp = mesh.shape["pp"]
    mb = b // m

    # replicated pre/post compute
    x = params["embed"][tokens]                       # [B, S, D]
    positions = jnp.broadcast_to(jnp.arange(s)[None], (b, s))
    cos, sin = rope_angles(positions, cfg.head_dim, cfg.rope_theta)
    xs = x.reshape(m, mb, s, -1)
    cos_m = cos.reshape(m, mb, *cos.shape[1:])
    sin_m = sin.reshape(m, mb, *sin.shape[1:])

    def local(layers_local, xs, cos_m, sin_m):
        """One pipeline stage. layers_local: this stage's [L/pp, ...]
        slice; xs/cos/sin arrive replicated [M, mb, ...]."""
        stage = jax.lax.axis_index("pp")
        pos_q = jnp.broadcast_to(
            jnp.arange(s)[None], (mb, s)
        )

        def run_stage(x_mb, cos_mb, sin_mb):
            def body(carry, lp):
                y, _ = qwen3._layer(
                    cfg, qwen3.attention_ref, carry, lp, cos_mb,
                    sin_mb, None, None, None, pos_q,
                )
                return y, None

            out, _ = jax.lax.scan(body, x_mb, layers_local)
            return out

        n_steps = m + pp - 1
        zero = jnp.zeros_like(xs[0])
        outputs = jnp.zeros_like(xs)

        def step(carry, t):
            current, outputs = carry
            # stage 0 ingests microbatch t (or keeps garbage past M —
            # masked out when collecting)
            feed_idx = jnp.clip(t, 0, m - 1)
            current = jnp.where(
                stage == 0,
                jnp.where(t < m, xs[feed_idx], zero),
                current,
            )
            cos_mb = cos_m[jnp.clip(t - stage, 0, m - 1)]
            sin_mb = sin_m[jnp.clip(t - stage, 0, m - 1)]
            y = run_stage(current, cos_mb, sin_mb)
            # the LAST stage finishes microbatch t-(pp-1) at step t
            done_idx = jnp.clip(t - (pp - 1), 0, m - 1)
            outputs = jnp.where(
                (stage == pp - 1) & (t >= pp - 1),
                outputs.at[done_idx].set(y),
                outputs,
            )
            # rotate activations one stage forward
            current = jax.lax.ppermute(
                y, "pp",
                [(i, (i + 1) % pp) for i in range(pp)],
            )
            return (current, outputs), None

        # jax 0.9 shard_map: loop carries become axis-varying after the
        # first step (axis_index/ppermute), so the initial values must
        # be pcast to varying or scan rejects the carry types
        init = (
            jax.lax.pcast(zero, ("pp",), to="varying"),
            jax.lax.pcast(outputs, ("pp",), to="varying"),
        )
        (_, outputs), _ = jax.lax.scan(
            step, init, jnp.arange(n_steps)
        )
        # only the last stage holds real outputs; broadcast them back
        outputs = jax.lax.psum(
            jnp.where(stage == pp - 1, outputs, jnp.zeros_like(outputs)),
            "pp",
        )
        return outputs

    fn = jax.shard_map(
        local,
        mesh=mesh,
        in_specs=(
            jax.tree.map(
                lambda a: P(*(("pp",) + (None,) * (a.ndim - 1))),
                params["layers"],
            ),
            P(),  # microbatches replicated
            P(),
            P(),
        ),
        out_specs=P(),
    )
    hidden = fn(params["layers"], xs, cos_m, sin_m)   # [M, mb, S, D]
    hidden = hidden.reshape(b, s, -1)
    return qwen3._head(params, cfg, hidden)
