"""Multi-host initialization: the DCN story (SURVEY §2.7 — the role the
reference family delegates to NCCL/MPI launchers; here it is
`jax.distributed` + XLA collectives, which ride ICI within a slice and
DCN across hosts).

One call per process, before any other JAX use:

    initialize_multihost()            # env-driven (see below)
    mesh = make_global_mesh(MeshSpec(dp=2, ep=2, tp=2))

Env contract (mirrors the usual TPU pod launcher variables):
    ROOM_TPU_COORDINATOR   host:port of process 0
    ROOM_TPU_NUM_PROCESSES world size
    ROOM_TPU_PROCESS_ID    this process's rank

After initialization `jax.devices()` is GLOBAL (every host's chips);
meshes built from it produce programs whose collectives span hosts —
the same `psum`/`all_gather`/`ppermute` code that runs single-host.
Data order note: `make_global_mesh` keeps device order host-major so
the dp axis splits across hosts first (gradient all-reduce inside a
host rides ICI; only the cross-host slice crosses DCN).

KV wire (docs/disagg.md)
------------------------
The second half of this module is the *host-side* DCN story: shipping
finished prefill KV between replicas that do not share a process (or a
host). A shipment is one spool-format payload — the exact bytes a
``.kvspool`` file holds — framed as::

    b"RTKW" | u32 version | u64 header_len | header json
           | u64 payload_len | payload bytes

The header carries the manifest-style session entry (history, pending
token, generation, kv metadata incl. the spool's sha256) plus the
donor's config fingerprint; the receiver hashes the payload while
writing it to its local spool dir and refuses a digest mismatch — the
same checksum contract ``.kvspool`` files already have, applied in
transit. A refused/lost/corrupt shipment degrades to the router's
history-mirror re-prefill, never a misroute: ``kv_wire`` is the fault
point. ``KVWireServer`` is the receiving end (one per fleet/host);
``kv_wire_send`` the sending call. Tests and single-host deployments
run it over loopback (ROOM_TPU_DISAGG_WIRE=loopback); a cross-host pod
points the sender at the decode host's listener.

Pod hardening (docs/podnet.md)
------------------------------
``kv_wire_send`` and ``wire_send_control`` retry transport failures
(``ROOM_TPU_WIRE_RETRIES`` attempts, jittered exponential backoff)
behind a per-peer circuit breaker (``serving/podnet.py``): a
partitioned peer costs one fast ``KVWireError`` once the breaker
opens, not a timeout per shipment, and a half-open probe per cooldown
re-closes it when the peer heals. A receiver REFUSAL
(``KVWireRefused``) is an application answer from a reachable peer —
it feeds the breaker a success and is never retried. The
``wire_partition`` fault point fails individual connection attempts
so chaos tests drive retry, breaker, and exhaustion separately from
the whole-shipment ``kv_wire`` point. Control frames (same RTKW
framing, ``header["control"]`` instead of a session entry, empty
payload) carry pod membership heartbeats and the sharded router
tier's placement-map epochs (``wire_broadcast_control`` fans one
frame out to every ``ROOM_TPU_POD_PEERS`` member); session entries
carry their ownership fence token (``entry["fence"]``) for the
receiver's stale-generation refusal. The server handles each connection on its
own bounded worker thread — one wedged peer can no longer hold the
acceptor — and reports a failed accept-thread join in ``stats()``
instead of silently proceeding.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import socket
import struct
import threading
import time
from typing import Callable, Optional

import jax
import numpy as np
from jax.sharding import Mesh

from .mesh import AXES, MeshSpec
from ..utils import knobs, locks


def initialize_multihost(
    coordinator: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    timeout_s: Optional[float] = None,
) -> bool:
    """Idempotent `jax.distributed.initialize` from args or env.
    Returns True when multi-process mode is active (False = single
    process, nothing to do).

    ``timeout_s`` (env ROOM_TPU_DCN_TIMEOUT_S) bounds the coordinator
    barrier: a pod launcher whose process 0 never came up must fail
    fast with a clear error, not hang for JAX's default five minutes
    (failure-detection contract, SURVEY §5)."""
    coordinator = coordinator or knobs.get_str("ROOM_TPU_COORDINATOR")
    if num_processes is None:
        raw = knobs.get_raw("ROOM_TPU_NUM_PROCESSES")
        num_processes = int(raw) if raw else None
    if process_id is None:
        raw = knobs.get_raw("ROOM_TPU_PROCESS_ID")
        process_id = int(raw) if raw else None
    if timeout_s is None:
        raw = knobs.get_raw("ROOM_TPU_DCN_TIMEOUT_S")
        timeout_s = float(raw) if raw else None

    if not coordinator or not num_processes or num_processes <= 1:
        return False
    if process_id is not None and not (
        0 <= process_id < num_processes
    ):
        raise ValueError(
            f"ROOM_TPU_PROCESS_ID={process_id} outside world size "
            f"{num_processes}"
        )
    # probe initialization state WITHOUT jax.process_count(): that
    # would initialize the XLA backend, after which distributed
    # initialize refuses to run
    from jax._src import distributed as _dist

    if getattr(_dist.global_state, "coordinator_address", None):
        return True  # already initialized
    kwargs = {}
    if timeout_s is not None:
        kwargs["initialization_timeout"] = int(timeout_s)
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id or 0,
        **kwargs,
    )
    return True


def make_global_mesh(spec: MeshSpec) -> Mesh:
    """dp/ep/tp mesh over the GLOBAL device list, host-major so model
    axes (ep/tp) stay within a host wherever the shape allows —
    their collectives are latency-sensitive and belong on ICI."""
    devs = jax.devices()
    if len(devs) < spec.n_devices:
        raise ValueError(
            f"mesh {spec} needs {spec.n_devices} devices, have "
            f"{len(devs)} across {jax.process_count()} processes"
        )
    arr = np.array(devs[: spec.n_devices]).reshape(
        spec.dp, spec.ep, spec.tp
    )
    return Mesh(arr, AXES)


# ---- KV wire: cross-host prefill->decode shipments (docs/disagg.md) ----

log = logging.getLogger(__name__)

WIRE_MAGIC = b"RTKW"
WIRE_VERSION = 1
# a header is a session entry (token ints) + kv metadata: far under
# this, and an unbounded length prefix must never allocate unbounded
_MAX_HEADER = 64 * 1024 * 1024
# payloads are KV spool bytes; generous but bounded
_MAX_PAYLOAD = 64 * 1024 * 1024 * 1024


class KVWireError(RuntimeError):
    """A shipment failed in transit (socket error, protocol garbage,
    checksum mismatch, receiver refusal). Callers degrade to the
    history-mirror re-prefill — this error never propagates past the
    ship coordinator."""


class KVWireRefused(KVWireError):
    """The peer ANSWERED and refused (stale fence, unknown target,
    checksum mismatch). Reachability is proven, so the breaker books a
    success and the retry loop never re-sends — a deterministic
    refusal repeated N times is still a refusal."""


def wire_timeout_s() -> float:
    try:
        return max(0.1, knobs.get_float("ROOM_TPU_KV_WIRE_TIMEOUT_S"))
    except ValueError:
        return 10.0


def _recv_exact(conn: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = conn.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            raise KVWireError("connection closed mid-frame")
        buf.extend(chunk)
    return bytes(buf)


def _send_json(conn: socket.socket, obj: dict) -> None:
    raw = json.dumps(obj, separators=(",", ":")).encode()
    conn.sendall(struct.pack("<Q", len(raw)) + raw)


def _recv_json(conn: socket.socket, cap: int = _MAX_HEADER) -> dict:
    (n,) = struct.unpack("<Q", _recv_exact(conn, 8))
    if n > cap:
        raise KVWireError(f"oversized wire frame ({n} bytes)")
    try:
        obj = json.loads(_recv_exact(conn, n).decode())
    except ValueError as e:
        raise KVWireError(f"bad wire json: {e}") from e
    if not isinstance(obj, dict):
        raise KVWireError("wire frame is not an object")
    return obj


def _wire_attempt(
    address: tuple[str, int],
    raw: bytes,
    src: Optional[str],
    payload_len: int,
    timeout_s: float,
) -> dict:
    """One framed send + reply read; raises KVWireError on transport
    failure."""
    try:
        with socket.create_connection(
            address, timeout=timeout_s
        ) as conn:
            conn.sendall(
                WIRE_MAGIC + struct.pack("<I", WIRE_VERSION)
                + struct.pack("<Q", len(raw)) + raw
                + struct.pack("<Q", payload_len)
            )
            if payload_len:
                with open(src, "rb") as f:
                    while True:
                        chunk = f.read(1 << 20)
                        if not chunk:
                            break
                        conn.sendall(chunk)
            return _recv_json(conn)
    except (OSError, struct.error) as e:
        raise KVWireError(f"wire send failed: {e}") from e


def _send_with_retry(
    address: tuple[str, int],
    header: dict,
    src: Optional[str],
    payload_len: int,
    timeout_s: Optional[float],
    retries: Optional[int],
) -> dict:
    """Bounded-retry, breaker-guarded frame send (docs/podnet.md):
    transport failures (and the ``wire_partition`` fault) consume
    attempts with jittered backoff between them; an open breaker
    refuses fast; a receiver refusal raises ``KVWireRefused`` without
    burning retries. Exhaustion raises KVWireError — the caller owns
    the degrade-to-mirror contract."""
    from ..serving import faults, podnet
    from ..serving.faults import FaultError

    timeout_s = timeout_s if timeout_s is not None else wire_timeout_s()
    attempts = retries if retries is not None else podnet.wire_retries()
    attempts = max(1, attempts)
    raw = json.dumps(header, separators=(",", ":")).encode()
    breaker = podnet.breaker_for(address)
    last: Optional[Exception] = None
    for attempt in range(attempts):
        if not breaker.allow():
            raise KVWireError(
                f"circuit open to {address[0]}:{address[1]} "
                f"({breaker.snapshot()['consecutive_failures']} "
                "consecutive failures)"
            )
        try:
            faults.maybe_fail("wire_partition")
            reply = _wire_attempt(
                address, raw, src, payload_len, timeout_s
            )
        except (KVWireError, FaultError) as e:
            breaker.record_failure()
            last = e
            if attempt + 1 < attempts:
                delay = podnet.wire_backoff_s(attempt)
                if delay > 0:
                    time.sleep(delay)
            continue
        if not reply.get("ok") and reply.get("retryable"):
            # transient backpressure (e.g. a saturated receiver): a
            # real failure for the breaker and the retry budget, NOT
            # an application refusal — one backoff later the peer may
            # have a free handler slot
            breaker.record_failure()
            last = KVWireError(
                f"receiver backpressure: {reply.get('error')}"
            )
            if attempt + 1 < attempts:
                delay = podnet.wire_backoff_s(attempt)
                if delay > 0:
                    time.sleep(delay)
            continue
        # the peer answered: reachability proven either way
        breaker.record_success()
        if not reply.get("ok"):
            raise KVWireRefused(
                f"receiver refused shipment: {reply.get('error')}"
            )
        return reply
    raise KVWireError(
        f"wire send exhausted {attempts} attempt(s): {last}"
    )


def kv_wire_send(
    address: tuple[str, int],
    entry: dict,
    *,
    fingerprint: Optional[dict] = None,
    target_rid: Optional[str] = None,
    timeout_s: Optional[float] = None,
    retries: Optional[int] = None,
) -> dict:
    """Ship one manifest-style session entry (and its spool file's
    bytes, when ``entry['kv']`` names one) to a ``KVWireServer``.
    Returns the receiver's reply dict; raises KVWireError on any
    transport/protocol/refusal failure — the caller owns the
    degrade-to-re-prefill fallback. The local spool file is NOT
    consumed; the caller unlinks it after a successful send.

    Transport failures retry with jittered backoff behind the peer's
    circuit breaker (docs/podnet.md); the entry's ``fence`` rides the
    frame so the receiver can refuse a stale-generation export."""
    from ..serving import faults

    faults.maybe_fail("kv_wire")
    kv = entry.get("kv") if isinstance(entry.get("kv"), dict) else None
    src = str(kv["file"]) if kv and kv.get("file") else None
    header_entry = dict(entry)
    payload_len = 0
    if kv is not None and src:
        try:
            payload_len = os.path.getsize(src)
        except OSError as e:
            raise KVWireError(f"spool file unreadable: {e}") from e
        kv = dict(kv)
        kv["file"] = os.path.basename(src)
        header_entry["kv"] = kv
    else:
        header_entry["kv"] = None
    header = {
        "entry": header_entry,
        "fingerprint": fingerprint,
        "target_rid": target_rid,
        "payload_sha256": (kv or {}).get("sha256"),
    }
    return _send_with_retry(
        address, header, src, payload_len, timeout_s, retries
    )


def wire_send_control(
    address: tuple[str, int],
    control: dict,
    *,
    timeout_s: Optional[float] = None,
    retries: Optional[int] = None,
) -> dict:
    """Send one payloadless control frame (pod heartbeats,
    docs/podnet.md) through the same RTKW framing, retry policy, and
    per-peer breaker as a KV shipment."""
    return _send_with_retry(
        address, {"control": control}, None, 0, timeout_s, retries
    )


def wire_broadcast_control(
    addresses,
    control: dict,
    *,
    timeout_s: Optional[float] = None,
    retries: Optional[int] = None,
) -> dict:
    """Fan one control frame (a placement-map epoch, an adoption
    notice) out to every peer address. Best-effort per peer: a
    partitioned member costs its breaker-bounded refusal and an error
    entry in the result, never the other peers' delivery — the
    replication contract is 'newest epoch wins at each receiver', not
    atomic broadcast. Returns peer-key -> reply dict (or
    ``{"error": ...}``)."""
    out: dict = {}
    for address in addresses:
        key = f"{address[0]}:{address[1]}" if isinstance(
            address, (tuple, list)
        ) else str(address)
        try:
            out[key] = wire_send_control(
                tuple(address), control,
                timeout_s=timeout_s, retries=retries,
            )
        except (KVWireError, KVWireRefused, OSError) as e:
            out[key] = {"error": str(e)[:200]}
    return out


class KVWireServer:
    """Receiving end of the KV wire: accepts framed shipments, writes
    the spool payload into ``spool_dir`` (sha256 verified in transit,
    atomic rename, receiver-PID-tagged so the dir's orphan sweeps
    protect it), then hands the localized entry to ``on_entry`` —
    the fleet adopts it into a decode replica there — and replies with
    that callback's dict. Control frames (``header["control"]`` —
    pod heartbeats, docs/podnet.md) dispatch to ``on_control``.

    One listener per fleet/host. Each accepted connection is handled
    on its own bounded worker thread (capped at ``max_handlers``,
    every read under the wire timeout): a peer that wedges mid-frame
    stalls only its handler, never the acceptor — heartbeats keep
    landing while a partition strands a shipment. At boot the spool
    dir is swept of payloads a DEAD receiver process persisted but
    never adopted (a crash between persist and adopt; the dead-PID
    check in lifecycle.sweep_orphans — live siblings' files are
    untouchable)."""

    def __init__(
        self,
        spool_dir: str,
        on_entry: Callable[[dict, Optional[dict], Optional[str]], dict],
        host: str = "127.0.0.1",
        port: Optional[int] = None,
        on_control: Optional[Callable[[dict], dict]] = None,
        max_handlers: int = 16,
    ) -> None:
        self.spool_dir = spool_dir
        self.on_entry = on_entry
        self.on_control = on_control
        self.max_handlers = max(1, max_handlers)
        if port is None:
            try:
                port = knobs.get_int("ROOM_TPU_KV_WIRE_PORT")
            except ValueError:
                port = 0
        os.makedirs(spool_dir, exist_ok=True)
        # wire-received payload files are PID-tagged by THIS receiver
        # at persist time; one left by a receiver that crashed between
        # persist and adopt has no consumer — sweep it now (age 0: the
        # dead-PID / live-PID check is the whole guard here)
        try:
            from ..serving.lifecycle import sweep_orphans

            swept = sweep_orphans(spool_dir, max_age_s=0.0)
        except Exception:
            swept = 0
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(8)
        self._sock.settimeout(0.25)
        self.address: tuple[str, int] = self._sock.getsockname()[:2]
        self._stop = threading.Event()
        self._lock = locks.make_lock("kv_wire_server")
        self._seq = 0
        self._handlers = 0
        self._stats = {
            "frames": 0, "control_frames": 0, "refusals": 0,
            "handler_errors": 0, "handlers_capped": 0,
            "orphans_swept": swept, "accept_join_failed": 0,
        }
        self._thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name=f"kv-wire-{self.address[1]}",
        )
        self._thread.start()

    def _bump(self, key: str, n: int = 1) -> None:
        with self._lock:
            self._stats[key] += n

    def stats(self) -> dict:
        """Receive counters + liveness for health surfaces: a non-zero
        ``accept_join_failed`` means a close() left the accept thread
        wedged (reported, like ModelHost.shutdown, never silent)."""
        with self._lock:
            out = dict(self._stats)
            out["open_handlers"] = self._handlers
        out["address"] = list(self.address)
        out["accept_alive"] = self._thread.is_alive()
        return out

    def close(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        self._thread.join(timeout=5.0)
        if self._thread.is_alive():
            # a wedged accept thread is an operational fact, not a
            # silent shrug: count it where stats()/health can see it
            self._bump("accept_join_failed")
            log.warning(
                "kv wire %s: accept thread did not join within 5s; "
                "proceeding (reported in stats)", self.address,
            )

    # operational alias (docs/podnet.md runbook speaks of stopping
    # the listener)
    stop = close

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            with self._lock:
                capped = self._handlers >= self.max_handlers
                if not capped:
                    self._handlers += 1
            if capped:
                # every handler slot is wedged/busy: answer FAST with
                # a RETRYABLE refusal — the sender's retry loop books
                # it as a transport-class failure (breaker failure,
                # backoff, retry), never as an application refusal a
                # heartbeat or shipment would give up on
                self._bump("handlers_capped")
                try:
                    conn.settimeout(1.0)
                    _send_json(conn, {
                        "ok": False, "retryable": True,
                        "error": "receiver saturated",
                    })
                except OSError:
                    pass
                try:
                    conn.close()
                except OSError:
                    pass
                continue
            try:
                threading.Thread(
                    target=self._handle_conn, args=(conn,),
                    daemon=True,
                    name=f"kv-wire-conn-{self.address[1]}",
                ).start()
            except RuntimeError:
                # thread exhaustion: give the slot and the socket
                # back and keep ACCEPTING — the acceptor dying here
                # would silently kill the whole receive side
                self._bump("handler_errors")
                with self._lock:
                    self._handlers -= 1
                try:
                    conn.close()
                except OSError:
                    pass

    def _handle_conn(self, conn: socket.socket) -> None:
        try:
            with conn:
                conn.settimeout(wire_timeout_s())
                self._serve_one(conn)
        except Exception:
            self._bump("handler_errors")
            log.exception("kv wire: connection handler failed")
        finally:
            with self._lock:
                self._handlers -= 1

    def _serve_one(self, conn: socket.socket) -> None:
        try:
            reply = self._receive(conn)
        except KVWireError as e:
            reply = {"ok": False, "error": str(e)}
        except Exception as e:   # noqa: BLE001 — reply, never die
            reply = {"ok": False, "error": f"receiver error: {e}"}
        if not reply.get("ok", True):
            self._bump("refusals")
        try:
            _send_json(conn, reply)
        except OSError:
            pass

    def _receive(self, conn: socket.socket) -> dict:
        magic = _recv_exact(conn, 4)
        if magic != WIRE_MAGIC:
            raise KVWireError(f"bad magic {magic!r}")
        (version,) = struct.unpack("<I", _recv_exact(conn, 4))
        if version != WIRE_VERSION:
            raise KVWireError(f"unsupported wire version {version}")
        (hdr_len,) = struct.unpack("<Q", _recv_exact(conn, 8))
        if hdr_len > _MAX_HEADER:
            raise KVWireError(f"oversized header ({hdr_len} bytes)")
        try:
            header = json.loads(_recv_exact(conn, hdr_len).decode())
        except ValueError as e:
            raise KVWireError(f"bad header json: {e}") from e
        (payload_len,) = struct.unpack("<Q", _recv_exact(conn, 8))
        if payload_len > _MAX_PAYLOAD:
            raise KVWireError(f"oversized payload ({payload_len} bytes)")
        control = header.get("control")
        if isinstance(control, dict):
            # control frame (pod heartbeat): payloadless by contract
            if payload_len:
                raise KVWireError("control frame with payload")
            self._bump("control_frames")
            if self.on_control is None:
                raise KVWireError("receiver handles no control frames")
            result = self.on_control(control)
            out = {"ok": True}
            if isinstance(result, dict):
                out.update(result)
            return out
        entry = header.get("entry")
        if not isinstance(entry, dict):
            raise KVWireError("header missing entry")
        self._bump("frames")
        # the shipment-loss fault scopes to SHIPMENTS: heartbeats ride
        # the same framing but model their loss via heartbeat_loss
        from ..serving import faults

        faults.maybe_fail("kv_wire")
        kv = entry.get("kv") if isinstance(entry.get("kv"), dict) \
            else None
        if payload_len and kv is not None:
            # handler threads race the counter now
            with self._lock:
                self._seq += 1
                seq = self._seq
            fname = f"pid{os.getpid()}-wire{seq}-" \
                f"{os.path.basename(str(kv.get('file') or 'kv'))}"
            if not fname.endswith(".kvspool"):
                fname += ".kvspool"
            path = os.path.join(self.spool_dir, fname)
            tmp = path + ".tmp"
            h = hashlib.sha256()
            remaining = payload_len
            try:
                with open(tmp, "wb") as f:
                    while remaining:
                        chunk = conn.recv(min(1 << 20, remaining))
                        if not chunk:
                            raise KVWireError(
                                "connection closed mid-payload"
                            )
                        f.write(chunk)
                        h.update(chunk)
                        remaining -= len(chunk)
            except KVWireError:
                # a sender dying mid-payload must not leave the
                # partial .tmp on disk any more than an I/O error does
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise
            except OSError as e:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise KVWireError(f"payload write failed: {e}") from e
            expected = header.get("payload_sha256")
            if expected and h.hexdigest() != expected:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise KVWireError("payload checksum mismatch")
            os.replace(tmp, path)
            kv = dict(kv)
            kv["file"] = path
            # the receiver recomputed the digest over the exact bytes
            # it persisted: that is the sha the adopting store should
            # verify lazily at first read
            kv["sha256"] = h.hexdigest()
            kv["nbytes"] = payload_len
            entry = dict(entry)
            entry["kv"] = kv
        else:
            if payload_len:
                # payload with no kv record: drain it to keep the
                # connection sane, then refuse
                remaining = payload_len
                while remaining:
                    chunk = conn.recv(min(1 << 20, remaining))
                    if not chunk:
                        break
                    remaining -= len(chunk)
                raise KVWireError("payload without kv metadata")
            entry = dict(entry)
            entry["kv"] = None
        persisted = (entry.get("kv") or {}).get("file") \
            if isinstance(entry.get("kv"), dict) else None
        try:
            result = self.on_entry(
                entry, header.get("fingerprint"),
                header.get("target_rid"),
            )
        except Exception:
            # the callback never queued an adoption: the persisted
            # spool has no consumer — drop it, don't fill wire-in
            if persisted:
                try:
                    os.unlink(persisted)
                except OSError:
                    pass
            raise
        out = {"ok": True}
        if isinstance(result, dict):
            out.update(result)
        if not out.get("ok", True) and persisted:
            # an explicit refusal (e.g. named target not serving) also
            # leaves the spool unowned; a queued-but-slow adoption
            # replies ok=True and keeps it
            try:
                os.unlink(persisted)
            except OSError:
                pass
        return out
