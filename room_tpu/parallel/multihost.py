"""Multi-host initialization: the DCN story (SURVEY §2.7 — the role the
reference family delegates to NCCL/MPI launchers; here it is
`jax.distributed` + XLA collectives, which ride ICI within a slice and
DCN across hosts).

One call per process, before any other JAX use:

    initialize_multihost()            # env-driven (see below)
    mesh = make_global_mesh(MeshSpec(dp=2, ep=2, tp=2))

Env contract (mirrors the usual TPU pod launcher variables):
    ROOM_TPU_COORDINATOR   host:port of process 0
    ROOM_TPU_NUM_PROCESSES world size
    ROOM_TPU_PROCESS_ID    this process's rank

After initialization `jax.devices()` is GLOBAL (every host's chips);
meshes built from it produce programs whose collectives span hosts —
the same `psum`/`all_gather`/`ppermute` code that runs single-host.
Data order note: `make_global_mesh` keeps device order host-major so
the dp axis splits across hosts first (gradient all-reduce inside a
host rides ICI; only the cross-host slice crosses DCN).
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh

from .mesh import AXES, MeshSpec
from ..utils import knobs


def initialize_multihost(
    coordinator: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    timeout_s: Optional[float] = None,
) -> bool:
    """Idempotent `jax.distributed.initialize` from args or env.
    Returns True when multi-process mode is active (False = single
    process, nothing to do).

    ``timeout_s`` (env ROOM_TPU_DCN_TIMEOUT_S) bounds the coordinator
    barrier: a pod launcher whose process 0 never came up must fail
    fast with a clear error, not hang for JAX's default five minutes
    (failure-detection contract, SURVEY §5)."""
    coordinator = coordinator or knobs.get_str("ROOM_TPU_COORDINATOR")
    if num_processes is None:
        raw = knobs.get_raw("ROOM_TPU_NUM_PROCESSES")
        num_processes = int(raw) if raw else None
    if process_id is None:
        raw = knobs.get_raw("ROOM_TPU_PROCESS_ID")
        process_id = int(raw) if raw else None
    if timeout_s is None:
        raw = knobs.get_raw("ROOM_TPU_DCN_TIMEOUT_S")
        timeout_s = float(raw) if raw else None

    if not coordinator or not num_processes or num_processes <= 1:
        return False
    if process_id is not None and not (
        0 <= process_id < num_processes
    ):
        raise ValueError(
            f"ROOM_TPU_PROCESS_ID={process_id} outside world size "
            f"{num_processes}"
        )
    # probe initialization state WITHOUT jax.process_count(): that
    # would initialize the XLA backend, after which distributed
    # initialize refuses to run
    from jax._src import distributed as _dist

    if getattr(_dist.global_state, "coordinator_address", None):
        return True  # already initialized
    kwargs = {}
    if timeout_s is not None:
        kwargs["initialization_timeout"] = int(timeout_s)
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id or 0,
        **kwargs,
    )
    return True


def make_global_mesh(spec: MeshSpec) -> Mesh:
    """dp/ep/tp mesh over the GLOBAL device list, host-major so model
    axes (ep/tp) stay within a host wherever the shape allows —
    their collectives are latency-sensitive and belong on ICI."""
    devs = jax.devices()
    if len(devs) < spec.n_devices:
        raise ValueError(
            f"mesh {spec} needs {spec.n_devices} devices, have "
            f"{len(devs)} across {jax.process_count()} processes"
        )
    arr = np.array(devs[: spec.n_devices]).reshape(
        spec.dp, spec.ep, spec.tp
    )
    return Mesh(arr, AXES)
