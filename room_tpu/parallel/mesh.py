"""Device mesh construction and sharding rules.

The communication backend of the framework is XLA collectives over the
mesh (ICI within a slice, DCN across slices) — the role NCCL/MPI plays in
GPU frameworks; the reference had no collective backend at all (SURVEY.md
§2.7). Axes:

- ``dp``: data/batch parallelism (independent sequences; no collectives
  on the forward path)
- ``ep``: expert parallelism for MoE layers (all-to-all style exchange,
  delegated to XLA's SPMD partitioner from sharding annotations)
- ``tp``: tensor parallelism (megatron-style head/ffn sharding;
  all-reduce on the residual stream)

A 2D mesh is the workhorse: v5e-8 serving the 30B runs (dp=1, ep=4,
tp=2) or (ep=8,); the 72B queen runs pure tp. Sequence parallelism for
long context lives in room_tpu.parallel.ring (its own axis over
shard_map).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.config import DecoderConfig, EncoderConfig

AXES = ("dp", "ep", "tp")


@dataclass(frozen=True)
class MeshSpec:
    dp: int = 1
    ep: int = 1
    tp: int = 1

    @property
    def n_devices(self) -> int:
        return self.dp * self.ep * self.tp


def make_mesh(
    spec: MeshSpec, devices: Optional[list] = None
) -> Mesh:
    devs = devices if devices is not None else jax.devices()
    if len(devs) < spec.n_devices:
        raise ValueError(
            f"mesh {spec} needs {spec.n_devices} devices, have {len(devs)}"
        )
    arr = np.array(devs[: spec.n_devices]).reshape(spec.dp, spec.ep, spec.tp)
    return Mesh(arr, AXES)


def parse_mesh_spec(value: str) -> tuple[MeshSpec, int]:
    """Parse a mesh env string ``"dp,ep,tp"`` or ``"dp,ep,tp@start"``.

    The optional ``@start`` selects a device offset so independent models
    can occupy disjoint submeshes of one pod — the hetero-swarm layout
    (BASELINE.md config #5: the 72B queen on one slice of chips, 30B
    workers on the rest). Returns (spec, device_start).
    """
    body, _, off = value.partition("@")
    dp, ep, tp = (int(x) for x in body.split(","))
    start = int(off) if off else 0
    if start < 0:
        raise ValueError(f"negative device offset in mesh spec {value!r}")
    return MeshSpec(dp, ep, tp), start


def make_submesh(spec: MeshSpec, start: int) -> Mesh:
    """Mesh over the device window [start, start+n) of jax.devices()."""
    devs = jax.devices()
    if start + spec.n_devices > len(devs):
        raise ValueError(
            f"submesh {spec}@{start} needs devices "
            f"[{start},{start + spec.n_devices}), have {len(devs)}"
        )
    return make_mesh(spec, devs[start:start + spec.n_devices])


# ---- sharding rules ----

def decoder_param_specs(cfg: DecoderConfig) -> dict[str, Any]:
    """PartitionSpec pytree matching models.qwen3.init_params.

    Layer-stacked arrays lead with the (unsharded) layer axis. Attention
    and dense-FFN weights shard megatron-style over ``tp``; expert weights
    shard over ``ep`` on the expert axis and ``tp`` on the hidden-expansion
    axis; the vocab axes of embed/lm_head shard over ``tp``.
    """
    layers: dict[str, Any] = {
        "wq": P(None, None, "tp"),
        "wk": P(None, None, "tp"),
        "wv": P(None, None, "tp"),
        "wo": P(None, "tp", None),
        "ln1": P(None, None),
        "ln2": P(None, None),
    }
    if cfg.qkv_bias:
        layers["bq"] = P(None, "tp")
        layers["bk"] = P(None, "tp")
        layers["bv"] = P(None, "tp")
    if cfg.qk_norm:
        layers["q_norm"] = P(None, None)
        layers["k_norm"] = P(None, None)
    if cfg.is_moe:
        layers["router"] = P(None, None, None)
        layers["w_gate"] = P(None, "ep", None, "tp")
        layers["w_up"] = P(None, "ep", None, "tp")
        layers["w_down"] = P(None, "ep", "tp", None)
    else:
        layers["w_gate"] = P(None, None, "tp")
        layers["w_up"] = P(None, None, "tp")
        layers["w_down"] = P(None, "tp", None)
    specs: dict[str, Any] = {
        "embed": P("tp", None),
        "layers": layers,
        "final_norm": P(None),
    }
    if not cfg.tie_embeddings:
        specs["lm_head"] = P(None, "tp")
    return specs


def kv_cache_specs(cfg: DecoderConfig) -> dict[str, Any]:
    """Cache shards over batch (dp); KV heads are few (GQA), so they stay
    replicated across tp rather than forcing head-count divisibility."""
    return {
        "k": P(None, "dp", None, None, None),
        "v": P(None, "dp", None, None, None),
        "lengths": P("dp"),
    }


def page_cache_specs(
    cfg: DecoderConfig, mesh: Mesh, quant: Optional[str] = None
) -> dict[str, Any]:
    """Sharding rule for the serving engine's paged KV pool
    [L, n_pages, page_size, Hkv, Dh].

    The page axis is addressed dynamically by block tables, so it stays
    replicated over dp/ep; the KV-head axis shards over ``tp`` alongside
    the attention weights (qwen3-30b: 4 kv heads, qwen2-72b: 8 — both
    divide the practical tp sizes). Falls back to replicated heads when
    tp doesn't divide, mirroring kv_cache_specs' GQA posture.
    """
    tp = mesh.shape.get("tp", 1)
    head_ax = "tp" if tp > 1 and cfg.n_kv_heads % tp == 0 else None
    spec = P(None, None, None, head_ax, None)
    out = {"k_pages": spec, "v_pages": spec}
    if quant == "int8":
        # scales [L, n_pages, page, Hkv] shard with their pages
        sspec = P(None, None, None, head_ax)
        out["k_scale"] = sspec
        out["v_scale"] = sspec
    return out


# ---- dp-sharded fused window: declarative sub-batch layout ----
#
# The engine's fused dispatch window (docs/serving.md) addresses every
# array it stages — decode-lane vectors, the ragged token stream, the
# chunk sub-batch, the spec emission ring — by NAME through this
# regex -> axes rule table (first match wins) instead of hard-coding a
# PartitionSpec at each call site. The table is the single place the
# dp-sharded layout lives: swapping it re-lays the whole sub-batch
# construction without touching the engine, and the default encodes
# the no-cross-shard-collectives contract (everything leads with the
# dp axis; trailing axes replicated; the page pool itself stays on
# page_cache_specs).
WINDOW_RULES: tuple[tuple[str, tuple], ...] = (
    # ragged [ndp, T_local] token stream + its positions/hidden states
    (r"^(tokens|positions|hidden)$", ("dp",)),
    # chunk sub-batch [ndp*Cl, ...]: shard-major rows, equal blocks
    # per shard, so the leading axis IS the dp axis
    (r"^chunk_", ("dp",)),
    # decode-batch vectors/matrices keyed by slot: [B, ...] with
    # B % dp == 0 (engine admission invariant)
    (r"^(slot|feed|fresh|spec|scan)_", ("dp",)),
    # everything else the window stages follows the slot layout
    (r".*", ("dp",)),
)


def window_spec(
    name: str, ndim: int,
    rules: Optional[tuple[tuple[str, tuple], ...]] = None,
) -> P:
    """Resolve a window array's PartitionSpec from the rule table:
    first pattern matching ``name`` wins; its axes tuple is truncated
    or right-padded with ``None`` to the array's rank (the
    match_partition_rules idiom, specialized to the window's arrays)."""
    import re

    for pat, axes in (WINDOW_RULES if rules is None else rules):
        if re.match(pat, name):
            ax = tuple(axes)[:ndim]
            return P(*(ax + (None,) * (ndim - len(ax))))
    return P()


def window_sharding(
    mesh: Mesh, name: str, ndim: int,
    rules: Optional[tuple[tuple[str, tuple], ...]] = None,
) -> NamedSharding:
    """NamedSharding for one named window array on ``mesh``."""
    return NamedSharding(mesh, window_spec(name, ndim, rules))


def encoder_param_specs(cfg: EncoderConfig) -> dict[str, Any]:
    return {
        "word_embed": P("tp", None),
        "pos_embed": P(None, None),
        "type_embed": P(None, None),
        "embed_ln_scale": P(None),
        "embed_ln_bias": P(None),
        "layers": {
            "wq": P(None, None, "tp"),
            "bq": P(None, "tp"),
            "wk": P(None, None, "tp"),
            "bk": P(None, "tp"),
            "wv": P(None, None, "tp"),
            "bv": P(None, "tp"),
            "wo": P(None, "tp", None),
            "bo": P(None, None),
            "attn_ln_scale": P(None, None),
            "attn_ln_bias": P(None, None),
            "w_in": P(None, None, "tp"),
            "b_in": P(None, "tp"),
            "w_out": P(None, "tp", None),
            "b_out": P(None, None),
            "ffn_ln_scale": P(None, None),
            "ffn_ln_bias": P(None, None),
        },
    }


def shard_pytree(tree: Any, specs: Any, mesh: Mesh) -> Any:
    """Place a pytree onto the mesh per its PartitionSpec pytree."""
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        tree, specs,
        is_leaf=lambda x: isinstance(x, P),
    )
