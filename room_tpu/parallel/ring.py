"""Ring attention: sequence/context parallelism over the mesh.

For sequences too long for one chip's HBM share, q/k/v shard along the
sequence axis; each device computes blockwise attention against its local
KV chunk, then the KV chunks rotate around the ring via ppermute
(ICI-neighbor traffic only) while an online-softmax accumulator folds
each visiting chunk in. After n_devices steps every query has attended
to the full sequence without any device ever holding it.

(SURVEY.md §5: the reference has no long-context path at all — it
shrinks context instead; this is a first-class capability of the
rebuild.)"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

NEG_INF = -1e30


def _block_attention(q, k, v, q_pos, kv_pos, causal, scale):
    """One (q-chunk × kv-chunk) block: returns (pv [*, Hq, D] f32,
    row max m, row sum l) for online-softmax combination.

    q: [B, Cq, Hq, Dh]; k/v: [B, Ckv, Hkv, Dh] (GQA)."""
    b, cq, hq, d = q.shape
    _, ckv, hkv, _ = k.shape
    group = hq // hkv

    qg = q.reshape(b, cq, hkv, group, d).astype(jnp.float32) * scale
    logits = jnp.einsum(
        "bqhgd,bkhd->bhgqk", qg, k.astype(jnp.float32)
    )                                                # [B,Hkv,G,Cq,Ckv]
    if causal:
        mask = kv_pos[None, :] <= q_pos[:, None]     # [Cq, Ckv]
        logits = jnp.where(
            mask[None, None, None], logits, NEG_INF
        )
    m = jnp.max(logits, axis=-1, keepdims=True)      # [B,Hkv,G,Cq,1]
    p = jnp.exp(logits - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    pv = jnp.einsum("bhgqk,bkhd->bhgqd", p, v.astype(jnp.float32))
    return pv, m, l


def ring_attention(
    q: jax.Array,   # [B, S, Hq, Dh] — S sharded over axis `axis_name`
    k: jax.Array,
    v: jax.Array,
    *,
    mesh: Mesh,
    axis_name: str = "sp",
    causal: bool = True,
) -> jax.Array:
    """Full-sequence attention with S sharded over `axis_name`."""
    d = q.shape[-1]
    scale = 1.0 / float(np.sqrt(d))
    n = mesh.shape[axis_name]

    def local_fn(q_loc, k_loc, v_loc):
        idx = jax.lax.axis_index(axis_name)
        b, c, hq, dh = q_loc.shape
        q_pos = idx * c + jnp.arange(c)

        acc = jnp.zeros(
            (b, k_loc.shape[2], hq // k_loc.shape[2], c, dh),
            jnp.float32,
        )
        m = jnp.full(
            (b, k_loc.shape[2], hq // k_loc.shape[2], c, 1), NEG_INF,
            jnp.float32,
        )
        l = jnp.zeros_like(m)
        # constants start unvarying over the manual axis; the loop carry
        # becomes varying, so align the initial types
        acc, m, l = (
            jax.lax.pcast(x, (axis_name,), to="varying")
            for x in (acc, m, l)
        )

        def step(t, carry):
            acc, m, l, k_cur, v_cur = carry
            src = jax.lax.rem(idx - t + n, n)   # owner of the visiting chunk
            kv_pos = src * c + jnp.arange(c)
            pv, m_blk, l_blk = _block_attention(
                q_loc, k_cur, v_cur, q_pos, kv_pos, causal, scale
            )
            m_new = jnp.maximum(m, m_blk)
            alpha = jnp.exp(m - m_new)
            beta = jnp.exp(m_blk - m_new)
            acc = acc * alpha + pv * beta
            l = l * alpha + l_blk * beta
            # rotate kv to the next device (skip after the last step)
            perm = [(i, (i + 1) % n) for i in range(n)]
            k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
            v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
            return acc, m_new, l, k_nxt, v_nxt

        acc, m, l, _, _ = jax.lax.fori_loop(
            0, n, step, (acc, m, l, k_loc, v_loc)
        )
        out = acc / jnp.maximum(l, 1e-30)
        return out.transpose(0, 3, 1, 2, 4).reshape(b, c, hq, dh) \
            .astype(q_loc.dtype)

    spec = P(None, axis_name, None, None)
    fn = jax.shard_map(
        local_fn, mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)


def sequence_sharded(mesh: Mesh, axis_name: str = "sp"):
    """NamedSharding for [B, S, H, D] arrays with S over the ring."""
    return NamedSharding(mesh, P(None, axis_name, None, None))
