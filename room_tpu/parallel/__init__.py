from .mesh import (
    MeshSpec,
    decoder_param_specs,
    encoder_param_specs,
    kv_cache_specs,
    make_mesh,
    make_submesh,
    page_cache_specs,
    parse_mesh_spec,
    shard_pytree,
)
from .multihost import initialize_multihost, make_global_mesh
from .pipeline import (
    pipeline_forward,
    pipeline_spec,
    shard_params_for_pipeline,
)

__all__ = [
    "initialize_multihost",
    "make_global_mesh",
    "pipeline_forward",
    "pipeline_spec",
    "shard_params_for_pipeline",
    "MeshSpec",
    "decoder_param_specs",
    "encoder_param_specs",
    "kv_cache_specs",
    "make_mesh",
    "make_submesh",
    "page_cache_specs",
    "parse_mesh_spec",
    "shard_pytree",
]
