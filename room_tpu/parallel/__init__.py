from .mesh import (
    MeshSpec,
    decoder_param_specs,
    encoder_param_specs,
    kv_cache_specs,
    make_mesh,
    page_cache_specs,
    shard_pytree,
)

__all__ = [
    "MeshSpec",
    "decoder_param_specs",
    "encoder_param_specs",
    "kv_cache_specs",
    "make_mesh",
    "page_cache_specs",
    "shard_pytree",
]
