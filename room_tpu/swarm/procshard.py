"""Multi-process swarm shards (docs/swarmshard.md "Process mode").

PR 17's swarm shards gave each shard its own SQLite file, supervision
domain, and journal keys — but all of them live in one Python process:
one segfault, OOM-kill, or wedged GIL holder takes every room down at
once. ``ROOM_TPU_SWARM_PROC=1`` (with ``ROOM_TPU_SWARM_SHARDS`` > 1)
launches each shard as a **supervised child OS process** — its own
interpreter, its own SQLite handle, its own agent-loop domain —
speaking to the parent runtime over the framed-RTKW control wire
(``parallel.multihost.wire_send_control``), the same checksummed
framing, retry policy, and per-peer circuit breakers that carry KV
shipments and pod heartbeats.

Three cooperating pieces:

- :class:`ShardChild` — the child-process runtime
  (``python -m room_tpu.swarm.procshard --shard K ...``). At boot it
  takes the shard's **PID-tagged lockfile** (``shard<k>.db.lock``;
  refused while a live process holds it — a restarted parent can never
  double-open a live child's SQLite file), opens the shard database,
  runs journal recovery (a SIGKILL mid-transaction leaves an intent
  row recovery abandons), then serves cross-shard dispatch frames and
  streams heartbeat + stats frames at the parent.

- :class:`ProcSupervisor` — the parent-side process supervisor in the
  PodMembership mold: per-child heartbeats feed the alive → suspect →
  dead detector; a dead child is restarted with jittered exponential
  backoff under the ``ROOM_TPU_SWARM_PROC_RESTARTS``-per-window
  budget; past budget the shard degrades to **sibling adoption**
  (a live child reopens the dead shard's file, journal-recovers it,
  and the placement map rehomes + bumps the epoch — exactly the
  in-process ``shard_crash`` dance) and is reported unhealthy in
  ``/api/tpu/health``. Graceful ``stop()`` drains children over
  SIGTERM and escalates stragglers to SIGKILL after
  ``ROOM_TPU_SWARM_PROC_DRAIN_S`` — the ``core.supervisor``
  forced-kill sweep contract. At boot the supervisor **reaps
  orphans**: shard lockfiles naming live PIDs from a crashed previous
  parent are killed before any child re-opens their files.

- the **exactly-once dispatch plane**: cross-shard ``send_message`` /
  ``escalate`` halves ride ``wire_send_control`` frames carrying the
  same content-derived idempotency keys the in-process tier journals
  (``cycle_journal`` v3 ``kind='xshard'``, ``shard.journaled_once``).
  The child's dedup is check-then-act under its dispatch lock, so the
  contract survives a child dying between halves (the redelivery
  fires only the missing half), a duplicate frame redelivered after a
  restart (both halves dedup), and a SIGKILL mid-transaction (the
  intent is abandoned at boot recovery; the retry re-runs it). The
  parent retries individual failed frames (the ``shard_wire_io``
  fault fires here) — safe precisely because frames are idempotent.

The per-class scheduler/SLO attribution (queue/TTFT/TPOT per
queen/worker/background, serving/trace.py) rides the heartbeat stats
frames: :func:`merge_attributions` folds the latest per-child snapshot
into the parent's own recorder so ``/api/tpu/health`` → ``swarm.proc``
``slo``, ``/metrics`` (``room_tpu_swarm_proc``), and the TPU panel
read the N-process pod as **one SLO surface**.

Chaos: ``shard_proc_kill`` SIGKILLs a live child at the supervisor
seam; ``shard_wire_io`` fails individual dispatch frames
(docs/chaos.md).
"""

from __future__ import annotations

import argparse
import json
import os
import random
import signal
import subprocess
import sys
import threading
import time
import zlib
from typing import Callable, Optional

from ..db import Database
from ..utils import knobs, locks
from .shard import (
    ShardDownError, _stride_sequences, journaled_once, shard_db_path,
)

__all__ = [
    "ShardChild", "ProcSupervisor", "ShardLockHeld",
    "acquire_shard_lock", "release_shard_lock", "read_shard_lock",
    "reap_orphan_children", "merge_attributions", "default_proc",
    "maybe_default_proc", "reset_default_proc", "main",
]

# child states the parent tracks (membership drives dead; the budget
# decision drives restarting vs failed)
CHILD_STARTING = "starting"
CHILD_SERVING = "serving"
CHILD_DEAD = "dead"
CHILD_RESTARTING = "restarting"
CHILD_FAILED = "failed"      # budget exhausted -> sibling adopted
CHILD_STOPPED = "stopped"


# ---- PID-tagged shard lockfiles ----
#
# ``shard<k>.db.lock`` next to the shard file, JSON {pid, shard, ts}.
# The holder is whoever's live PID the file names: a dead holder's
# lock is stale and silently replaced; a live holder's lock refuses
# the open — the guard that makes "restarted parent + still-running
# orphan child" structurally unable to double-open one SQLite file.


class ShardLockHeld(RuntimeError):
    def __init__(self, path: str, pid: int) -> None:
        super().__init__(
            f"shard lockfile {path} held by live pid {pid}"
        )
        self.path = path
        self.pid = pid


def _pid_alive(pid: int) -> bool:
    if pid <= 0:
        return False
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    return True


def lock_path_for(db_path: str) -> str:
    return db_path + ".lock"


def read_shard_lock(db_path: str) -> Optional[dict]:
    try:
        with open(lock_path_for(db_path)) as f:
            data = json.load(f)
    except (OSError, ValueError):
        return None
    return data if isinstance(data, dict) else None


def acquire_shard_lock(db_path: str, shard_id: int) -> str:
    """Take the shard's PID lockfile or raise :class:`ShardLockHeld`
    while a live process holds it. A stale lock (dead PID, corrupt
    JSON) is replaced."""
    path = lock_path_for(db_path)
    held = read_shard_lock(db_path)
    if held is not None:
        pid = int(held.get("pid") or 0)
        if pid != os.getpid() and _pid_alive(pid):
            raise ShardLockHeld(path, pid)
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        json.dump({"pid": os.getpid(), "shard": shard_id,
                   "ts": time.time()}, f)
    os.replace(tmp, path)
    return path


def release_shard_lock(db_path: str) -> None:
    """Drop the lockfile — only if THIS process still holds it (a
    successor that already replaced it keeps its own)."""
    held = read_shard_lock(db_path)
    if held is not None and int(held.get("pid") or 0) == os.getpid():
        try:
            os.unlink(lock_path_for(db_path))
        except OSError:
            pass


def reap_orphan_children(db_dir: Optional[str],
                         n_shards: int) -> list[int]:
    """Parent-crash orphan reap: kill any live process still holding a
    shard lockfile under this swarm's directory (a child the previous
    parent spawned and then died without sweeping), and clear the
    stale locks. Returns the PIDs killed. Runs BEFORE the new parent
    spawns anything — the spawned children then take the locks
    cleanly."""
    from ..core.supervisor import kill_pid_tree

    reaped: list[int] = []
    for k in range(n_shards):
        db_path = shard_db_path(k, db_dir)
        held = read_shard_lock(db_path)
        if held is None:
            continue
        pid = int(held.get("pid") or 0)
        if pid > 0 and pid != os.getpid() and _pid_alive(pid):
            kill_pid_tree(pid, signal.SIGKILL)
            reaped.append(pid)
        try:
            os.unlink(lock_path_for(db_path))
        except OSError:
            pass
    return reaped


# ---- cross-process SLO attribution merge ----

def merge_attributions(snaps: list) -> dict:
    """Fold per-process ``trace.recorder.attribution()`` snapshots
    (parent + the latest heartbeat from every child) into one surface:
    counters and component-ms sums add; ``ttft_ms_mean`` re-averages
    weighted by each process's finished turns of that class. Snapshots
    are monotonic per process, so summing the LATEST per child is the
    fleet total."""
    merged: dict = {"finished_turns": 0, "classes": {}}
    weights: dict[str, float] = {}
    for snap in snaps:
        if not isinstance(snap, dict):
            continue
        merged["finished_turns"] += int(
            snap.get("finished_turns") or 0
        )
        for cls, a in (snap.get("classes") or {}).items():
            if not isinstance(a, dict):
                continue
            out = merged["classes"].setdefault(cls, {})
            turns = float(a.get("turns") or 0)
            mean = a.get("ttft_ms_mean")
            if mean is not None and turns > 0:
                prev_w = weights.get(cls, 0.0)
                prev = out.get("ttft_ms_mean") or 0.0
                weights[cls] = prev_w + turns
                out["ttft_ms_mean"] = round(
                    (prev * prev_w + float(mean) * turns)
                    / weights[cls], 3,
                )
            for key, v in a.items():
                if key == "ttft_ms_mean":
                    continue
                if isinstance(v, bool) or \
                        not isinstance(v, (int, float)):
                    continue
                out[key] = round(out.get(key, 0) + v, 3)
    return merged


# ---- the child process ----

class ShardChild:
    """One swarm shard as a process: lockfile, database, journal
    recovery, a control-wire listener for dispatch frames, and a
    heartbeat loop streaming stats at the parent."""

    def __init__(
        self,
        shard_id: int,
        db_dir: Optional[str] = None,
        parent: Optional[tuple[str, int]] = None,
        hb_s: Optional[float] = None,
        bind_host: Optional[str] = None,
        advertise_host: Optional[str] = None,
    ) -> None:
        self.shard_id = shard_id
        self.db_dir = db_dir
        self.parent = parent
        # containerized children bind 0.0.0.0 but must advertise an
        # address the parent can dial (service DNS / pod IP)
        self.bind_host = bind_host or "127.0.0.1"
        self.advertise_host = advertise_host or (
            self.bind_host if self.bind_host != "0.0.0.0"
            else "127.0.0.1"
        )
        self.hb_s = float(
            hb_s if hb_s is not None
            else knobs.get_float("ROOM_TPU_SWARM_PROC_HB_S")
        )
        self.db_path = shard_db_path(shard_id, db_dir)
        acquire_shard_lock(self.db_path, shard_id)
        self.db = Database(self.db_path)
        _stride_sequences(self.db, shard_id)
        from ..core import journal as journal_mod

        self.boot_recovery = journal_mod.recover(self.db)
        if shard_id == 0:
            self._seed_room_counter()
        # check-then-act dedup serialization for every xshard frame
        # this child journals (own file AND adopted files — coarser
        # than the in-process per-file lock, equally correct)
        self._dispatch_lock = locks.make_lock("swarm_proc_child")
        self._stop = threading.Event()
        self._domain = None
        # origin shard id -> Database, files adopted after a sibling
        # child exhausted its restart budget
        self.adopted: dict[int, Database] = {}
        self.stats = {
            "frames": 0, "messages_in": 0, "messages_out": 0,
            "escalations": 0, "dedup_skips": 0, "rooms_created": 0,
            "adoptions": 0, "heartbeats_sent": 0,
        }
        from ..parallel.multihost import KVWireServer

        spool = os.path.join(
            os.path.dirname(self.db_path) or ".",
            f"proc-spool-{os.getpid()}",
        )
        self.server = KVWireServer(
            spool, self._refuse_entry, host=self.bind_host, port=0,
            on_control=self._on_control,
        )

    def _seed_room_counter(self) -> None:
        """Shard 0 holds the swarm-global room-id counter: start it
        above any room already in this file so a proc swarm over a
        pre-existing shard 0 can't re-mint a taken id."""
        from ..core import messages as messages_mod
        from .shard import _ROOM_COUNTER_KEY

        row = self.db.query_one("SELECT MAX(id) AS m FROM rooms")
        top = int(row["m"]) if row and row["m"] else 0
        cur = int(
            messages_mod.get_setting(self.db, _ROOM_COUNTER_KEY)
            or "1"
        )
        if top >= cur:
            messages_mod.set_setting(
                self.db, _ROOM_COUNTER_KEY, str(top + 1)
            )

    # the swarm control plane never ships KV payloads
    def _refuse_entry(self, entry, kv, src):
        from ..parallel.multihost import KVWireError

        raise KVWireError("swarm shard child accepts control frames only")

    @property
    def domain(self):
        if self._domain is None:
            from ..core import agent_loop

            self._domain = agent_loop.LoopDomain()
        return self._domain

    def _db_for_home(self, home: int) -> Database:
        if home == self.shard_id:
            return self.db
        db = self.adopted.get(home)
        if db is None:
            from ..parallel.multihost import KVWireError

            raise KVWireError(
                f"shard child {self.shard_id} does not own home {home}"
            )
        return db

    # ---- control ops ----

    def _on_control(self, control: dict) -> dict:
        op = control.get("op")
        self.stats["frames"] += 1
        if op == "swarm_ping":
            return {"pong": True, "shard": self.shard_id,
                    "pid": os.getpid()}
        if op == "swarm_xshard":
            return self._op_xshard(control)
        if op == "swarm_alloc_room_id":
            return self._op_alloc_room_id(control)
        if op == "swarm_create_room":
            return self._op_create_room(control)
        if op == "swarm_adopt":
            return self._op_adopt(control)
        if op == "swarm_query":
            return self._op_query(control)
        if op == "swarm_stats":
            return {"stats": self.snapshot()}
        if op == "swarm_drain":
            # the test seam models a fully wedged child: deaf to the
            # drain frame AND to SIGTERM, so only the supervisor's
            # forced-kill sweep can clear it
            if not knobs.get_bool("ROOM_TPU_SWARM_PROC_IGNORE_TERM"):
                self._stop.set()
            return {"draining": True, "shard": self.shard_id}
        from ..parallel.multihost import KVWireError

        raise KVWireError(f"unknown swarm control op {op!r}")

    def _op_xshard(self, control: dict) -> dict:
        """One journaled dispatch half. The frame carries everything
        the journal key derives from (name + args), so a redelivered
        byte-identical frame dedups here no matter which incarnation
        of this child — or which adopter — it lands on."""
        name = control.get("name")
        args = control.get("args")
        if not isinstance(args, dict) or not isinstance(name, str):
            from ..parallel.multihost import KVWireError

            raise KVWireError("xshard frame missing name/args")
        home = int(control.get("home", self.shard_id))
        room_id = control.get("room_id")
        actor_id = control.get("actor_id")
        db = self._db_for_home(home)
        fn = self._effect_fn(db, name, args)
        with self._dispatch_lock:
            result, deduped = journaled_once(
                db, room_id, actor_id, name, args, fn
            )
        if name == "xshard_msg_out":
            self.stats["messages_out"] += 1
        elif name == "xshard_msg_in":
            self.stats["messages_in"] += 1
        elif name == "xshard_escalation":
            self.stats["escalations"] += 1
        if deduped:
            self.stats["dedup_skips"] += 1
        return {"result": result, "deduped": deduped}

    def _effect_fn(self, db: Database, name: str,
                   args: dict) -> Callable[[], str]:
        """The effect bodies — byte-for-byte the rows the in-process
        ``SwarmRouter`` halves insert, so proc mode and in-process
        mode journal interchangeably over the same files."""
        if name == "xshard_msg_out":
            return lambda: str(db.insert(
                "INSERT INTO room_messages(room_id, direction, "
                "from_room_id, to_room_id, subject, body, status) "
                "VALUES (?,?,?,?,?,?,'read')",
                (args["from"], "outbound", str(args["from"]),
                 str(args["to"]), args["subject"], args["body"]),
            ))
        if name == "xshard_msg_in":
            return lambda: str(db.insert(
                "INSERT INTO room_messages(room_id, direction, "
                "from_room_id, to_room_id, subject, body) "
                "VALUES (?,?,?,?,?,?)",
                (args["to"], "inbound", str(args["from"]),
                 str(args["to"]), args["subject"], args["body"]),
            ))
        if name == "xshard_escalation":
            def _escalate() -> str:
                from ..core import escalations as escalations_mod

                return str(escalations_mod.create_escalation(
                    db, args["room"], args["question"],
                    from_agent_id=args.get("from"),
                    to_agent_id=args.get("to"),
                ))
            return _escalate
        from ..parallel.multihost import KVWireError

        raise KVWireError(f"unknown xshard effect {name!r}")

    def _op_alloc_room_id(self, control: dict) -> dict:
        from ..core import messages as messages_mod
        from .shard import _ROOM_COUNTER_KEY

        db = self._db_for_home(int(control.get("home", 0)))
        with self._dispatch_lock:
            with db.transaction():
                cur = int(
                    messages_mod.get_setting(db, _ROOM_COUNTER_KEY)
                    or "1"
                )
                messages_mod.set_setting(
                    db, _ROOM_COUNTER_KEY, str(cur + 1)
                )
        return {"room_id": cur}

    def _op_create_room(self, control: dict) -> dict:
        from ..core import rooms as rooms_mod

        home = int(control.get("home", self.shard_id))
        rid = int(control["room_id"])
        db = self._db_for_home(home)
        with self._dispatch_lock:
            # id is pinned by the caller: a redelivered frame whose
            # first reply was lost finds the row and dedups
            prior = db.query_one(
                "SELECT id, name FROM rooms WHERE id=?", (rid,)
            )
            if prior is not None:
                return {"room_id": int(prior["id"]),
                        "name": prior["name"], "deduped": True}
            room = rooms_mod.create_room(
                db, str(control.get("name")), room_id=rid
            )
        self.stats["rooms_created"] += 1
        return {"room_id": int(room["id"]), "name": room["name"]}

    def _op_adopt(self, control: dict) -> dict:
        """Budget-exhausted sibling adoption: reopen the dead shard's
        file (taking its lockfile — refused while the old child is
        somehow still alive), journal-recover it, and serve its homes
        from here on."""
        from ..core import journal as journal_mod

        dead = int(control["shard"])
        if dead == self.shard_id or dead in self.adopted:
            return {"adopted": dead, "already": True}
        dead_path = shard_db_path(dead, self.db_dir)
        acquire_shard_lock(dead_path, dead)
        db = Database(dead_path)
        summary = journal_mod.recover(db)
        with self._dispatch_lock:
            self.adopted[dead] = db
        self.stats["adoptions"] += 1
        return {"adopted": dead, "recovery": summary}

    def _op_query(self, control: dict) -> dict:
        """SELECT-only diagnostics seam (bench + test accounting read
        shard state without opening the child's SQLite file)."""
        from ..parallel.multihost import KVWireError

        sql = str(control.get("sql") or "")
        if not sql.lstrip().lower().startswith("select"):
            raise KVWireError("swarm_query is SELECT-only")
        db = self._db_for_home(int(control.get("home",
                                               self.shard_id)))
        rows = db.query(sql, tuple(control.get("params") or ()))
        return {"rows": [dict(r) for r in rows]}

    # ---- snapshot + heartbeat ----

    def snapshot(self) -> dict:
        from ..core import journal as journal_mod
        from ..serving import trace as trace_mod

        try:
            journal_bytes = os.path.getsize(self.db_path)
        except OSError:
            journal_bytes = 0
        out = {
            "shard": self.shard_id,
            "pid": os.getpid(),
            "state": "draining" if self._stop.is_set() else "serving",
            "adopted": sorted(self.adopted),
            "journal": journal_mod.stats(self.db),
            "journal_bytes": journal_bytes,
            "boot_recovery": self.boot_recovery,
            "attribution": trace_mod.recorder.attribution(),
            **self.stats,
        }
        if self._domain is not None:
            from ..core import agent_loop

            out["supervision"] = agent_loop.supervision_snapshot(
                domain=self._domain
            )
        return out

    def _beat(self) -> None:
        if self.parent is None:
            return
        from ..parallel.multihost import KVWireError, \
            wire_send_control

        try:
            wire_send_control(
                self.parent,
                {"op": "swarm_heartbeat", "shard": self.shard_id,
                 "pid": os.getpid(),
                 "host": self.advertise_host,
                 "port": self.server.address[1],
                 "stats": self.snapshot()},
                retries=1,
            )
            self.stats["heartbeats_sent"] += 1
        except (KVWireError, OSError):
            # a briefly-absent parent costs a beat, never the child
            pass

    def run(self) -> None:
        """Serve until SIGTERM / a drain frame; then drain: wait out
        in-flight dispatch (the lock), close the listener, close the
        database cleanly, drop the lockfile, say goodbye."""
        if knobs.get_bool("ROOM_TPU_SWARM_PROC_IGNORE_TERM"):
            signal.signal(signal.SIGTERM, signal.SIG_IGN)
        else:
            signal.signal(
                signal.SIGTERM, lambda *_: self._stop.set()
            )
        self._beat()  # hello: registers address with the parent now
        while not self._stop.wait(timeout=self.hb_s):
            self._beat()
        self.close()

    def close(self) -> None:
        # in-flight journaled halves commit before the db closes:
        # taking the dispatch lock queues behind them
        with self._dispatch_lock:
            self.server.close()
            try:
                self.db.close()
            except Exception:
                pass
            for db in self.adopted.values():
                try:
                    db.close()
                except Exception:
                    pass
        release_shard_lock(self.db_path)
        for dead in self.adopted:
            release_shard_lock(shard_db_path(dead, self.db_dir))
        if self.parent is not None:
            from ..parallel.multihost import KVWireError, \
                wire_send_control

            try:
                wire_send_control(
                    self.parent,
                    {"op": "swarm_goodbye",
                     "shard": self.shard_id, "pid": os.getpid()},
                    retries=1,
                )
            except (KVWireError, OSError):
                pass


# ---- the parent-side supervisor ----

class _Child:
    """Parent-side record of one shard child (mutated under the
    supervisor's lock)."""

    __slots__ = (
        "shard", "proc", "pid", "address", "state", "restarts",
        "next_restart_at", "last_stats", "spawned_at", "adopter",
    )

    def __init__(self, shard: int) -> None:
        self.shard = shard
        self.proc: Optional[subprocess.Popen] = None
        self.pid: Optional[int] = None
        self.address: Optional[tuple[str, int]] = None
        self.state = CHILD_STARTING
        self.restarts: list[float] = []   # monotonic restart stamps
        self.next_restart_at: Optional[float] = None
        self.last_stats: dict = {}
        self.spawned_at: Optional[float] = None
        self.adopter: Optional[int] = None


class ProcSupervisor:
    """The parent: spawns one :class:`ShardChild` per shard, feeds
    their wire heartbeats into a PodMembership detector, restarts the
    dead under a windowed budget with jittered backoff, degrades past
    budget to sibling adoption + unhealthy, and carries the
    exactly-once cross-shard dispatch plane over
    ``wire_send_control``."""

    def __init__(
        self,
        n_shards: Optional[int] = None,
        db_dir: Optional[str] = None,
        restart_budget: Optional[int] = None,
        restart_window_s: Optional[float] = None,
        backoff_s: Optional[float] = None,
        drain_s: Optional[float] = None,
        hb_s: Optional[float] = None,
        suspect_s: Optional[float] = None,
        dead_s: Optional[float] = None,
        lease_s: Optional[float] = None,
        child_env: Optional[dict] = None,
        spawn: bool = True,
        external: Optional[bool] = None,
    ) -> None:
        from ..parallel.multihost import KVWireServer
        from ..serving import podnet as podnet_mod

        # external = shard children run as separate containers
        # (launched by compose/kubelet, not by this parent): the
        # supervisor keeps the heartbeat ladder, dispatch plane, and
        # adoption, but never spawns, signals, or lockfile-reaps —
        # every PID it sees lives in a foreign namespace
        self.external = bool(
            external if external is not None
            else knobs.get_bool("ROOM_TPU_SWARM_PROC_EXTERNAL")
        )

        self.n_shards = max(1, int(
            n_shards if n_shards is not None
            else knobs.get_int("ROOM_TPU_SWARM_SHARDS")
        ))
        self.db_dir = db_dir
        self.restart_budget = int(
            restart_budget if restart_budget is not None
            else knobs.get_int("ROOM_TPU_SWARM_PROC_RESTARTS")
        )
        self.restart_window_s = float(
            restart_window_s if restart_window_s is not None
            else knobs.get_float("ROOM_TPU_SWARM_PROC_WINDOW_S")
        )
        self.backoff_s = float(
            backoff_s if backoff_s is not None
            else knobs.get_float("ROOM_TPU_SWARM_PROC_BACKOFF_S")
        )
        self.drain_s = float(
            drain_s if drain_s is not None
            else knobs.get_float("ROOM_TPU_SWARM_PROC_DRAIN_S")
        )
        self.hb_s = float(
            hb_s if hb_s is not None
            else knobs.get_float("ROOM_TPU_SWARM_PROC_HB_S")
        )
        self._child_env = child_env
        self._lock = locks.make_lock("swarm_proc")
        self.placement = podnet_mod.PlacementMap(self.n_shards)
        self.membership = podnet_mod.PodMembership(
            suspect_s=suspect_s, dead_s=dead_s, lease_s=lease_s,
        )
        self.stats = {
            "dispatches": 0, "dedup_skips": 0, "restarts": 0,
            "adoptions": 0, "proc_kills": 0, "wire_retries": 0,
            "sheds": 0, "orphans_reaped": 0, "forced_kills": 0,
        }
        self.children: dict[int, _Child] = {
            k: _Child(k) for k in range(self.n_shards)
        }
        # a previous parent may have died leaving live children
        # holding the shard locks: reap them BEFORE spawning (skipped
        # in external mode — lockfile PIDs belong to other containers
        # and signalling them here would hit unrelated local
        # processes)
        if not self.external:
            reaped = reap_orphan_children(db_dir, self.n_shards)
            self.stats["orphans_reaped"] = len(reaped)
        spool = os.path.join(
            os.path.dirname(shard_db_path(0, db_dir)) or ".",
            f"proc-parent-spool-{os.getpid()}",
        )
        self.server = KVWireServer(
            spool, self._refuse_entry,
            host=knobs.get_str("ROOM_TPU_SWARM_PROC_HOST"),
            port=knobs.get_int("ROOM_TPU_SWARM_PROC_PORT"),
            on_control=self._on_control,
        )
        for k in range(self.n_shards):
            self.membership.register(f"shard{k}")
        self._closed = False
        if spawn and not self.external:
            for k in range(self.n_shards):
                self._spawn(k)

    def _refuse_entry(self, entry, kv, src):
        from ..parallel.multihost import KVWireError

        raise KVWireError("swarm proc supervisor accepts control "
                          "frames only")

    # ---- spawn / reap ----

    def _spawn(self, shard: int) -> None:
        from ..core.supervisor import spawn_managed

        cmd = [
            sys.executable, "-m", "room_tpu.swarm.procshard",
            "--shard", str(shard),
            "--parent",
            f"{self.server.address[0]}:{self.server.address[1]}",
            "--hb-s", str(self.hb_s),
        ]
        if self.db_dir:
            cmd += ["--db-dir", self.db_dir]
        env = dict(os.environ)
        # the child boots via `-m room_tpu.swarm.procshard`, which
        # resolves the package through the CHILD's sys.path — pin the
        # parent's package root onto PYTHONPATH so supervisors driven
        # from outside the repo/install dir (embedding scripts, cwd
        # elsewhere) still spawn importable children
        pkg_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env["PYTHONPATH"] = pkg_root + (
            os.pathsep + env["PYTHONPATH"]
            if env.get("PYTHONPATH") else "")
        if self._child_env:
            env.update(self._child_env)
        proc = spawn_managed(
            cmd, label=f"swarm-shard-{shard}", env=env,
            stdout=subprocess.DEVNULL,
        )
        with self._lock:
            child = self.children[shard]
            child.proc = proc
            child.pid = proc.pid
            child.address = None
            child.state = CHILD_STARTING
            child.spawned_at = time.monotonic()
            child.next_restart_at = None

    def _reap(self, child: _Child) -> None:
        """Make a declared-dead child REALLY dead (SIGKILL the tree)
        and reap the zombie so the PID table stays clean."""
        from ..core.supervisor import kill_pid_tree, \
            unregister_managed_process

        proc, pid = child.proc, child.pid
        if pid is not None:
            kill_pid_tree(pid, signal.SIGKILL)
            unregister_managed_process(pid)
        if proc is not None:
            try:
                proc.wait(timeout=5.0)
            except Exception:
                pass

    # ---- heartbeats (wire server callback) ----

    def _on_control(self, control: dict) -> dict:
        op = control.get("op")
        if op in ("swarm_heartbeat", "swarm_goodbye"):
            shard = int(control.get("shard", -1))
            pid = int(control.get("pid") or 0)
            with self._lock:
                child = self.children.get(shard)
                stale = child is None or (
                    child.pid is not None and pid != child.pid
                )
                if not stale and op == "swarm_heartbeat":
                    port = int(control.get("port") or 0)
                    if port:
                        child.address = (
                            str(control.get("host") or "127.0.0.1"),
                            port,
                        )
                    child.last_stats = control.get("stats") or {}
                    if child.state == CHILD_STARTING:
                        child.state = CHILD_SERVING
                        if self.external and child.pid is None:
                            child.pid = pid or None
                if not stale and op == "swarm_goodbye":
                    child.state = CHILD_STOPPED
            # a beat from a replaced incarnation must not heal the
            # member its successor owns
            if not stale and op == "swarm_heartbeat":
                self.membership.observe(f"shard{shard}")
            return {"ok": True}
        from ..parallel.multihost import KVWireError

        raise KVWireError(f"unknown swarm control op {op!r}")

    # ---- placement ----

    def base_home(self, room_id) -> int:
        return zlib.crc32(str(room_id).encode("utf-8")) % self.n_shards

    def owner_of_home(self, home: int) -> int:
        """Follow placement redirects home→adopter (chains collapse to
        one hop on rehome)."""
        redirects = self.placement.frame()["redirects"]
        k, seen = int(home), set()
        while str(k) in redirects and k not in seen:
            seen.add(k)
            k = int(redirects[str(k)])
        return k % self.n_shards

    def _address_for_home(self, home: int) -> tuple[str, int]:
        owner = self.owner_of_home(home)
        with self._lock:
            child = self.children[owner]
            if child.state != CHILD_SERVING or child.address is None:
                self.stats["sheds"] += 1
                raise ShardDownError(home)
            return child.address

    # ---- dispatch plane ----

    def _frame(self, home: int, control: dict) -> dict:
        """One retried dispatch frame to the home's owning child. The
        ``shard_wire_io`` fault fires per attempt; retrying a frame
        that may ALREADY have landed is safe because every frame
        journals under its content-derived key on the child."""
        from ..parallel.multihost import (
            KVWireError, KVWireRefused, wire_send_control,
        )
        from ..serving import faults
        from ..serving import podnet as podnet_mod
        from ..serving.faults import FaultError

        attempts = max(1, podnet_mod.wire_retries())
        last: Optional[Exception] = None
        for attempt in range(attempts):
            try:
                faults.maybe_fail("shard_wire_io")
                return wire_send_control(
                    self._address_for_home(home), control, retries=1,
                )
            except KVWireRefused:
                raise
            except (KVWireError, FaultError) as e:
                last = e
                with self._lock:
                    self.stats["wire_retries"] += 1
                if attempt + 1 < attempts:
                    delay = podnet_mod.wire_backoff_s(attempt)
                    if delay > 0:
                        time.sleep(delay)
        raise ShardDownError(home) from last

    def _xshard(
        self,
        home: int,
        name: str,
        args: dict,
        room_id: Optional[int],
        actor_id: Optional[int],
    ) -> tuple[str, bool]:
        reply = self._frame(home, {
            "op": "swarm_xshard", "home": home, "name": name,
            "args": args, "room_id": room_id, "actor_id": actor_id,
        })
        with self._lock:
            self.stats["dispatches"] += 1
            if reply.get("deduped"):
                self.stats["dedup_skips"] += 1
        return str(reply.get("result") or ""), \
            bool(reply.get("deduped"))

    def send_message(
        self,
        from_room_id: int,
        to_room_id: int,
        subject: str,
        body: str,
        actor_id: Optional[int] = None,
    ) -> tuple[int, int]:
        """Cross-shard ``message_send`` over the wire: the same two
        journaled halves as the in-process tier, each riding its own
        idempotent frame — a child dying between them leaves one
        committed half, and the caller's retry fires only the missing
        one."""
        args = {"from": from_room_id, "to": to_room_id,
                "subject": subject, "body": body}
        out_raw, _ = self._xshard(
            self.base_home(from_room_id), "xshard_msg_out", args,
            from_room_id, actor_id,
        )
        in_raw, _ = self._xshard(
            self.base_home(to_room_id), "xshard_msg_in", args,
            to_room_id, actor_id,
        )
        return int(out_raw or 0), int(in_raw or 0)

    def escalate(
        self,
        room_id: int,
        question: str,
        from_agent_id: Optional[int] = None,
        to_agent_id: Optional[int] = None,
    ) -> int:
        args = {"room": room_id, "question": question,
                "from": from_agent_id, "to": to_agent_id}
        raw, _ = self._xshard(
            self.base_home(room_id), "xshard_escalation", args,
            room_id, from_agent_id,
        )
        return int(raw or 0)

    def create_room(self, name: str) -> dict:
        """Mint a swarm-unique id from the home-0 counter (wherever
        home 0 is currently served), then create the room on the
        shard the id hashes to."""
        reply = self._frame(0, {"op": "swarm_alloc_room_id",
                                "home": 0})
        rid = int(reply["room_id"])
        home = self.base_home(rid)
        out = self._frame(home, {
            "op": "swarm_create_room", "home": home,
            "room_id": rid, "name": name,
        })
        return {"id": int(out["room_id"]), "name": out.get("name")}

    def query(self, home: int, sql: str,
              params: tuple = ()) -> list[dict]:
        """SELECT-only pass-through to the home's owning child."""
        reply = self._frame(home, {
            "op": "swarm_query", "home": home, "sql": sql,
            "params": list(params),
        })
        return list(reply.get("rows") or [])

    # ---- supervision ----

    def _maybe_chaos_kill(self) -> None:
        if self.external:
            return
        faults = sys.modules.get("room_tpu.serving.faults")
        if faults is None or not faults.is_armed() or \
                faults.should_fire("shard_proc_kill") is None:
            return
        from ..core.supervisor import kill_pid_tree

        with self._lock:
            live = [
                c for c in self.children.values()
                if c.state == CHILD_SERVING and c.pid is not None
            ]
            if not live:
                return
            victim = max(
                live,
                key=lambda c: (
                    (c.last_stats or {}).get("frames", 0), -c.shard,
                ),
            )
            self.stats["proc_kills"] += 1
        kill_pid_tree(victim.pid, signal.SIGKILL)
        self._trace_note("swarm.shard_proc_kill",
                         {"shard": victim.shard, "pid": victim.pid})

    def supervise(self, now: Optional[float] = None) -> list[dict]:
        """One supervision pass: roll the ``shard_proc_kill`` chaos
        point, advance the membership detector, reap children it
        declared dead, then restart-under-budget or degrade to
        sibling adoption. Returns the adoptions performed."""
        self._maybe_chaos_kill()
        mono = time.monotonic() if now is None else now
        for member, _old, new in self.membership.tick(now=now):
            if new != "dead":
                continue
            shard = int(member.removeprefix("shard"))
            with self._lock:
                child = self.children.get(shard)
                if child is None or child.state in (
                    CHILD_FAILED, CHILD_STOPPED,
                ):
                    continue
                child.state = CHILD_DEAD
                child.address = None
            if self.external:
                # the PID is another container's; the replacement
                # incarnation (container-runtime restart) registers
                # itself by heartbeat
                with self._lock:
                    child.pid = None
            else:
                self._reap(child)
            self._trace_note("swarm.shard_proc_dead",
                             {"shard": shard})
        adoptions: list[dict] = []
        for member in self.membership.lease_expired(now=now):
            shard = int(member.removeprefix("shard"))
            with self._lock:
                child = self.children.get(shard)
                if child is None or child.state != CHILD_DEAD:
                    continue
                child.restarts = [
                    t for t in child.restarts
                    if mono - t < self.restart_window_s
                ]
                over_budget = \
                    len(child.restarts) >= self.restart_budget
                if not over_budget:
                    n = len(child.restarts)
                    child.next_restart_at = mono + \
                        self.backoff_s * (2 ** n) * \
                        (1.0 + random.random())
                    child.state = CHILD_RESTARTING
            if over_budget:
                entry = self._adopt(shard)
                if entry is not None:
                    adoptions.append(entry)
                else:
                    # no sibling could adopt (none serving yet, or
                    # the frame failed): re-arm the member so the
                    # dead→lease cycle fires again and we retry
                    self.membership.forget(member)
                    self.membership.register(member)
        self._restart_due(mono)
        return adoptions

    def _restart_due(self, mono: float) -> None:
        due: list[int] = []
        with self._lock:
            for child in self.children.values():
                if child.state == CHILD_RESTARTING and \
                        child.next_restart_at is not None and \
                        mono >= child.next_restart_at:
                    child.restarts.append(mono)
                    due.append(child.shard)
        for shard in due:
            # the dead incarnation's lease already fired: re-arm the
            # detector so the replacement's first beat registers fresh
            self.membership.forget(f"shard{shard}")
            self.membership.register(f"shard{shard}")
            if self.external:
                # the container runtime owns the actual respawn; open
                # the slot so the replacement's hello registers
                with self._lock:
                    child = self.children[shard]
                    child.pid = None
                    child.address = None
                    child.state = CHILD_STARTING
                    child.next_restart_at = None
            else:
                self._spawn(shard)
            with self._lock:
                self.stats["restarts"] += 1
            self._trace_note("swarm.shard_proc_restart",
                             {"shard": shard})

    def _adopt(self, shard: int) -> Optional[dict]:
        """Past the restart budget: a serving sibling child reopens
        the shard's file (journal recovery included), the placement
        map rehomes + bumps the epoch, and the shard is FAILED —
        unhealthy in /api/tpu/health until an operator intervenes."""
        with self._lock:
            serving = [
                c for c in self.children.values()
                if c.state == CHILD_SERVING and c.address is not None
            ]
            if not serving:
                return None
            adopter = min(
                serving,
                key=lambda c: (
                    len((c.last_stats or {}).get("adopted") or []),
                    c.shard,
                ),
            )
        from ..parallel.multihost import KVWireError

        try:
            reply = self._frame(adopter.shard, {
                "op": "swarm_adopt", "home": adopter.shard,
                "shard": shard,
            })
        except (KVWireError, ShardDownError):
            return None
        epoch = self.placement.rehome(shard, adopter.shard)
        with self._lock:
            child = self.children[shard]
            child.state = CHILD_FAILED
            child.adopter = adopter.shard
            self.stats["adoptions"] += 1
        entry = {
            "shard": shard, "adopter": adopter.shard,
            "epoch": epoch,
            "recovery": reply.get("recovery"),
        }
        self._trace_note("swarm.shard_proc_adopted", entry)
        from ..core.events import event_bus

        event_bus.emit("swarm:proc_adopted", "runtime", entry)
        return entry

    # ---- shutdown ----

    def stop(self, drain_s: Optional[float] = None) -> dict:
        """Graceful shutdown: drain frame + SIGTERM to every child,
        wait out the drain deadline, SIGKILL the stragglers (the
        forced-kill sweep — a SIGTERM-ignoring child cannot wedge the
        parent), reap, release. Runs BEFORE the parent's clean
        shutdown marker is written (server runtime stop order)."""
        from ..core.supervisor import kill_pid_tree, \
            unregister_managed_process
        from ..parallel.multihost import KVWireError, \
            wire_send_control

        drain_s = self.drain_s if drain_s is None else float(drain_s)
        with self._lock:
            targets = [
                c for c in self.children.values()
                if c.pid is not None and c.state in (
                    CHILD_STARTING, CHILD_SERVING, CHILD_DEAD,
                    CHILD_RESTARTING,
                )
            ]
        if self.external:
            # container children: ask them to drain over the wire —
            # their lifecycle (and any escalation) is the container
            # runtime's, not ours to signal
            for child in targets:
                if child.address is not None:
                    try:
                        wire_send_control(
                            child.address, {"op": "swarm_drain"},
                            retries=1, timeout_s=1.0,
                        )
                    except (KVWireError, OSError):
                        pass
                with self._lock:
                    child.state = CHILD_STOPPED
            self.server.close()
            self._closed = True
            return {"stopped": len(targets), "forced_kills": 0}
        for child in targets:
            if child.address is not None:
                try:
                    wire_send_control(
                        child.address, {"op": "swarm_drain"},
                        retries=1, timeout_s=1.0,
                    )
                except (KVWireError, OSError):
                    pass
            kill_pid_tree(child.pid, signal.SIGTERM)
        deadline = time.monotonic() + drain_s
        pending = list(targets)
        while pending and time.monotonic() < deadline:
            pending = [
                c for c in pending
                if c.proc is not None and c.proc.poll() is None
            ]
            if pending:
                time.sleep(0.05)
        forced = 0
        for child in pending:
            kill_pid_tree(child.pid, signal.SIGKILL)
            forced += 1
        for child in targets:
            if child.proc is not None:
                try:
                    child.proc.wait(timeout=5.0)
                except Exception:
                    pass
            unregister_managed_process(child.pid)
            with self._lock:
                child.state = CHILD_STOPPED
        with self._lock:
            self.stats["forced_kills"] += forced
        self.server.close()
        self._closed = True
        return {"stopped": len(targets), "forced_kills": forced}

    close = stop

    # ---- observability ----

    def _trace_note(self, kind: str, data: dict) -> None:
        trace = sys.modules.get("room_tpu.serving.trace")
        if trace is not None:
            try:
                trace.note_event(kind, data)
            except Exception:
                pass

    def attribution(self) -> dict:
        """The process-spanning per-class SLO surface: the parent's
        own recorder plus the latest stats frame from every child."""
        from ..serving import trace as trace_mod

        snaps = [trace_mod.recorder.attribution()]
        with self._lock:
            for child in self.children.values():
                attr = (child.last_stats or {}).get("attribution")
                if attr:
                    snaps.append(attr)
        return merge_attributions(snaps)

    def unhealthy_shards(self) -> list[int]:
        with self._lock:
            return sorted(
                c.shard for c in self.children.values()
                if c.state in (CHILD_DEAD, CHILD_FAILED)
            )

    def snapshot(self) -> dict:
        with self._lock:
            children = []
            for c in sorted(self.children.values(),
                            key=lambda x: x.shard):
                stats = c.last_stats or {}
                children.append({
                    "shard": c.shard,
                    "state": c.state,
                    "pid": c.pid,
                    "address": list(c.address) if c.address else None,
                    "restarts_in_window": len(c.restarts),
                    "adopter": c.adopter,
                    "adopted": stats.get("adopted") or [],
                    "journal": stats.get("journal"),
                    "journal_bytes": stats.get("journal_bytes", 0),
                    "frames": stats.get("frames", 0),
                    "messages_in": stats.get("messages_in", 0),
                    "messages_out": stats.get("messages_out", 0),
                    "escalations": stats.get("escalations", 0),
                    "dedup_skips": stats.get("dedup_skips", 0),
                    "rooms_created": stats.get("rooms_created", 0),
                    "supervision": stats.get("supervision"),
                })
            stats = dict(self.stats)
        return {
            "mode": "proc",
            "external": self.external,
            "n_shards": self.n_shards,
            "restart_budget": self.restart_budget,
            "restart_window_s": self.restart_window_s,
            "placement": self.placement.snapshot(),
            "membership": self.membership.snapshot(),
            "children": children,
            "slo": self.attribution(),
            **stats,
        }


# ---- process-wide default supervisor ----

_default_proc: Optional[ProcSupervisor] = None
_default_proc_lock = locks.make_lock("swarm_proc_default")


def default_proc() -> ProcSupervisor:
    global _default_proc
    with _default_proc_lock:
        if _default_proc is None:
            _default_proc = ProcSupervisor()
        return _default_proc


def maybe_default_proc() -> Optional[ProcSupervisor]:
    """The default process-mode supervisor when
    ``ROOM_TPU_SWARM_PROC`` is armed over a multi-shard swarm, else
    None — the cheap guard the health/metrics/runtime surfaces call
    every tick."""
    if _default_proc is not None:
        return _default_proc
    if knobs.get_bool("ROOM_TPU_SWARM_PROC") and \
            knobs.get_int("ROOM_TPU_SWARM_SHARDS") > 1:
        return default_proc()
    return None


def reset_default_proc() -> None:
    """Testing hook."""
    global _default_proc
    with _default_proc_lock:
        if _default_proc is not None:
            try:
                _default_proc.stop()
            except Exception:
                pass
        _default_proc = None


# ---- child entry point ----

def main(argv: Optional[list[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        description="room-tpu swarm shard child process"
    )
    parser.add_argument("--shard", type=int, required=True)
    parser.add_argument("--db-dir", default=None)
    parser.add_argument("--parent", default=None,
                        help="host:port of the parent's control wire")
    parser.add_argument("--hb-s", type=float, default=None)
    parser.add_argument("--bind-host", default=None,
                        help="bind the child's wire listener here "
                        "(0.0.0.0 for containerized children)")
    parser.add_argument("--advertise-host", default=None,
                        help="address the parent dials back "
                        "(service DNS / pod IP when containerized)")
    args = parser.parse_args(argv)
    parent = None
    if args.parent:
        host, _, port = args.parent.rpartition(":")
        parent = (host or "127.0.0.1", int(port))
    from ..serving import faults

    faults.configure_from_env()
    try:
        child = ShardChild(
            args.shard, db_dir=args.db_dir, parent=parent,
            hb_s=args.hb_s, bind_host=args.bind_host,
            advertise_host=args.advertise_host,
        )
    except ShardLockHeld as e:
        print(f"refusing to start: {e}", file=sys.stderr)
        return 3
    child.run()
    return 0


if __name__ == "__main__":
    sys.exit(main())
