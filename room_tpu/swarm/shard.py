"""Room-partitioned swarm-runtime shards behind the placement map
(docs/swarmshard.md).

Everything below the providers scales out (fleet replicas, disagg
roles, pod membership, the room-id-sharded router tier), but the swarm
runtime — Queens, Workers, Quorum, goals, the cycle journal — was one
process around one WAL-mode SQLite singleton: every cycle's journal
writes, every room's events, and every loop's supervision shared a
single writer. ``ROOM_TPU_SWARM_SHARDS`` > 1 partitions rooms across N
swarm-runtime shards. Each :class:`SwarmShard` owns

- its own SQLite file (``shard<k>.db``; schema and migrations are the
  classic ones, applied per shard by ``Database`` itself),
- its own agent-loop supervision domain (``agent_loop.LoopDomain`` —
  registry, crash strikes, unhealthy roster), and
- its own event-bus segment (an ``EventBus`` fed the shard's
  ``room:<id>`` traffic by the router's tap on the global bus).

Placement is the SAME epoch-versioned machinery the router tier uses
(``serving.podnet.PlacementMap``): the room's **data home** is the
stable crc32 hash of its id (which file holds its rows — never changes
while the file lives), and its **owner** is the redirect-followed
placement lookup (which shard's supervision domain runs it — changes
on failover, fenced by the epoch).

Cross-shard seams:

- **Identity.** Every AUTOINCREMENT sequence is strided per shard
  (shard k mints ids from ``k * 10^9``), so worker/cycle/journal ids
  are globally unique and an N→M re-placement moves rows between
  files without collision — zero journal loss, ids preserved. Room
  ids come from a swarm-global counter (shard 0's settings) and the
  hash of the allocated id decides the home shard.
- **Dispatch.** Inter-room ``message_send`` and escalations route
  through :meth:`SwarmRouter.send_message` / :meth:`SwarmRouter.escalate`:
  each remote half is journaled on the *target* shard's database under
  the cycle journal's content-derived idempotency key
  (``journal.effect_key``), so a crashed dispatch redelivered after
  adoption commits exactly once — the same exactly-once contract
  in-shard tool effects already carry.
- **Failover.** ``kill_shard`` (chaos: the ``shard_crash`` fault in
  :meth:`SwarmRouter.supervise`) closes a shard's database mid-flight;
  its rooms shed (``ShardDownError``, retryable) for
  ``ROOM_TPU_SWARM_LEASE_S``, then a sibling reopens the file, runs
  ``journal.recover`` over it (interrupted cycles failed, committed
  effects replay-flagged), and the placement rehome + epoch bump
  fences the dead owner out.
- **Resize.** :func:`resize_swarm` re-places every room under an
  M-shard map, moving whole room row-sets between shard files over an
  ATTACHed connection in one transaction per room.

Single-shard mode (``ROOM_TPU_SWARM_SHARDS`` unset or 1) never builds
a router: ``db.get_database()`` keeps its classic singleton behavior.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
import zlib
from typing import Callable, Optional

from ..core.events import EventBus, event_bus
from ..db import Database
from ..db.database import default_db_path
from ..core import journal as journal_mod
from ..core import messages as messages_mod
from ..utils import knobs, locks

# per-shard AUTOINCREMENT id stride: shard k mints ids >= k * STRIDE,
# making every row id globally unique across shard files (the property
# resize_swarm's row moves and the cross-shard journal rely on)
ID_STRIDE = 1_000_000_000

# the swarm-global room-id counter, kept in shard 0's settings table
_ROOM_COUNTER_KEY = "swarm:next_room_id"

# every AUTOINCREMENT table (sqlite_sequence rows are seeded to the
# shard's stride floor on open; tables created empty have no row yet)
_SEQ_TABLES = (
    "workers", "rooms", "entities", "observations", "relations",
    "embeddings", "tasks", "task_runs", "console_logs", "watches",
    "chat_messages", "room_activity", "quorum_decisions",
    "quorum_votes", "goals", "goal_updates", "skills",
    "self_mod_audit", "escalations", "credentials", "wallets",
    "wallet_transactions", "room_messages", "worker_cycles",
    "cycle_journal", "cycle_logs", "clerk_messages", "clerk_usage",
)

# room-scoped row-set spec for resize_swarm, copy order respects FKs
# (parents before children); the WHERE clauses run with the source
# file ATTACHed as ``src`` on the target connection
_ROOM_TABLES: tuple[tuple[str, str], ...] = (
    # workers first: rooms.queen_worker_id REFERENCES workers(id)
    # (workers.room_id is a plain column, so no cycle)
    ("workers", "room_id=?"),
    ("rooms", "id=?"),
    ("agent_sessions",
     "worker_id IN (SELECT id FROM src.workers WHERE room_id=?)"),
    ("entities", "room_id=?"),
    ("observations",
     "entity_id IN (SELECT id FROM src.entities WHERE room_id=?)"),
    ("relations",
     "from_entity IN (SELECT id FROM src.entities WHERE room_id=?)"),
    ("embeddings",
     "entity_id IN (SELECT id FROM src.entities WHERE room_id=?)"),
    ("tasks", "room_id=?"),
    ("task_runs",
     "task_id IN (SELECT id FROM src.tasks WHERE room_id=?)"),
    ("console_logs",
     "run_id IN (SELECT id FROM src.task_runs WHERE task_id IN "
     "(SELECT id FROM src.tasks WHERE room_id=?))"),
    ("watches", "room_id=?"),
    ("chat_messages", "room_id=?"),
    ("room_activity", "room_id=?"),
    ("quorum_decisions", "room_id=?"),
    ("quorum_votes",
     "decision_id IN (SELECT id FROM src.quorum_decisions "
     "WHERE room_id=?)"),
    ("goals", "room_id=?"),
    ("goal_updates",
     "goal_id IN (SELECT id FROM src.goals WHERE room_id=?)"),
    ("skills", "room_id=?"),
    ("self_mod_audit", "room_id=?"),
    ("self_mod_snapshots",
     "audit_id IN (SELECT id FROM src.self_mod_audit "
     "WHERE room_id=?)"),
    ("escalations", "room_id=?"),
    ("credentials", "room_id=?"),
    ("wallets", "room_id=?"),
    ("wallet_transactions",
     "wallet_id IN (SELECT id FROM src.wallets WHERE room_id=?)"),
    ("room_messages", "room_id=?"),
    ("worker_cycles", "room_id=?"),
    ("cycle_journal", "room_id=?"),
    ("cycle_logs",
     "cycle_id IN (SELECT id FROM src.worker_cycles "
     "WHERE room_id=?)"),
)


class ShardDownError(RuntimeError):
    """The room's shard is dead and not yet adopted: shed, retryable
    once a sibling finishes the lease/adopt dance."""

    def __init__(self, shard_id: int) -> None:
        super().__init__(
            f"swarm shard {shard_id} is down; retry after adoption"
        )
        self.shard_id = shard_id
        self.transient = True


def shard_db_path(shard_id: int, db_dir: Optional[str] = None) -> str:
    """Shard k's database file. With ``ROOM_TPU_SWARM_DB_DIR`` (or an
    explicit ``db_dir``) every shard lives as ``<dir>/shard<k>.db``;
    otherwise shard 0 keeps the classic ``default_db_path()`` file —
    a 1→N resize starts from the data already on disk — and shard
    k > 0 gets a ``.shard<k>`` sibling."""
    directory = db_dir or knobs.get_str("ROOM_TPU_SWARM_DB_DIR")
    if directory:
        directory = os.path.expanduser(directory)
        os.makedirs(directory, exist_ok=True)
        return os.path.join(directory, f"shard{shard_id}.db")
    base = default_db_path()
    if shard_id == 0:
        return base
    root, ext = os.path.splitext(base)
    return f"{root}.shard{shard_id}{ext}"


def _stride_sequences(db: Database, shard_id: int) -> None:
    """Seed every AUTOINCREMENT sequence to the shard's id floor so no
    two shards can mint the same row id. Idempotent; an existing
    sequence already past the floor is left alone."""
    floor = shard_id * ID_STRIDE
    with db.transaction():
        for table in _SEQ_TABLES:
            row = db.query_one(
                "SELECT seq FROM sqlite_sequence WHERE name=?", (table,)
            )
            if row is None:
                db.execute(
                    "INSERT INTO sqlite_sequence(name, seq) "
                    "VALUES (?,?)", (table, floor),
                )
            elif int(row["seq"]) < floor:
                db.execute(
                    "UPDATE sqlite_sequence SET seq=? WHERE name=?",
                    (floor, table),
                )


def journaled_once(
    db: Database,
    room_id: Optional[int],
    actor_id: Optional[int],
    name: str,
    args: dict,
    fn: Callable[[], str],
) -> tuple[str, bool]:
    """Exactly-once cross-shard delivery on the *target* shard's
    database: the cycle journal's content-derived idempotency key
    (``journal.effect_key`` — kind ``xshard``) dedups any prior
    committed or replay-flagged entry inside the replay window, so a
    dispatch redelivered after a sender crash or a shard adoption
    commits once and only once. Returns ``(result, deduped)``.

    The intent→commit protocol is the journal's own: a crash between
    intent and commit leaves an ``intent`` row that startup recovery
    abandons, and the redelivery re-runs the effect; a crash after
    commit leaves the ``committed`` row this very dedup matches.

    The dedup check is check-then-act: callers serialize concurrent
    dispatches onto one database under that shard's
    ``_dispatch_lock``.
    """
    key = journal_mod.effect_key("xshard", actor_id, name, args)
    cutoff = f"-{int(journal_mod.REPLAY_WINDOW_S)} seconds"
    prior = db.query_one(
        "SELECT payload FROM cycle_journal WHERE entry='effect' AND "
        "idem_key=? AND status IN ('committed','replay_skip') AND "
        "updated_at > strftime('%Y-%m-%dT%H:%M:%fZ','now', ?) "
        "ORDER BY id DESC LIMIT 1",
        (key, cutoff),
    )
    if prior is not None:
        payload = json.loads(prior["payload"] or "{}")
        return payload.get("result", ""), True
    entry_id = db.insert(
        "INSERT INTO cycle_journal(kind, ref_id, room_id, worker_id, "
        "entry, status, idem_key, payload) VALUES "
        "('xshard',0,?,?,'effect','intent',?,?)",
        (room_id, actor_id, key,
         json.dumps({"tool": name, "args": args}, default=str)),
    )
    with db.transaction():
        out = fn()
        db.execute(
            "UPDATE cycle_journal SET status='committed', payload=?, "
            "updated_at=? WHERE id=?",
            (json.dumps({"tool": name, "args": args,
                         "result": (out or "")[:2000]}, default=str),
             journal_mod.utc_now(), entry_id),
        )
    return out, False


class SwarmShard:
    """One swarm-runtime shard: a database file, an event-bus segment,
    and (lazily) an agent-loop supervision domain. State transitions
    ``serving`` → ``dead`` (kill/crash) → ``retired`` (adopted)."""

    def __init__(self, shard_id: int, db: Database) -> None:
        self.shard_id = shard_id
        self.db: Optional[Database] = db
        self.bus = EventBus()
        self.state = "serving"
        self.died_at: Optional[float] = None
        self._domain = None
        # serializes journaled_once's check-then-act sequence for
        # effects landing on THIS shard's file (concurrent
        # redeliveries of one idempotency key must queue)
        self._dispatch_lock = locks.make_lock("swarm_dispatch")
        self.stats = {
            "events": 0, "messages_in": 0, "messages_out": 0,
            "escalations": 0, "adoptions": 0, "dedup_skips": 0,
            "rooms_created": 0,
        }
        # origin shard id -> reopened Database, for files this shard
        # adopted after a sibling died
        self.adopted: dict[int, Database] = {}

    @property
    def domain(self):
        """The shard's ``agent_loop.LoopDomain`` (imported lazily: the
        data path must not drag the provider stack in)."""
        if self._domain is None:
            from ..core import agent_loop

            self._domain = agent_loop.LoopDomain()
        return self._domain

    def snapshot(self) -> dict:
        out = {
            "shard": self.shard_id,
            "state": self.state,
            "adopted": sorted(self.adopted),
            **self.stats,
        }
        if self._domain is not None:
            from ..core import agent_loop

            out["supervision"] = agent_loop.supervision_snapshot(
                domain=self._domain
            )
        return out


class SwarmRouter:
    """The shard-aware control plane: placement, per-room database
    resolution, cross-shard dispatch, chaos, failover, and the health/
    metrics snapshot. One per process (``default_router``); tests and
    the bench build private ones over temp directories."""

    def __init__(
        self,
        n_shards: Optional[int] = None,
        db_dir: Optional[str] = None,
        lease_s: Optional[float] = None,
        db_factory: Optional[Callable[[int], Database]] = None,
    ) -> None:
        from ..serving import podnet as podnet_mod

        self.n_shards = max(1, int(
            n_shards if n_shards is not None
            else knobs.get_int("ROOM_TPU_SWARM_SHARDS")
        ))
        self.lease_s = float(
            lease_s if lease_s is not None
            else knobs.get_float("ROOM_TPU_SWARM_LEASE_S")
        )
        self._db_dir = db_dir
        self._db_factory = db_factory or (
            lambda k: Database(shard_db_path(k, db_dir))
        )
        self._lock = locks.make_lock("swarm_router")
        self.placement = podnet_mod.PlacementMap(self.n_shards)
        self.shards: list[SwarmShard] = []
        # data home (origin shard id) -> live Database over that file;
        # a dead shard's slot is None until a sibling adopts the file
        self._dbs: dict[int, Optional[Database]] = {}
        for k in range(self.n_shards):
            db = self._db_factory(k)
            if self.n_shards > 1:
                _stride_sequences(db, k)
            self.shards.append(SwarmShard(k, db))
            self._dbs[k] = db
        self.stats = {
            "cross_shard_messages": 0, "cross_shard_escalations": 0,
            "dedup_skips": 0, "shard_crashes": 0, "adoptions": 0,
            "sheds": 0, "resizes": 0,
        }
        self._seed_room_counter()
        # the event tap: room-channel traffic on the global bus fans
        # into the owning shard's segment (per-shard WS fan-out and
        # metrics read the segments; the global bus stays the
        # process-wide aggregate)
        self._untap = event_bus.subscribe(None, self._route_event)
        self._closed = False

    # ---- placement ----

    def base_home(self, room_id) -> int:
        """The room's *data home*: which shard file holds its rows.
        The stable crc32 hash — deliberately the same formula as
        ``PlacementMap.shard_of``'s base hash, minus the failover
        redirects (adoption reopens the origin file; it never moves
        rows)."""
        return zlib.crc32(str(room_id).encode("utf-8")) % self.n_shards

    def owner_of(self, room_id) -> int:
        """The room's *owner*: which shard's supervision domain runs
        it — the redirect-followed placement lookup."""
        return self.placement.shard_of(str(room_id))

    def db_for(self, room_id: Optional[int] = None) -> Database:
        """Resolve a room id to the live database over its home file.
        ``None`` means the swarm-global tables (settings, clerk) on
        shard 0. Raises :class:`ShardDownError` while the home shard
        is dead and unadopted — the shed window."""
        home = 0 if room_id is None else self.base_home(room_id)
        db = self._dbs.get(home)
        if db is None:
            with self._lock:
                self.stats["sheds"] += 1
            raise ShardDownError(home)
        return db

    def shard_for(self, room_id) -> SwarmShard:
        """The shard whose supervision domain / event segment owns the
        room right now (post-failover: the adopter)."""
        return self.shards[self.owner_of(room_id)]

    def all_dbs(self) -> list[Database]:
        """One live Database per shard *file* (serving shards plus
        adopted files), for runtime loops that sweep every room."""
        seen: list[Database] = []
        for k in sorted(self._dbs):
            db = self._dbs[k]
            if db is not None and db not in seen:
                seen.append(db)
        return seen

    # ---- room identity ----

    def _meta_db(self) -> Database:
        db = self._dbs.get(0)
        if db is None:
            raise ShardDownError(0)
        return db

    def _seed_room_counter(self) -> None:
        """Start the room-id counter above any room already on disk
        (a 1→N resize inherits the classic file's rooms)."""
        top = 0
        for db in self.all_dbs():
            row = db.query_one("SELECT MAX(id) AS m FROM rooms")
            if row and row["m"]:
                top = max(top, int(row["m"]))
        meta = self._meta_db()
        cur = int(
            messages_mod.get_setting(meta, _ROOM_COUNTER_KEY) or "1"
        )
        if top >= cur:
            messages_mod.set_setting(
                meta, _ROOM_COUNTER_KEY, str(top + 1)
            )

    def allocate_room_id(self) -> int:
        """Mint a swarm-unique room id from the shard-0 counter. The
        crc32 of the *allocated id* decides the home shard — identity
        drives placement, never the other way around."""
        meta = self._meta_db()
        with self._lock:
            with meta.transaction():
                cur = int(
                    messages_mod.get_setting(meta, _ROOM_COUNTER_KEY)
                    or "1"
                )
                messages_mod.set_setting(
                    meta, _ROOM_COUNTER_KEY, str(cur + 1)
                )
        return cur

    def create_room(self, name: str, **kwargs) -> dict:
        """Create a room on the shard its allocated id hashes to."""
        from ..core import rooms as rooms_mod

        rid = self.allocate_room_id()
        db = self.db_for(rid)
        room = rooms_mod.create_room(db, name, room_id=rid, **kwargs)
        shard = self.shards[self.base_home(rid)]
        shard.stats["rooms_created"] += 1
        return room

    # ---- cross-shard dispatch ----

    def send_message(
        self,
        from_room_id: int,
        to_room_id: int,
        subject: str,
        body: str,
        actor_id: Optional[int] = None,
    ) -> tuple[int, int]:
        """Shard-aware ``message_send``: the single-shard swarm keeps
        the classic two-insert path; any multi-shard topology journals
        each half on its room's home shard under one content-derived
        key, so redelivery after a crash/adoption — or after a resize
        merged the pair onto ONE file, where the committed journal
        rows moved with their rooms — is exactly-once."""
        src = self.db_for(from_room_id)
        dst = self.db_for(to_room_id)
        if src is dst and self.n_shards == 1:
            return messages_mod.send_room_message(
                src, from_room_id, to_room_id, subject, body
            )
        args = {"from": from_room_id, "to": to_room_id,
                "subject": subject, "body": body}
        shard_src = self.shards[self.base_home(from_room_id)]
        shard_dst = self.shards[self.base_home(to_room_id)]
        # each half serializes on ITS shard's dispatch lock
        # (sequentially, never nested — opposite-direction sends can't
        # deadlock); journaled_once's dedup check is check-then-act
        with shard_src._dispatch_lock:
            out_raw, out_dup = journaled_once(
                src, from_room_id, actor_id, "xshard_msg_out", args,
                lambda: str(src.insert(
                    "INSERT INTO room_messages(room_id, direction, "
                    "from_room_id, to_room_id, subject, body, status) "
                    "VALUES (?,?,?,?,?,?,'read')",
                    (from_room_id, "outbound", str(from_room_id),
                     str(to_room_id), subject, body),
                )),
            )
        with shard_dst._dispatch_lock:
            in_raw, in_dup = journaled_once(
                dst, to_room_id, actor_id, "xshard_msg_in", args,
                lambda: str(dst.insert(
                    "INSERT INTO room_messages(room_id, direction, "
                    "from_room_id, to_room_id, subject, body) "
                    "VALUES (?,?,?,?,?,?)",
                    (to_room_id, "inbound", str(from_room_id),
                     str(to_room_id), subject, body),
                )),
            )
        with self._lock:
            if src is not dst:
                self.stats["cross_shard_messages"] += 1
            if out_dup or in_dup:
                self.stats["dedup_skips"] += 1
        self.shards[self.base_home(from_room_id)].stats[
            "messages_out"] += 1
        self.shards[self.base_home(to_room_id)].stats[
            "messages_in"] += 1
        if out_dup or in_dup:
            self.shards[self.base_home(to_room_id)].stats[
                "dedup_skips"] += 1
        return int(out_raw or 0), int(in_raw or 0)

    def escalate(
        self,
        room_id: int,
        question: str,
        from_agent_id: Optional[int] = None,
        to_agent_id: Optional[int] = None,
    ) -> int:
        """Shard-aware escalation: journaled on the room's shard under
        the content-derived key (a webhook/MCP caller retrying after a
        shard crash lands exactly one escalation row)."""
        from ..core import escalations as escalations_mod

        db = self.db_for(room_id)
        args = {"room": room_id, "question": question,
                "from": from_agent_id, "to": to_agent_id}
        shard_dst = self.shards[self.base_home(room_id)]
        with shard_dst._dispatch_lock:
            raw, dup = journaled_once(
                db, room_id, from_agent_id, "xshard_escalation", args,
                lambda: str(escalations_mod.create_escalation(
                    db, room_id, question,
                    from_agent_id=from_agent_id, to_agent_id=to_agent_id,
                )),
            )
        with self._lock:
            self.stats["cross_shard_escalations"] += 1
            if dup:
                self.stats["dedup_skips"] += 1
        shard = self.shards[self.base_home(room_id)]
        shard.stats["escalations"] += 1
        if dup:
            shard.stats["dedup_skips"] += 1
        return int(raw or 0)

    # ---- chaos + failover ----

    def kill_shard(
        self, shard_id: int, reason: str = "killed"
    ) -> bool:
        """Crash one serving shard: its database handle closes (every
        in-flight statement errors like a process death) and its rooms
        shed until a sibling adopts the file after the lease. Refused
        when it would kill the last serving shard."""
        with self._lock:
            shard = self.shards[shard_id]
            serving = [
                s for s in self.shards if s.state == "serving"
            ]
            if shard.state != "serving" or len(serving) < 2:
                return False
            shard.state = "dead"
            shard.died_at = time.monotonic()
            db, shard.db = shard.db, None
            self._dbs[shard_id] = None
            self.stats["shard_crashes"] += 1
        if db is not None:
            try:
                db.close()
            except Exception:
                pass
        if shard._domain is not None:
            from ..core import agent_loop

            agent_loop.stop_domain_loops(shard._domain)
        event_bus.emit(
            "swarm:shard_dead", "runtime",
            {"shard": shard_id, "reason": reason},
        )
        self._trace_note("swarm.shard_crash",
                         {"shard": shard_id, "reason": reason})
        return True

    def adopt_dead_shards(
        self, now: Optional[float] = None
    ) -> list[dict]:
        """Sibling adoption of dead shards past their lease: reopen
        the dead shard's file, run journal recovery over it
        (interrupted cycles failed, committed effects replay-flagged),
        hand the live handle to the emptiest serving sibling, and
        fence the dead owner out with a placement rehome + epoch
        bump."""
        now = time.monotonic() if now is None else now
        out: list[dict] = []
        with self._lock:
            expired = [
                s for s in self.shards
                if s.state == "dead" and s.died_at is not None
                and now - s.died_at >= self.lease_s
            ]
            for s in expired:
                s.state = "adopting"
        for s in expired:
            serving = [
                x for x in self.shards if x.state == "serving"
            ]
            if not serving:
                s.state = "dead"  # nobody left to adopt into
                continue
            adopter = min(
                serving, key=lambda x: (len(x.adopted), x.shard_id)
            )
            db = self._db_factory(s.shard_id)
            summary = journal_mod.recover(db)
            epoch = self.placement.rehome(
                s.shard_id, adopter.shard_id
            )
            with self._lock:
                adopter.adopted[s.shard_id] = db
                self._dbs[s.shard_id] = db
                s.state = "retired"
                adopter.stats["adoptions"] += 1
                self.stats["adoptions"] += 1
            entry = {
                "shard": s.shard_id, "adopter": adopter.shard_id,
                "epoch": epoch, **summary,
            }
            out.append(entry)
            event_bus.emit("swarm:shard_adopted", "runtime", entry)
            self._trace_note("swarm.shard_adopted", entry)
        return out

    def supervise(self, now: Optional[float] = None) -> list[dict]:
        """One supervision pass: fire the ``shard_crash`` chaos point
        (kills the busiest serving shard when a sibling exists — the
        swarm twin of ``router_shard_crash``), then run the adoption
        sweep. Returns the adoptions performed."""
        faults = sys.modules.get("room_tpu.serving.faults")
        if faults is not None and faults.is_armed() and \
                faults.should_fire("shard_crash") is not None:
            with self._lock:
                serving = [
                    s for s in self.shards if s.state == "serving"
                ]
            if len(serving) >= 2:
                busiest = max(
                    serving,
                    key=lambda s: (s.stats["rooms_created"],
                                   -s.shard_id),
                )
                self.kill_shard(busiest.shard_id,
                                reason="chaos: shard_crash")
        out = self.adopt_dead_shards(now=now)
        # system-invariant witness (docs/chaosfuzz.md): the shard
        # sweep probes exactly-once xshard effects across the live
        # shard files — resolved like faults above, so the swarm
        # layer never hard-depends on the chaos package
        inv = sys.modules.get("room_tpu.chaos.invariants")
        if inv is not None and inv.enabled():
            inv.probe_swarm(self)
        return out

    # ---- events ----

    def _route_event(self, event) -> None:
        """Global-bus tap: fan ``room:<id>`` traffic into the owning
        shard's segment (one O(1) handler, not one per socket — the
        firehose the WS hub used to be)."""
        channel = getattr(event, "channel", "") or ""
        if not channel.startswith("room:"):
            return
        try:
            room_id = int(channel.split(":", 1)[1])
        except ValueError:
            return
        shard = self.shards[self.owner_of(room_id)]
        shard.stats["events"] += 1
        shard.bus.emit(event.type, event.channel, event.data)

    # ---- observability + teardown ----

    def _trace_note(self, kind: str, data: dict) -> None:
        trace = sys.modules.get("room_tpu.serving.trace")
        if trace is not None:
            try:
                trace.note_event(kind, data)
            except Exception:
                pass

    def snapshot(self) -> dict:
        with self._lock:
            shards = [s.snapshot() for s in self.shards]
            stats = dict(self.stats)
        return {
            "n_shards": self.n_shards,
            "lease_s": self.lease_s,
            "placement": self.placement.snapshot(),
            "shards": shards,
            **stats,
        }

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            self._untap()
        except Exception:
            pass
        for db in self.all_dbs():
            try:
                db.close()
            except Exception:
                pass


def resize_swarm(
    router: SwarmRouter,
    new_n: int,
    db_dir: Optional[str] = None,
    db_factory: Optional[Callable[[int], Database]] = None,
) -> tuple[SwarmRouter, dict]:
    """Shard-count change N→M: close the old router, open an M-shard
    one over the same directory, and re-place every room under the new
    map — each room whose home changed moves as one whole row-set
    (ids preserved; the strided sequences guarantee no collisions)
    inside a single cross-file transaction per room. Returns the new
    router and a summary whose ``journal_rows_lost`` is asserted zero
    by the swarm tier."""
    old_n = router.n_shards
    db_dir = db_dir if db_dir is not None else router._db_dir
    old_paths = {
        k: shard_db_path(k, db_dir) for k in range(old_n)
    }
    with router._lock:
        dbs_snapshot = dict(router._dbs)
    for k, db in dbs_snapshot.items():
        if db is not None and k in old_paths:
            old_paths[k] = db.path
    journal_before = _count_journal_rows(router.all_dbs())
    router.close()

    new_router = SwarmRouter(
        n_shards=new_n, db_dir=db_dir, db_factory=db_factory
    )
    summary = {
        "old_shards": old_n, "new_shards": new_router.n_shards,
        "rooms_moved": 0, "rooms_kept": 0,
        "journal_rows_before": journal_before,
    }
    # files beyond the new count are drained too: their rooms all
    # re-place somewhere under the new map
    orphan_dbs: dict[str, Database] = {}
    try:
        for old_k in sorted(old_paths):
            src_path = old_paths[old_k]
            if not os.path.exists(src_path):
                continue
            if old_k < new_router.n_shards and \
                    new_router._dbs[old_k] is not None and \
                    new_router._dbs[old_k].path == src_path:
                src_db = new_router._dbs[old_k]
            else:
                src_db = orphan_dbs.setdefault(
                    src_path, Database(src_path)
                )
            rooms = src_db.query("SELECT id FROM rooms ORDER BY id")
            for row in rooms:
                rid = int(row["id"])
                new_home = new_router.base_home(rid)
                dst_db = new_router._dbs[new_home]
                if dst_db is not None and dst_db.path == src_path:
                    summary["rooms_kept"] += 1
                    continue
                _move_room(src_db, dst_db, rid)
                summary["rooms_moved"] += 1
        new_router._seed_room_counter()
    finally:
        for db in orphan_dbs.values():
            try:
                db.close()
            except Exception:
                pass
    after = _count_journal_rows(new_router.all_dbs())
    # journal rows with no room (room_id NULL or pruned) stay in their
    # origin file only while that file is still a live shard; count
    # only what the new map can see, and compare against the before
    summary["journal_rows_after"] = after
    summary["journal_rows_lost"] = max(0, journal_before - after)
    new_router.stats["resizes"] += 1
    event_bus.emit("swarm:resized", "runtime", dict(summary))
    return new_router, summary


def _count_journal_rows(dbs: list[Database]) -> int:
    n = 0
    for db in dbs:
        row = db.query_one("SELECT COUNT(*) AS n FROM cycle_journal")
        n += int(row["n"]) if row else 0
    return n


def _move_room(src_db: Database, dst_db: Database, room_id: int) -> None:
    """Move one room's whole row-set between shard files: ATTACH the
    source on the destination connection, copy parents→children,
    delete children→parents, all in one transaction spanning both
    files."""
    dst_db.execute("ATTACH DATABASE ? AS src", (src_db.path,))
    try:
        with dst_db.transaction():
            for table, cond in _ROOM_TABLES:
                dst_db.execute(
                    f"INSERT INTO {table} SELECT * FROM src.{table} "
                    f"WHERE {cond}",
                    (room_id,),
                )
            for table, cond in reversed(_ROOM_TABLES):
                dst_db.execute(
                    f"DELETE FROM src.{table} WHERE {cond}",
                    (room_id,),
                )
    finally:
        dst_db.execute("DETACH DATABASE src")


# ---- process-wide default router ----

_default_router: Optional[SwarmRouter] = None
_default_router_lock = locks.make_lock("swarm_default")


def default_router() -> SwarmRouter:
    """The process-wide router, built on first use from
    ``ROOM_TPU_SWARM_SHARDS`` (callers check the knob first:
    ``db.get_database`` only routes here when it is > 1)."""
    global _default_router
    with _default_router_lock:
        if _default_router is None:
            _default_router = SwarmRouter()
        return _default_router


def maybe_default_router() -> Optional[SwarmRouter]:
    """The default router when swarm sharding is configured, else
    None — the cheap guard surfaces (health, metrics, runtime loops)
    call this every tick."""
    if _default_router is not None:
        return _default_router
    if knobs.get_bool("ROOM_TPU_SWARM_PROC"):
        # process mode: the shard runtimes live in child processes
        # (procshard.ProcSupervisor owns the files), so the
        # in-process router must never open them here
        return None
    if knobs.get_int("ROOM_TPU_SWARM_SHARDS") > 1:
        return default_router()
    return None


def reset_default_router() -> None:
    """Testing hook (reached via db.reset_database_singleton)."""
    global _default_router
    with _default_router_lock:
        if _default_router is not None:
            _default_router.close()
        _default_router = None
