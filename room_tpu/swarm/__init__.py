"""Sharded swarm control plane (docs/swarmshard.md)."""

from .shard import (  # noqa: F401
    ShardDownError, SwarmRouter, SwarmShard, default_router,
    maybe_default_router, reset_default_router, resize_swarm,
    shard_db_path,
)
from .procshard import (  # noqa: F401
    ProcSupervisor, ShardChild, ShardLockHeld, default_proc,
    maybe_default_proc, merge_attributions, reset_default_proc,
)
