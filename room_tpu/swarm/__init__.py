"""Sharded swarm control plane (docs/swarmshard.md)."""

from .shard import (  # noqa: F401
    ShardDownError, SwarmRouter, SwarmShard, default_router,
    maybe_default_router, reset_default_router, resize_swarm,
    shard_db_path,
)
