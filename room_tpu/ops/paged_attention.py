"""Pallas TPU kernel: paged-attention decode.

The hot op of the serving engine (PAPERS.md: Ragged Paged Attention for
TPU): one query token per sequence attends over that sequence's KV pages
without materializing a gathered dense cache. Per sequence the kernel
walks its block table, DMAs pages HBM→VMEM double-buffered, and runs an
online-softmax accumulation (flash-attention style) with GQA.

Shapes
    q         [B, Hq, D]
    k_pages   [P, page, Hkv, D]   (one layer's pool)
    v_pages   [P, page, Hkv, D]
    tables    [B, max_pages]      int32 page ids (scalar-prefetched)
    lengths   [B]                 int32 valid tokens (scalar-prefetched)
    out       [B, Hq, D]

Mosaic-shaped design (constraints observed compiling on a real v5e):
batched ``dot_general`` with non-leading batch dims and sub-tile HBM
slices both fail to lower, so the kernel never slices the Hkv dim.
Each page is viewed as a flat ``[page*Hkv, D]`` tile (a row-major
bitcast done by the wrapper), one DMA per page, and the GQA dot runs
over ALL (query-head, kv-head) pairs in a single MXU matmul per page —
entries whose kv head doesn't own the query head are masked to -inf
before the online softmax, so they contribute exp(-inf)=0 to both the
normalizer and the PV accumulation. For the qwen3 shapes
(page=32 × Hkv=4 = 128 rows) the "wasted" lanes are exactly the MXU's
native width: the pair-masked matmul costs the same wall-clock as the
per-head ideal and avoids every layout hazard.

The XLA reference path (kv_pages.make_paged_kv_hook) stays the default
on CPU; the engine switches to this kernel on TPU via
ROOM_TPU_PAGED_KERNEL=pallas. Numerics are pinned against
ops.attention_ref in tests (interpret mode)."""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _decode_kernel(
    # scalar prefetch
    tables_ref,      # [B, max_pages] SMEM
    lengths_ref,     # [B] SMEM
    # inputs
    q_ref,           # [1, Hq, D] VMEM (this sequence's query)
    k_pages_hbm,     # [P, page*Hkv, D] ANY/HBM (flattened view)
    v_pages_hbm,     # [P, page*Hkv, D] ANY/HBM
    # output
    o_ref,           # [1, Hq, D] VMEM
    # scratch
    k_buf,           # [2, page*Hkv, D] VMEM
    v_buf,           # [2, page*Hkv, D] VMEM
    acc_ref,         # [Hq, D] f32 VMEM
    m_ref,           # [Hq, 1] f32 VMEM
    l_ref,           # [Hq, 1] f32 VMEM
    sems,            # DMA sems [2, 2]
    *,
    page_size: int,
    n_kv_heads: int,
    scale: float,
):
    b = pl.program_id(0)
    length = lengths_ref[b]
    n_pages = jax.lax.div(length + page_size - 1, page_size)

    hq = q_ref.shape[1]
    hkv = n_kv_heads
    d = q_ref.shape[2]
    group = hq // hkv
    rows = page_size * hkv

    m_ref[:] = jnp.full_like(m_ref, NEG_INF)
    l_ref[:] = jnp.zeros_like(l_ref)
    acc_ref[:] = jnp.zeros_like(acc_ref)

    def start_fetch(i, slot):
        page_id = tables_ref[b, i]
        pltpu.make_async_copy(
            k_pages_hbm.at[page_id], k_buf.at[slot], sems.at[slot, 0]
        ).start()
        pltpu.make_async_copy(
            v_pages_hbm.at[page_id], v_buf.at[slot], sems.at[slot, 1]
        ).start()

    def wait_fetch(i, slot):
        page_id = tables_ref[b, i]
        pltpu.make_async_copy(
            k_pages_hbm.at[page_id], k_buf.at[slot], sems.at[slot, 0]
        ).wait()
        pltpu.make_async_copy(
            v_pages_hbm.at[page_id], v_buf.at[slot], sems.at[slot, 1]
        ).wait()

    @pl.when(n_pages > 0)
    def _():
        start_fetch(0, 0)

    q = q_ref[0].astype(jnp.float32) * scale          # [Hq, D]

    # Static (head-pair) half of the mask: row j of a flat page belongs
    # to kv head j % Hkv; query head h reads kv head h // group.
    j = jax.lax.broadcasted_iota(jnp.int32, (hq, rows), 1)
    h = jax.lax.broadcasted_iota(jnp.int32, (hq, rows), 0)
    pair_ok = jax.lax.rem(j, hkv) == jax.lax.div(h, group)
    tok_of_j = jax.lax.div(j, hkv)                    # token within page

    def body(i, _):
        slot = jax.lax.rem(i, 2)

        @pl.when(i + 1 < n_pages)
        def _():
            start_fetch(i + 1, 1 - slot)

        wait_fetch(i, slot)
        k = k_buf[slot].astype(jnp.float32)           # [rows, D]
        v = v_buf[slot].astype(jnp.float32)

        # one MXU matmul for every (query head, kv row) pair
        logits = jax.lax.dot_general(
            q, k,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                             # [Hq, rows]
        valid = pair_ok & ((i * page_size + tok_of_j) < length)
        logits = jnp.where(valid, logits, NEG_INF)

        m_prev = m_ref[:]                              # [Hq, 1]
        m_new = jnp.maximum(
            m_prev, jnp.max(logits, axis=1, keepdims=True)
        )
        p = jnp.exp(logits - m_new)                    # [Hq, rows]
        alpha = jnp.exp(m_prev - m_new)                # [Hq, 1]

        l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=1, keepdims=True)
        pv = jax.lax.dot_general(
            p, v,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                              # [Hq, D]
        acc_ref[:] = acc_ref[:] * alpha + pv
        m_ref[:] = m_new
        return 0

    jax.lax.fori_loop(0, n_pages, body, 0)

    denom = jnp.maximum(l_ref[:], 1e-30)
    o_ref[0] = (acc_ref[:] / denom).astype(o_ref.dtype)


def _decode_kernel_int8(
    # scalar prefetch
    tables_ref,      # [B, max_pages] SMEM
    lengths_ref,     # [B] SMEM
    # inputs
    q_ref,           # [1, Hq, D] VMEM
    k_pages_hbm,     # [P, page*Hkv, D] int8 ANY/HBM
    v_pages_hbm,     # [P, page*Hkv, D] int8 ANY/HBM
    k_scale_hbm,     # [P, page*Hkv, 1] f32 ANY/HBM
    v_scale_hbm,     # [P, page*Hkv, 1] f32 ANY/HBM
    # output
    o_ref,           # [1, Hq, D] VMEM
    # scratch
    k_buf,           # [2, page*Hkv, D] int8 VMEM
    v_buf,
    ks_buf,          # [2, page*Hkv, 1] f32 VMEM
    vs_buf,
    acc_ref, m_ref, l_ref,
    sems,            # DMA sems [2, 4]
    *,
    page_size: int,
    n_kv_heads: int,
    scale: float,
):
    """int8-KV variant of _decode_kernel: pages arrive as int8 plus a
    per-(token, head) f32 scale ([rows, 1] tile, broadcast over lanes
    in the dequant multiply) — half the page DMA bytes of bf16, the
    decisive lever for the HBM-bandwidth-bound decode. Same flat-tile
    / all-head-pairs-in-one-matmul Mosaic posture as the bf16 kernel."""
    b = pl.program_id(0)
    length = lengths_ref[b]
    n_pages = jax.lax.div(length + page_size - 1, page_size)

    hq = q_ref.shape[1]
    hkv = n_kv_heads
    group = hq // hkv
    rows = page_size * hkv

    m_ref[:] = jnp.full_like(m_ref, NEG_INF)
    l_ref[:] = jnp.zeros_like(l_ref)
    acc_ref[:] = jnp.zeros_like(acc_ref)

    def start_fetch(i, slot):
        page_id = tables_ref[b, i]
        for src, dst, sem in (
            (k_pages_hbm, k_buf, 0), (v_pages_hbm, v_buf, 1),
            (k_scale_hbm, ks_buf, 2), (v_scale_hbm, vs_buf, 3),
        ):
            pltpu.make_async_copy(
                src.at[page_id], dst.at[slot], sems.at[slot, sem]
            ).start()

    def wait_fetch(i, slot):
        page_id = tables_ref[b, i]
        for src, dst, sem in (
            (k_pages_hbm, k_buf, 0), (v_pages_hbm, v_buf, 1),
            (k_scale_hbm, ks_buf, 2), (v_scale_hbm, vs_buf, 3),
        ):
            pltpu.make_async_copy(
                src.at[page_id], dst.at[slot], sems.at[slot, sem]
            ).wait()

    @pl.when(n_pages > 0)
    def _():
        start_fetch(0, 0)

    q = q_ref[0].astype(jnp.float32) * scale          # [Hq, D]

    j = jax.lax.broadcasted_iota(jnp.int32, (hq, rows), 1)
    h = jax.lax.broadcasted_iota(jnp.int32, (hq, rows), 0)
    pair_ok = jax.lax.rem(j, hkv) == jax.lax.div(h, group)
    tok_of_j = jax.lax.div(j, hkv)

    def body(i, _):
        slot = jax.lax.rem(i, 2)

        @pl.when(i + 1 < n_pages)
        def _():
            start_fetch(i + 1, 1 - slot)

        wait_fetch(i, slot)
        k = k_buf[slot].astype(jnp.float32) * ks_buf[slot]
        v = v_buf[slot].astype(jnp.float32) * vs_buf[slot]

        logits = jax.lax.dot_general(
            q, k,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                             # [Hq, rows]
        valid = pair_ok & ((i * page_size + tok_of_j) < length)
        logits = jnp.where(valid, logits, NEG_INF)

        m_prev = m_ref[:]
        m_new = jnp.maximum(
            m_prev, jnp.max(logits, axis=1, keepdims=True)
        )
        p = jnp.exp(logits - m_new)
        alpha = jnp.exp(m_prev - m_new)

        l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=1, keepdims=True)
        pv = jax.lax.dot_general(
            p, v,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_ref[:] = acc_ref[:] * alpha + pv
        m_ref[:] = m_new
        return 0

    jax.lax.fori_loop(0, n_pages, body, 0)

    denom = jnp.maximum(l_ref[:], 1e-30)
    o_ref[0] = (acc_ref[:] / denom).astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("page_size", "interpret")
)
def paged_attention_decode_int8(
    q: jax.Array,          # [B, Hq, D]
    k_pages: jax.Array,    # [P, page, Hkv, D] int8
    v_pages: jax.Array,    # [P, page, Hkv, D] int8
    k_scale: jax.Array,    # [P, page, Hkv] f32
    v_scale: jax.Array,    # [P, page, Hkv] f32
    tables: jax.Array,     # [B, max_pages] int32
    lengths: jax.Array,    # [B] int32
    *,
    page_size: int,
    interpret: bool = False,
) -> jax.Array:
    b, hq, d = q.shape
    p_count, _, hkv, _ = k_pages.shape
    scale = 1.0 / float(np.sqrt(d))
    rows = page_size * hkv

    k_flat = k_pages.reshape(p_count, rows, d)
    v_flat = v_pages.reshape(p_count, rows, d)
    ks_flat = k_scale.reshape(p_count, rows, 1)
    vs_flat = v_scale.reshape(p_count, rows, 1)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b,),
        in_specs=[
            pl.BlockSpec(
                (1, hq, d), lambda i, *_: (i, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec(
            (1, hq, d), lambda i, *_: (i, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        scratch_shapes=[
            pltpu.VMEM((2, rows, d), k_pages.dtype),
            pltpu.VMEM((2, rows, d), v_pages.dtype),
            pltpu.VMEM((2, rows, 1), jnp.float32),
            pltpu.VMEM((2, rows, 1), jnp.float32),
            pltpu.VMEM((hq, d), jnp.float32),
            pltpu.VMEM((hq, 1), jnp.float32),
            pltpu.VMEM((hq, 1), jnp.float32),
            pltpu.SemaphoreType.DMA((2, 4)),
        ],
    )

    kernel = functools.partial(
        _decode_kernel_int8,
        page_size=page_size,
        n_kv_heads=hkv,
        scale=scale,
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, hq, d), q.dtype),
        interpret=interpret,
    )(tables, lengths, q, k_flat, v_flat, ks_flat, vs_flat)


PREFILL_Q_BLOCK = 8


def _prefill_kernel(
    # scalar prefetch
    tables_ref,      # [B, max_pages] SMEM
    lengths_ref,     # [B] SMEM (prefix length BEFORE this chunk)
    # inputs
    q_ref,           # [1, QB, Hq, D] VMEM (one query block)
    k_pages_hbm,     # [P, page*Hkv, D] ANY/HBM (flattened view)
    v_pages_hbm,     # [P, page*Hkv, D] ANY/HBM
    # output
    o_ref,           # [1, QB, Hq, D] VMEM
    # scratch
    k_buf,           # [2, page*Hkv, D] VMEM
    v_buf,           # [2, page*Hkv, D] VMEM
    acc_ref,         # [QB*Hq, D] f32
    m_ref,           # [QB*Hq, 1] f32
    l_ref,           # [QB*Hq, 1] f32
    sems,            # DMA sems [2, 2]
    *,
    page_size: int,
    n_kv_heads: int,
    scale: float,
):
    """Ragged paged attention over an S>1 query block: chunked prefill
    on top of an arbitrary-length paged prefix, O(actual context) page
    traffic per block (VERDICT r2 #2 — replaces the full-capacity XLA
    gather on TPU). Same Mosaic-shaped design as the decode kernel
    (flat [page*Hkv, D] tiles, one DMA per page, all head pairs in one
    MXU matmul, invalid pairs masked before the online softmax) plus a
    causal mask inside the chunk: query at position len+t sees kv
    positions <= len+t."""
    b = pl.program_id(0)
    qb = pl.program_id(1)

    length = lengths_ref[b]
    _, qblk, hq, d = q_ref.shape
    hkv = n_kv_heads
    group = hq // hkv
    rows = page_size * hkv
    qrows = qblk * hq

    # this block's highest query position decides how many pages to walk
    hi_pos = length + (qb + 1) * qblk - 1
    n_pages = jax.lax.div(hi_pos, page_size) + 1

    m_ref[:] = jnp.full_like(m_ref, NEG_INF)
    l_ref[:] = jnp.zeros_like(l_ref)
    acc_ref[:] = jnp.zeros_like(acc_ref)

    def start_fetch(i, slot):
        page_id = tables_ref[b, i]
        pltpu.make_async_copy(
            k_pages_hbm.at[page_id], k_buf.at[slot], sems.at[slot, 0]
        ).start()
        pltpu.make_async_copy(
            v_pages_hbm.at[page_id], v_buf.at[slot], sems.at[slot, 1]
        ).start()

    def wait_fetch(i, slot):
        page_id = tables_ref[b, i]
        pltpu.make_async_copy(
            k_pages_hbm.at[page_id], k_buf.at[slot], sems.at[slot, 0]
        ).wait()
        pltpu.make_async_copy(
            v_pages_hbm.at[page_id], v_buf.at[slot], sems.at[slot, 1]
        ).wait()

    start_fetch(0, 0)

    q = q_ref[0].astype(jnp.float32).reshape(qrows, d) * scale

    # row r = (token t within block) * Hq + head h; col j of a flat page
    # = (token within page) * Hkv + kv head
    j = jax.lax.broadcasted_iota(jnp.int32, (qrows, rows), 1)
    r = jax.lax.broadcasted_iota(jnp.int32, (qrows, rows), 0)
    pair_ok = jax.lax.rem(j, hkv) == jax.lax.div(
        jax.lax.rem(r, hq), group
    )
    tok_of_j = jax.lax.div(j, hkv)
    q_pos = length + qb * qblk + jax.lax.div(r, hq)

    def body(i, _):
        slot = jax.lax.rem(i, 2)

        @pl.when(i + 1 < n_pages)
        def _():
            start_fetch(i + 1, 1 - slot)

        wait_fetch(i, slot)
        k = k_buf[slot].astype(jnp.float32)           # [rows, D]
        v = v_buf[slot].astype(jnp.float32)

        logits = jax.lax.dot_general(
            q, k,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                             # [qrows, rows]
        kv_pos = i * page_size + tok_of_j
        valid = pair_ok & (kv_pos <= q_pos)
        logits = jnp.where(valid, logits, NEG_INF)

        m_prev = m_ref[:]
        m_new = jnp.maximum(
            m_prev, jnp.max(logits, axis=1, keepdims=True)
        )
        p = jnp.exp(logits - m_new)
        alpha = jnp.exp(m_prev - m_new)

        l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=1, keepdims=True)
        pv = jax.lax.dot_general(
            p, v,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_ref[:] = acc_ref[:] * alpha + pv
        m_ref[:] = m_new
        return 0

    jax.lax.fori_loop(0, n_pages, body, 0)

    denom = jnp.maximum(l_ref[:], 1e-30)
    o_ref[0] = (acc_ref[:] / denom).reshape(qblk, hq, d).astype(
        o_ref.dtype
    )


@functools.partial(
    jax.jit, static_argnames=("page_size", "interpret")
)
def paged_attention_prefill(
    q: jax.Array,          # [B, S, Hq, D] chunk queries
    k_pages: jax.Array,    # [P, page, Hkv, D]
    v_pages: jax.Array,    # [P, page, Hkv, D]
    tables: jax.Array,     # [B, max_pages] int32
    lengths: jax.Array,    # [B] int32 prefix length BEFORE the chunk
    *,
    page_size: int,
    interpret: bool = False,
) -> jax.Array:
    """S>1 companion to paged_attention_decode: the chunk's KV must
    already be written into the pages (the kv_hook does this first).
    Requires S % PREFILL_Q_BLOCK == 0 (engine chunk widths are powers
    of two >= 16; callers fall back to the XLA gather otherwise)."""
    b, s, hq, d = q.shape
    p_count, _, hkv, _ = k_pages.shape
    scale = 1.0 / float(np.sqrt(d))
    rows = page_size * hkv
    qblk = PREFILL_Q_BLOCK
    if s % qblk != 0:
        raise ValueError(f"S={s} not divisible by {qblk}")

    k_flat = k_pages.reshape(p_count, rows, d)
    v_flat = v_pages.reshape(p_count, rows, d)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, s // qblk),
        in_specs=[
            pl.BlockSpec(
                (1, qblk, hq, d), lambda i, j, *_: (i, j, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec(
            (1, qblk, hq, d), lambda i, j, *_: (i, j, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        scratch_shapes=[
            pltpu.VMEM((2, rows, d), k_pages.dtype),
            pltpu.VMEM((2, rows, d), v_pages.dtype),
            pltpu.VMEM((qblk * hq, d), jnp.float32),
            pltpu.VMEM((qblk * hq, 1), jnp.float32),
            pltpu.VMEM((qblk * hq, 1), jnp.float32),
            pltpu.SemaphoreType.DMA((2, 2)),
        ],
    )

    kernel = functools.partial(
        _prefill_kernel,
        page_size=page_size,
        n_kv_heads=hkv,
        scale=scale,
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, s, hq, d), q.dtype),
        interpret=interpret,
    )(tables, lengths, q, k_flat, v_flat)


def _prefill_kernel_int8(
    # scalar prefetch
    tables_ref,      # [B, max_pages] SMEM
    lengths_ref,     # [B] SMEM (prefix length BEFORE this chunk)
    # inputs
    q_ref,           # [1, QB, Hq, D] VMEM
    k_pages_hbm,     # [P, page*Hkv, D] int8 ANY/HBM
    v_pages_hbm,     # [P, page*Hkv, D] int8 ANY/HBM
    k_scale_hbm,     # [P, page*Hkv, 1] f32 ANY/HBM
    v_scale_hbm,     # [P, page*Hkv, 1] f32 ANY/HBM
    # output
    o_ref,           # [1, QB, Hq, D] VMEM
    # scratch
    k_buf,           # [2, page*Hkv, D] int8 VMEM
    v_buf,
    ks_buf,          # [2, page*Hkv, 1] f32 VMEM
    vs_buf,
    acc_ref, m_ref, l_ref,
    sems,            # DMA sems [2, 4]
    *,
    page_size: int,
    n_kv_heads: int,
    scale: float,
):
    """int8-KV variant of _prefill_kernel: same ragged chunked-prefill
    walk (O(actual context) page traffic per query block) with the
    int8 decode kernel's dequant posture — int8 page tiles at half the
    DMA bytes plus [rows, 1] f32 scale tiles broadcast over lanes."""
    b = pl.program_id(0)
    qb = pl.program_id(1)

    length = lengths_ref[b]
    _, qblk, hq, d = q_ref.shape
    hkv = n_kv_heads
    group = hq // hkv
    rows = page_size * hkv
    qrows = qblk * hq

    hi_pos = length + (qb + 1) * qblk - 1
    n_pages = jax.lax.div(hi_pos, page_size) + 1

    m_ref[:] = jnp.full_like(m_ref, NEG_INF)
    l_ref[:] = jnp.zeros_like(l_ref)
    acc_ref[:] = jnp.zeros_like(acc_ref)

    def start_fetch(i, slot):
        page_id = tables_ref[b, i]
        for src, dst, sem in (
            (k_pages_hbm, k_buf, 0), (v_pages_hbm, v_buf, 1),
            (k_scale_hbm, ks_buf, 2), (v_scale_hbm, vs_buf, 3),
        ):
            pltpu.make_async_copy(
                src.at[page_id], dst.at[slot], sems.at[slot, sem]
            ).start()

    def wait_fetch(i, slot):
        page_id = tables_ref[b, i]
        for src, dst, sem in (
            (k_pages_hbm, k_buf, 0), (v_pages_hbm, v_buf, 1),
            (k_scale_hbm, ks_buf, 2), (v_scale_hbm, vs_buf, 3),
        ):
            pltpu.make_async_copy(
                src.at[page_id], dst.at[slot], sems.at[slot, sem]
            ).wait()

    start_fetch(0, 0)

    q = q_ref[0].astype(jnp.float32).reshape(qrows, d) * scale

    j = jax.lax.broadcasted_iota(jnp.int32, (qrows, rows), 1)
    r = jax.lax.broadcasted_iota(jnp.int32, (qrows, rows), 0)
    pair_ok = jax.lax.rem(j, hkv) == jax.lax.div(
        jax.lax.rem(r, hq), group
    )
    tok_of_j = jax.lax.div(j, hkv)
    q_pos = length + qb * qblk + jax.lax.div(r, hq)

    def body(i, _):
        slot = jax.lax.rem(i, 2)

        @pl.when(i + 1 < n_pages)
        def _():
            start_fetch(i + 1, 1 - slot)

        wait_fetch(i, slot)
        k = k_buf[slot].astype(jnp.float32) * ks_buf[slot]
        v = v_buf[slot].astype(jnp.float32) * vs_buf[slot]

        logits = jax.lax.dot_general(
            q, k,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                             # [qrows, rows]
        kv_pos = i * page_size + tok_of_j
        valid = pair_ok & (kv_pos <= q_pos)
        logits = jnp.where(valid, logits, NEG_INF)

        m_prev = m_ref[:]
        m_new = jnp.maximum(
            m_prev, jnp.max(logits, axis=1, keepdims=True)
        )
        p = jnp.exp(logits - m_new)
        alpha = jnp.exp(m_prev - m_new)

        l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=1, keepdims=True)
        pv = jax.lax.dot_general(
            p, v,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_ref[:] = acc_ref[:] * alpha + pv
        m_ref[:] = m_new
        return 0

    jax.lax.fori_loop(0, n_pages, body, 0)

    denom = jnp.maximum(l_ref[:], 1e-30)
    o_ref[0] = (acc_ref[:] / denom).reshape(qblk, hq, d).astype(
        o_ref.dtype
    )


@functools.partial(
    jax.jit, static_argnames=("page_size", "interpret")
)
def paged_attention_prefill_int8(
    q: jax.Array,          # [B, S, Hq, D]
    k_pages: jax.Array,    # [P, page, Hkv, D] int8
    v_pages: jax.Array,    # [P, page, Hkv, D] int8
    k_scale: jax.Array,    # [P, page, Hkv] f32
    v_scale: jax.Array,    # [P, page, Hkv] f32
    tables: jax.Array,     # [B, max_pages] int32
    lengths: jax.Array,    # [B] int32 prefix length BEFORE the chunk
    *,
    page_size: int,
    interpret: bool = False,
) -> jax.Array:
    b, s, hq, d = q.shape
    p_count, _, hkv, _ = k_pages.shape
    scale = 1.0 / float(np.sqrt(d))
    rows = page_size * hkv
    qblk = PREFILL_Q_BLOCK
    if s % qblk != 0:
        raise ValueError(f"S={s} not divisible by {qblk}")

    k_flat = k_pages.reshape(p_count, rows, d)
    v_flat = v_pages.reshape(p_count, rows, d)
    ks_flat = k_scale.reshape(p_count, rows, 1)
    vs_flat = v_scale.reshape(p_count, rows, 1)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, s // qblk),
        in_specs=[
            pl.BlockSpec(
                (1, qblk, hq, d), lambda i, j, *_: (i, j, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec(
            (1, qblk, hq, d), lambda i, j, *_: (i, j, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        scratch_shapes=[
            pltpu.VMEM((2, rows, d), k_pages.dtype),
            pltpu.VMEM((2, rows, d), v_pages.dtype),
            pltpu.VMEM((2, rows, 1), jnp.float32),
            pltpu.VMEM((2, rows, 1), jnp.float32),
            pltpu.VMEM((qblk * hq, d), jnp.float32),
            pltpu.VMEM((qblk * hq, 1), jnp.float32),
            pltpu.VMEM((qblk * hq, 1), jnp.float32),
            pltpu.SemaphoreType.DMA((2, 4)),
        ],
    )

    kernel = functools.partial(
        _prefill_kernel_int8,
        page_size=page_size,
        n_kv_heads=hkv,
        scale=scale,
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, s, hq, d), q.dtype),
        interpret=interpret,
    )(tables, lengths, q, k_flat, v_flat, ks_flat, vs_flat)


# ---------------------------------------------------------------------------
# Unified ragged kernel: one dispatch for mixed prefill-chunks + decode-lanes
# ---------------------------------------------------------------------------

RAGGED_Q_BLOCK = 8


def ragged_block_layout(
    q_lens: "list[int] | tuple[int, ...]", q_block: int = RAGGED_Q_BLOCK,
) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Static q-block layout for the ragged kernel.

    Each row's query segment is padded up to q-block granularity (at
    most q_block-1 pad positions per row — never to the batch max, and
    never to the KV capacity), so a block always belongs to exactly one
    row. Returns int32 arrays:

      row_of_block [NB]  — which ragged row each q-block serves
      blk_in_row   [NB]  — the block's index within its row
      gather_idx   [NB*q_block] — padded position -> flat token index
                    (pad slots point at token 0; the kernel masks them)
      scatter_idx  [T]   — flat token index -> padded position

    The maps are pure functions of the per-row query lengths, which the
    engine's fused dispatch keys statically (decode lanes are length 1,
    chunk rows are the fixed scheduler chunk width) — so there is one
    compile per (batch, chunks) shape, never per chunk width."""
    row_of_block: list[int] = []
    blk_in_row: list[int] = []
    gather: list[int] = []
    scatter: list[int] = []
    src = 0
    for r, ql in enumerate(q_lens):
        if ql <= 0:
            raise ValueError(f"ragged row {r} has query_len {ql}")
        n_blocks = -(-ql // q_block)
        base = len(row_of_block) * q_block
        for b in range(n_blocks):
            row_of_block.append(r)
            blk_in_row.append(b)
            for t in range(q_block):
                tok = b * q_block + t
                gather.append(src + tok if tok < ql else 0)
        scatter.extend(base + t for t in range(ql))
        src += ql
    return (
        np.asarray(row_of_block, np.int32),
        np.asarray(blk_in_row, np.int32),
        np.asarray(gather, np.int32),
        np.asarray(scatter, np.int32),
    )


def ragged_shard_layout(
    n_decode: int, n_chunks: int, chunk_width: int, n_shards: int,
) -> dict[str, np.ndarray]:
    """Static shard-local ragged layout for the dp-sharded fused window
    (docs/serving.md).

    The sharded window's token stream is [n_shards, T_local] with each
    shard laid out exactly like the dp=1 ragged stream — its
    ``n_decode/n_shards`` decode lanes first, then its
    ``n_chunks/n_shards`` chunk rows of ``chunk_width`` tokens — and
    rows stored shard-major, so every map here is per-shard-periodic
    and no q-row (a fortiori no q-block) ever straddles a shard
    boundary: each dp shard's slice of the stream is a complete,
    self-contained ragged sub-batch the kernel (or the XLA reference)
    can consume with zero cross-shard reads. Returns int32 maps over
    the FLAT shard-major token/row order:

      row_of_token [S*T_local] — flat token -> flat ragged row
      off_in_row   [S*T_local] — token's offset within its row's chunk
      dec_rows     [n_decode]  — flat row ids of the decode lanes
      ch_rows      [n_chunks]  — flat row ids of the chunk rows
      dec_toks     [n_decode]  — flat token ids of the decode queries
      ch_toks      [n_chunks*chunk_width] — flat token ids of chunk qs
      inv_perm     [S*T_local] — concat([dec, ch]) order -> flat order

    With ``n_shards == 1`` every map degenerates to the unsharded fused
    layout (decode tokens first, chunks after, inv_perm identity)."""
    if n_decode % n_shards or n_chunks % n_shards:
        raise ValueError(
            f"decode {n_decode} / chunk {n_chunks} rows must divide "
            f"over {n_shards} dp shards"
        )
    bl = n_decode // n_shards
    cl = n_chunks // n_shards
    r_local = bl + cl
    t_local = bl + cl * chunk_width
    sh = np.arange(n_shards, dtype=np.int32)
    local_row = np.concatenate([
        np.arange(bl, dtype=np.int32),
        bl + np.repeat(np.arange(cl, dtype=np.int32), chunk_width),
    ]) if cl else np.arange(bl, dtype=np.int32)
    local_off = np.concatenate([
        np.zeros(bl, np.int32),
        np.tile(np.arange(chunk_width, dtype=np.int32), cl),
    ]) if cl else np.zeros(bl, np.int32)
    row_of_token = (
        sh[:, None] * r_local + local_row[None]
    ).reshape(-1)
    off_in_row = np.tile(local_off, n_shards)
    dec_rows = (
        sh[:, None] * r_local + np.arange(bl, dtype=np.int32)[None]
    ).reshape(-1)
    ch_rows = (
        sh[:, None] * r_local + bl
        + np.arange(cl, dtype=np.int32)[None]
    ).reshape(-1)
    dec_toks = (
        sh[:, None] * t_local + np.arange(bl, dtype=np.int32)[None]
    ).reshape(-1)
    ch_toks = (
        sh[:, None] * t_local + bl
        + np.arange(cl * chunk_width, dtype=np.int32)[None]
    ).reshape(-1)
    perm = np.concatenate([dec_toks, ch_toks])
    inv_perm = np.argsort(perm).astype(np.int32)
    return {
        "row_of_token": row_of_token, "off_in_row": off_in_row,
        "dec_rows": dec_rows, "ch_rows": ch_rows,
        "dec_toks": dec_toks, "ch_toks": ch_toks,
        "inv_perm": inv_perm,
    }


def _ragged_kernel(
    # scalar prefetch
    tables_ref,      # [R, max_pages] SMEM
    plens_ref,       # [R] SMEM prefix length BEFORE each row's queries
    qlens_ref,       # [R] SMEM query tokens per row (decode lane = 1)
    rowmap_ref,      # [NB] SMEM q-block -> row
    blkmap_ref,      # [NB] SMEM q-block -> index within row
    # inputs
    q_ref,           # [1, QB*Hq, D] VMEM (one block-aligned q tile)
    k_pages_hbm,     # [P, page*Hkv, D] ANY/HBM (flattened view)
    v_pages_hbm,     # [P, page*Hkv, D] ANY/HBM
    # output
    o_ref,           # [1, QB*Hq, D] VMEM
    # scratch
    k_buf,           # [2, page*Hkv, D] VMEM
    v_buf,           # [2, page*Hkv, D] VMEM
    acc_ref,         # [QB*Hq, D] f32
    m_ref,           # [QB*Hq, 1] f32
    l_ref,           # [QB*Hq, 1] f32
    sems,            # DMA sems [2, 2]
    *,
    page_size: int,
    n_kv_heads: int,
    q_block: int,
    scale: float,
):
    """Unified ragged paged attention (PAPERS.md lead citation): one
    grid over the ragged [prefill-chunks + decode-lanes] batch. Every
    q-block belongs to exactly one row (ragged_block_layout); a decode
    lane is simply a row with query_len 1 whose pad q positions are
    masked, a prefill chunk a row with query_len S — the SAME kernel,
    page walk, and online softmax serve both, so a scheduler window's
    chunk writes and decode steps share one dispatch with no padding
    to the batch max length. Same Mosaic posture as the split kernels:
    flat [page*Hkv, D] tiles, one double-buffered DMA per page, all
    (query-head, kv-row) pairs in one MXU matmul, invalid pairs masked
    to -inf before the online softmax."""
    blk = pl.program_id(0)
    r = rowmap_ref[blk]
    qb = blkmap_ref[blk]
    prefix = plens_ref[r]
    qlen = qlens_ref[r]

    qrows, d = q_ref.shape[1], q_ref.shape[2]
    hq = qrows // q_block
    hkv = n_kv_heads
    group = hq // hkv
    rows = page_size * hkv

    # this block's highest VALID query position bounds the page walk:
    # O(actual context) traffic per block, never table capacity
    hi_tok = jnp.minimum(qlen, (qb + 1) * q_block)   # exclusive
    n_pages = jax.lax.div(prefix + hi_tok - 1, page_size) + 1

    m_ref[:] = jnp.full_like(m_ref, NEG_INF)
    l_ref[:] = jnp.zeros_like(l_ref)
    acc_ref[:] = jnp.zeros_like(acc_ref)

    def start_fetch(i, slot):
        page_id = tables_ref[r, i]
        pltpu.make_async_copy(
            k_pages_hbm.at[page_id], k_buf.at[slot], sems.at[slot, 0]
        ).start()
        pltpu.make_async_copy(
            v_pages_hbm.at[page_id], v_buf.at[slot], sems.at[slot, 1]
        ).start()

    def wait_fetch(i, slot):
        page_id = tables_ref[r, i]
        pltpu.make_async_copy(
            k_pages_hbm.at[page_id], k_buf.at[slot], sems.at[slot, 0]
        ).wait()
        pltpu.make_async_copy(
            v_pages_hbm.at[page_id], v_buf.at[slot], sems.at[slot, 1]
        ).wait()

    start_fetch(0, 0)

    q = q_ref[0].astype(jnp.float32) * scale          # [qrows, D]

    # row rr = (token t within block) * Hq + head h; col j of a flat
    # page = (token within page) * Hkv + kv head
    j = jax.lax.broadcasted_iota(jnp.int32, (qrows, rows), 1)
    rr = jax.lax.broadcasted_iota(jnp.int32, (qrows, rows), 0)
    pair_ok = jax.lax.rem(j, hkv) == jax.lax.div(
        jax.lax.rem(rr, hq), group
    )
    tok_of_j = jax.lax.div(j, hkv)
    t_of_r = jax.lax.div(rr, hq)                      # token in block
    q_pos = prefix + qb * q_block + t_of_r
    # pad q positions past the row's ragged query length mask out
    q_valid = (qb * q_block + t_of_r) < qlen

    def body(i, _):
        slot = jax.lax.rem(i, 2)

        @pl.when(i + 1 < n_pages)
        def _():
            start_fetch(i + 1, 1 - slot)

        wait_fetch(i, slot)
        k = k_buf[slot].astype(jnp.float32)           # [rows, D]
        v = v_buf[slot].astype(jnp.float32)

        logits = jax.lax.dot_general(
            q, k,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                             # [qrows, rows]
        kv_pos = i * page_size + tok_of_j
        valid = pair_ok & q_valid & (kv_pos <= q_pos)
        logits = jnp.where(valid, logits, NEG_INF)

        m_prev = m_ref[:]
        m_new = jnp.maximum(
            m_prev, jnp.max(logits, axis=1, keepdims=True)
        )
        p = jnp.exp(logits - m_new)
        alpha = jnp.exp(m_prev - m_new)

        l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=1, keepdims=True)
        pv = jax.lax.dot_general(
            p, v,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_ref[:] = acc_ref[:] * alpha + pv
        m_ref[:] = m_new
        return 0

    jax.lax.fori_loop(0, n_pages, body, 0)

    denom = jnp.maximum(l_ref[:], 1e-30)
    o_ref[0] = (acc_ref[:] / denom).astype(o_ref.dtype)


def _ragged_kernel_int8(
    # scalar prefetch
    tables_ref, plens_ref, qlens_ref, rowmap_ref, blkmap_ref,
    # inputs
    q_ref,           # [1, QB*Hq, D] VMEM
    k_pages_hbm,     # [P, page*Hkv, D] int8 ANY/HBM
    v_pages_hbm,     # [P, page*Hkv, D] int8 ANY/HBM
    k_scale_hbm,     # [P, page*Hkv, 1] f32 ANY/HBM
    v_scale_hbm,     # [P, page*Hkv, 1] f32 ANY/HBM
    # output
    o_ref,           # [1, QB*Hq, D] VMEM
    # scratch
    k_buf, v_buf,    # [2, page*Hkv, D] int8 VMEM
    ks_buf, vs_buf,  # [2, page*Hkv, 1] f32 VMEM
    acc_ref, m_ref, l_ref,
    sems,            # DMA sems [2, 4]
    *,
    page_size: int,
    n_kv_heads: int,
    q_block: int,
    scale: float,
):
    """int8-KV variant of _ragged_kernel: the unified ragged walk with
    the in-kernel dequant posture the split int8 kernels established —
    int8 page tiles at half the DMA bytes plus [rows, 1] f32 scale
    tiles broadcast over lanes. One dispatch covers the window's mixed
    chunk/decode batch reading the quantized pool directly."""
    blk = pl.program_id(0)
    r = rowmap_ref[blk]
    qb = blkmap_ref[blk]
    prefix = plens_ref[r]
    qlen = qlens_ref[r]

    qrows, d = q_ref.shape[1], q_ref.shape[2]
    hq = qrows // q_block
    hkv = n_kv_heads
    group = hq // hkv
    rows = page_size * hkv

    hi_tok = jnp.minimum(qlen, (qb + 1) * q_block)
    n_pages = jax.lax.div(prefix + hi_tok - 1, page_size) + 1

    m_ref[:] = jnp.full_like(m_ref, NEG_INF)
    l_ref[:] = jnp.zeros_like(l_ref)
    acc_ref[:] = jnp.zeros_like(acc_ref)

    def start_fetch(i, slot):
        page_id = tables_ref[r, i]
        for src, dst, sem in (
            (k_pages_hbm, k_buf, 0), (v_pages_hbm, v_buf, 1),
            (k_scale_hbm, ks_buf, 2), (v_scale_hbm, vs_buf, 3),
        ):
            pltpu.make_async_copy(
                src.at[page_id], dst.at[slot], sems.at[slot, sem]
            ).start()

    def wait_fetch(i, slot):
        page_id = tables_ref[r, i]
        for src, dst, sem in (
            (k_pages_hbm, k_buf, 0), (v_pages_hbm, v_buf, 1),
            (k_scale_hbm, ks_buf, 2), (v_scale_hbm, vs_buf, 3),
        ):
            pltpu.make_async_copy(
                src.at[page_id], dst.at[slot], sems.at[slot, sem]
            ).wait()

    start_fetch(0, 0)

    q = q_ref[0].astype(jnp.float32) * scale

    j = jax.lax.broadcasted_iota(jnp.int32, (qrows, rows), 1)
    rr = jax.lax.broadcasted_iota(jnp.int32, (qrows, rows), 0)
    pair_ok = jax.lax.rem(j, hkv) == jax.lax.div(
        jax.lax.rem(rr, hq), group
    )
    tok_of_j = jax.lax.div(j, hkv)
    t_of_r = jax.lax.div(rr, hq)
    q_pos = prefix + qb * q_block + t_of_r
    q_valid = (qb * q_block + t_of_r) < qlen

    def body(i, _):
        slot = jax.lax.rem(i, 2)

        @pl.when(i + 1 < n_pages)
        def _():
            start_fetch(i + 1, 1 - slot)

        wait_fetch(i, slot)
        k = k_buf[slot].astype(jnp.float32) * ks_buf[slot]
        v = v_buf[slot].astype(jnp.float32) * vs_buf[slot]

        logits = jax.lax.dot_general(
            q, k,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        kv_pos = i * page_size + tok_of_j
        valid = pair_ok & q_valid & (kv_pos <= q_pos)
        logits = jnp.where(valid, logits, NEG_INF)

        m_prev = m_ref[:]
        m_new = jnp.maximum(
            m_prev, jnp.max(logits, axis=1, keepdims=True)
        )
        p = jnp.exp(logits - m_new)
        alpha = jnp.exp(m_prev - m_new)

        l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=1, keepdims=True)
        pv = jax.lax.dot_general(
            p, v,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_ref[:] = acc_ref[:] * alpha + pv
        m_ref[:] = m_new
        return 0

    jax.lax.fori_loop(0, n_pages, body, 0)

    denom = jnp.maximum(l_ref[:], 1e-30)
    o_ref[0] = (acc_ref[:] / denom).astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("page_size", "q_block", "interpret")
)
def paged_attention_ragged(
    q: jax.Array,            # [NB, q_block, Hq, D] block-aligned queries
    k_pages: jax.Array,      # [P, page, Hkv, D]
    v_pages: jax.Array,      # [P, page, Hkv, D]
    tables: jax.Array,       # [R, max_pages] int32
    prefix_lens: jax.Array,  # [R] int32 KV tokens BEFORE each row's queries
    q_lens: jax.Array,       # [R] int32 ragged query length per row
    row_of_block: jax.Array,  # [NB] int32 (ragged_block_layout)
    blk_in_row: jax.Array,    # [NB] int32
    *,
    page_size: int,
    q_block: int = RAGGED_Q_BLOCK,
    interpret: bool = False,
) -> jax.Array:
    """One kernel over the ragged [prefill-chunks + decode-lanes]
    batch: row i contributes q_lens[i] query tokens (1 for a decode
    lane, the chunk width for a prefill chunk) on top of prefix_lens[i]
    tokens already in its pages. Callers lay queries out block-aligned
    via ragged_block_layout; output has the same [NB, q_block, Hq, D]
    layout (pad positions are garbage, gathered away by scatter_idx)."""
    nb, qblk, hq, d = q.shape
    if qblk != q_block:
        raise ValueError(f"q block dim {qblk} != q_block {q_block}")
    p_count, _, hkv, _ = k_pages.shape
    scale = 1.0 / float(np.sqrt(d))
    rows = page_size * hkv
    qrows = q_block * hq

    k_flat = k_pages.reshape(p_count, rows, d)
    v_flat = v_pages.reshape(p_count, rows, d)
    q_flat = q.reshape(nb, qrows, d)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=5,
        grid=(nb,),
        in_specs=[
            pl.BlockSpec(
                (1, qrows, d), lambda i, *_: (i, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec(
            (1, qrows, d), lambda i, *_: (i, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        scratch_shapes=[
            pltpu.VMEM((2, rows, d), k_pages.dtype),
            pltpu.VMEM((2, rows, d), v_pages.dtype),
            pltpu.VMEM((qrows, d), jnp.float32),
            pltpu.VMEM((qrows, 1), jnp.float32),
            pltpu.VMEM((qrows, 1), jnp.float32),
            pltpu.SemaphoreType.DMA((2, 2)),
        ],
    )

    kernel = functools.partial(
        _ragged_kernel,
        page_size=page_size,
        n_kv_heads=hkv,
        q_block=q_block,
        scale=scale,
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((nb, qrows, d), q.dtype),
        interpret=interpret,
    )(tables, prefix_lens, q_lens, row_of_block, blk_in_row,
      q_flat, k_flat, v_flat)
    return out.reshape(nb, q_block, hq, d)


@functools.partial(
    jax.jit, static_argnames=("page_size", "q_block", "interpret")
)
def paged_attention_ragged_int8(
    q: jax.Array,            # [NB, q_block, Hq, D]
    k_pages: jax.Array,      # [P, page, Hkv, D] int8
    v_pages: jax.Array,      # [P, page, Hkv, D] int8
    k_scale: jax.Array,      # [P, page, Hkv] f32
    v_scale: jax.Array,      # [P, page, Hkv] f32
    tables: jax.Array,       # [R, max_pages] int32
    prefix_lens: jax.Array,  # [R] int32
    q_lens: jax.Array,       # [R] int32
    row_of_block: jax.Array,  # [NB] int32
    blk_in_row: jax.Array,    # [NB] int32
    *,
    page_size: int,
    q_block: int = RAGGED_Q_BLOCK,
    interpret: bool = False,
) -> jax.Array:
    nb, qblk, hq, d = q.shape
    if qblk != q_block:
        raise ValueError(f"q block dim {qblk} != q_block {q_block}")
    p_count, _, hkv, _ = k_pages.shape
    scale = 1.0 / float(np.sqrt(d))
    rows = page_size * hkv
    qrows = q_block * hq

    k_flat = k_pages.reshape(p_count, rows, d)
    v_flat = v_pages.reshape(p_count, rows, d)
    ks_flat = k_scale.reshape(p_count, rows, 1)
    vs_flat = v_scale.reshape(p_count, rows, 1)
    q_flat = q.reshape(nb, qrows, d)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=5,
        grid=(nb,),
        in_specs=[
            pl.BlockSpec(
                (1, qrows, d), lambda i, *_: (i, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec(
            (1, qrows, d), lambda i, *_: (i, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        scratch_shapes=[
            pltpu.VMEM((2, rows, d), k_pages.dtype),
            pltpu.VMEM((2, rows, d), v_pages.dtype),
            pltpu.VMEM((2, rows, 1), jnp.float32),
            pltpu.VMEM((2, rows, 1), jnp.float32),
            pltpu.VMEM((qrows, d), jnp.float32),
            pltpu.VMEM((qrows, 1), jnp.float32),
            pltpu.VMEM((qrows, 1), jnp.float32),
            pltpu.SemaphoreType.DMA((2, 4)),
        ],
    )

    kernel = functools.partial(
        _ragged_kernel_int8,
        page_size=page_size,
        n_kv_heads=hkv,
        q_block=q_block,
        scale=scale,
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((nb, qrows, d), q.dtype),
        interpret=interpret,
    )(tables, prefix_lens, q_lens, row_of_block, blk_in_row,
      q_flat, k_flat, v_flat, ks_flat, vs_flat)
    return out.reshape(nb, q_block, hq, d)


@functools.partial(
    jax.jit, static_argnames=("page_size", "interpret")
)
def paged_attention_decode(
    q: jax.Array,          # [B, Hq, D]
    k_pages: jax.Array,    # [P, page, Hkv, D]
    v_pages: jax.Array,    # [P, page, Hkv, D]
    tables: jax.Array,     # [B, max_pages] int32
    lengths: jax.Array,    # [B] int32
    *,
    page_size: int,
    interpret: bool = False,
) -> jax.Array:
    b, hq, d = q.shape
    p_count, _, hkv, _ = k_pages.shape
    scale = 1.0 / float(np.sqrt(d))
    rows = page_size * hkv

    # Row-major bitcast: a page [page, Hkv, D] viewed as [page*Hkv, D]
    # so the kernel's DMAs and dots never slice the tiled Hkv dim.
    k_flat = k_pages.reshape(p_count, rows, d)
    v_flat = v_pages.reshape(p_count, rows, d)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b,),
        in_specs=[
            pl.BlockSpec(
                (1, hq, d), lambda i, *_: (i, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec(
            (1, hq, d), lambda i, *_: (i, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        scratch_shapes=[
            pltpu.VMEM((2, rows, d), k_pages.dtype),
            pltpu.VMEM((2, rows, d), v_pages.dtype),
            pltpu.VMEM((hq, d), jnp.float32),
            pltpu.VMEM((hq, 1), jnp.float32),
            pltpu.VMEM((hq, 1), jnp.float32),
            pltpu.SemaphoreType.DMA((2, 2)),
        ],
    )

    kernel = functools.partial(
        _decode_kernel,
        page_size=page_size,
        n_kv_heads=hkv,
        scale=scale,
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, hq, d), q.dtype),
        interpret=interpret,
    )(tables, lengths, q, k_flat, v_flat)
