"""Pallas TPU kernel: paged-attention decode.

The hot op of the serving engine (PAPERS.md: Ragged Paged Attention for
TPU): one query token per sequence attends over that sequence's KV pages
without materializing a gathered dense cache. Per sequence the kernel
walks its block table, DMAs pages HBM→VMEM double-buffered, and runs an
online-softmax accumulation (flash-attention style) with GQA.

Shapes
    q         [B, Hq, D]
    k_pages   [P, page, Hkv, D]   (one layer's pool)
    v_pages   [P, page, Hkv, D]
    tables    [B, max_pages]      int32 page ids (scalar-prefetched)
    lengths   [B]                 int32 valid tokens (scalar-prefetched)
    out       [B, Hq, D]

The XLA reference path (kv_pages.make_paged_kv_hook) stays the default
on CPU; the engine switches to this kernel on TPU via
ROOM_TPU_PAGED_KERNEL=pallas. Numerics are pinned against
ops.attention_ref in tests (interpret mode)."""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _decode_kernel(
    # scalar prefetch
    tables_ref,      # [B, max_pages] SMEM
    lengths_ref,     # [B] SMEM
    # inputs
    q_ref,           # [1, Hq, D] VMEM (this sequence's query)
    k_pages_hbm,     # [P, page, Hkv, D] ANY/HBM
    v_pages_hbm,     # [P, page, Hkv, D] ANY/HBM
    # output
    o_ref,           # [1, Hq, D] VMEM
    # scratch
    k_buf,           # [2, page, Hkv, D] VMEM
    v_buf,           # [2, page, Hkv, D] VMEM
    acc_ref,         # [Hq, D] f32 VMEM
    m_ref,           # [Hq, 1] f32 VMEM
    l_ref,           # [Hq, 1] f32 VMEM
    sems,            # DMA sems [2, 2]
    *,
    page_size: int,
    max_pages: int,
    scale: float,
):
    b = pl.program_id(0)
    length = lengths_ref[b]
    n_pages = jax.lax.div(length + page_size - 1, page_size)

    hq = q_ref.shape[1]
    hkv = k_buf.shape[2]
    d = q_ref.shape[2]
    group = hq // hkv

    m_ref[:] = jnp.full_like(m_ref, NEG_INF)
    l_ref[:] = jnp.zeros_like(l_ref)
    acc_ref[:] = jnp.zeros_like(acc_ref)

    def start_fetch(i, slot):
        page_id = tables_ref[b, i]
        pltpu.make_async_copy(
            k_pages_hbm.at[page_id], k_buf.at[slot], sems.at[slot, 0]
        ).start()
        pltpu.make_async_copy(
            v_pages_hbm.at[page_id], v_buf.at[slot], sems.at[slot, 1]
        ).start()

    def wait_fetch(i, slot):
        page_id = tables_ref[b, i]
        pltpu.make_async_copy(
            k_pages_hbm.at[page_id], k_buf.at[slot], sems.at[slot, 0]
        ).wait()
        pltpu.make_async_copy(
            v_pages_hbm.at[page_id], v_buf.at[slot], sems.at[slot, 1]
        ).wait()

    @pl.when(n_pages > 0)
    def _():
        start_fetch(0, 0)

    q = q_ref[0].astype(jnp.float32) * scale          # [Hq, D]
    qg = q.reshape(hkv, group, d)

    def body(i, _):
        slot = jax.lax.rem(i, 2)

        @pl.when(i + 1 < n_pages)
        def _():
            start_fetch(i + 1, 1 - slot)

        wait_fetch(i, slot)
        k = k_buf[slot].astype(jnp.float32)           # [page, Hkv, D]
        v = v_buf[slot].astype(jnp.float32)

        # logits [Hkv, G, page]
        logits = jax.lax.dot_general(
            qg, k,
            dimension_numbers=(((2,), (2,)), ((0,), (1,))),
            preferred_element_type=jnp.float32,
        )
        # mask past the sequence length within this page
        pos = i * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, 1, page_size), 2
        )
        logits = jnp.where(pos < length, logits, NEG_INF)
        logits2 = logits.reshape(hq, page_size)

        m_prev = m_ref[:]                              # [Hq, 1]
        m_new = jnp.maximum(
            m_prev, jnp.max(logits2, axis=1, keepdims=True)
        )
        p = jnp.exp(logits2 - m_new)                   # [Hq, page]
        alpha = jnp.exp(m_prev - m_new)                # [Hq, 1]

        l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=1, keepdims=True)
        # pv [Hkv, G, D]
        pv = jax.lax.dot_general(
            p.reshape(hkv, group, page_size), v,
            dimension_numbers=(((2,), (0,)), ((0,), (1,))),
            preferred_element_type=jnp.float32,
        )
        acc_ref[:] = acc_ref[:] * alpha + pv.reshape(hq, d)
        m_ref[:] = m_new
        return 0

    jax.lax.fori_loop(0, n_pages, body, 0)

    denom = jnp.maximum(l_ref[:], 1e-30)
    o_ref[0] = (acc_ref[:] / denom).astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("page_size", "interpret")
)
def paged_attention_decode(
    q: jax.Array,          # [B, Hq, D]
    k_pages: jax.Array,    # [P, page, Hkv, D]
    v_pages: jax.Array,    # [P, page, Hkv, D]
    tables: jax.Array,     # [B, max_pages] int32
    lengths: jax.Array,    # [B] int32
    *,
    page_size: int,
    interpret: bool = False,
) -> jax.Array:
    b, hq, d = q.shape
    _, _, hkv, _ = k_pages.shape
    max_pages = tables.shape[1]
    scale = 1.0 / float(np.sqrt(d))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b,),
        in_specs=[
            pl.BlockSpec(
                (1, hq, d), lambda i, *_: (i, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec(
            (1, hq, d), lambda i, *_: (i, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        scratch_shapes=[
            pltpu.VMEM((2, page_size, hkv, d), k_pages.dtype),
            pltpu.VMEM((2, page_size, hkv, d), v_pages.dtype),
            pltpu.VMEM((hq, d), jnp.float32),
            pltpu.VMEM((hq, 1), jnp.float32),
            pltpu.VMEM((hq, 1), jnp.float32),
            pltpu.SemaphoreType.DMA((2, 2)),
        ],
    )

    kernel = functools.partial(
        _decode_kernel,
        page_size=page_size,
        max_pages=max_pages,
        scale=scale,
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, hq, d), q.dtype),
        interpret=interpret,
    )(tables, lengths, q, k_pages, v_pages)
