"""On-mesh speculative-drafting primitives (docs/serving.md).

The serving engine's dispatch window keeps a device-resident recent-token
tail per decode lane so prompt-lookup (n-gram) drafts are computed ON THE
MESH, inside the jitted ``lax.scan`` step — the reason the old
speculative path had to flush the multi-step pipeline was that drafting
read each session's *host-side* history, which an undrained window runs
ahead of. With the tail on device, a spec round is just a window step
that happens to emit up to ``1 + gamma`` tokens.

Three pure-JAX helpers live here so they are unit-testable against the
host reference (``engine.propose_ngram``):

``ngram_propose``
    Batched prompt-lookup over the tail — trailing 3-gram match with a
    2-gram fallback, most recent occurrence wins, exactly the host
    rule. Tokens are right-aligned in the tail; ``-1`` marks padding
    (never a valid token, so padded windows can't match).
``shift_tail``
    Roll a variable number of freshly emitted tokens per lane into the
    tail (the per-step carry update).
``draft_propose``
    The optional second draft tier (``ROOM_TPU_DRAFT_MODEL``): a tiny
    on-mesh qwen3 decoder proposes ``gamma`` greedy tokens from the
    tail's trailing window. The window is prefilled ONCE into a
    device-resident dense draft KV cache, then each proposal step is a
    single-token incremental forward against that cache — O(window +
    gamma) draft tokens per proposal round instead of the old
    O(gamma * window) full re-forwards. A wrong draft is merely
    rejected by the target's verify, never emitted.

reference: none (the reference delegates decoding to Ollama); the
prompt-lookup rule mirrors engine.propose_ngram and the verify rule is
sampler.spec_verify.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["seed_tail", "ngram_propose", "shift_tail", "draft_propose"]

# tail padding marker: never equals a real token id, so a window that
# still contains padding can never match a (real-token) pattern
TAIL_PAD = -1


def seed_tail(tokens: list, tail_len: int) -> np.ndarray:
    """Host-side seed for one lane's device tail: the last ``tail_len``
    tokens right-aligned, left-padded with ``TAIL_PAD``. ``tokens``
    must end with the lane's feed token (the pending token about to be
    dispatched), matching the in-scan invariant."""
    arr = np.full((tail_len,), TAIL_PAD, np.int32)
    src = tokens[-tail_len:]
    if src:
        arr[tail_len - len(src):] = src
    return arr


def _match_last(tail: jax.Array, n: int) -> tuple[jax.Array, jax.Array]:
    """Most recent occurrence of each row's trailing ``n``-gram within
    the body (windows may not reach the final token, and the trailing
    n-gram itself is excluded) — the host rule, batched. Returns
    (found [B] bool, start [B] int32) where ``start`` indexes the first
    proposal token."""
    t = tail.shape[1]
    pat = tail[:, t - n:]                                # [B, n]
    idx = jnp.arange(t - n)[:, None] + jnp.arange(n)     # [T-n, n]
    wins = tail[:, idx]                                  # [B, T-n, n]
    ok = (wins == pat[:, None, :]).all(-1)
    ok &= (wins >= 0).all(-1)                            # no padding
    found = ok.any(-1)
    # index of the LAST matching window start
    last = (t - n - 1) - jnp.argmax(ok[:, ::-1], axis=-1)
    return found, (last + n).astype(jnp.int32)


def ngram_propose(
    tail: jax.Array, gamma: int
) -> tuple[jax.Array, jax.Array]:
    """Batched prompt-lookup draft over the device tail: match the
    trailing 3-gram (2-gram fallback) against the row's own earlier
    content and propose the tokens that followed the most recent
    previous occurrence — ``engine.propose_ngram``, on-mesh. Returns
    ``(n_prop [B] int32, prop [B, gamma] int32)``; rows with no
    repeating n-gram propose nothing (``n_prop == 0``)."""
    t = tail.shape[1]
    f3, s3 = _match_last(tail, 3)
    f2, s2 = _match_last(tail, 2)
    found = f3 | f2
    start = jnp.where(f3, s3, s2)
    gidx = start[:, None] + jnp.arange(gamma)
    prop = jnp.take_along_axis(
        tail, jnp.clip(gidx, 0, t - 1), axis=1
    )
    avail = jnp.clip(t - start, 0, gamma)
    n_prop = jnp.where(found, avail, 0).astype(jnp.int32)
    return n_prop, prop


def shift_tail(
    tail: jax.Array, emitted: jax.Array, emit_n: jax.Array
) -> jax.Array:
    """Shift ``emit_n[b]`` freshly emitted tokens into each row's tail
    (per-row dynamic roll): the new tail is the last T tokens of
    ``tail ++ emitted[:emit_n]``."""
    t = tail.shape[1]
    ext = jnp.concatenate([tail, emitted], axis=1)
    idx = jnp.arange(t)[None] + emit_n[:, None]
    return jnp.take_along_axis(ext, idx, axis=1)


def draft_propose(
    draft_params: Any,
    draft_cfg: Any,
    tail: jax.Array,
    gamma: int,
    window: int,
) -> jax.Array:
    """Tier-2 drafting: the tiny on-mesh draft decoder greedily
    proposes ``gamma`` tokens from the tail's trailing ``window``
    tokens, with a persistent per-lane draft KV cache.

    The trailing window is prefilled ONCE into a device-resident dense
    KV cache (models.qwen3.init_kv_cache, capacity window + gamma);
    each subsequent proposal token is a single-token incremental
    forward that appends to that cache — the draft forward cost per
    round drops from O(gamma * window) re-prefills to O(window +
    gamma) tokens. The cache lives only inside this (traced) proposal
    round: the verify step may reject any suffix, so nothing older
    than the round can stay coherent with the target's emission —
    exactly the trailing-window contract the stateless variant had,
    minus its redundant re-forwards. Padding clamps to token 0; an
    imperfect draft costs a rejection, never a wrong emission. Returns
    ``prop [B, gamma]``."""
    from ..models import qwen3
    from ..serving.sampler import greedy_argmax

    w = min(window, tail.shape[1])
    b = tail.shape[0]
    seq = jnp.maximum(tail[:, tail.shape[1] - w:], 0)

    # one prefill over the window seeds the draft KV tail on device
    cache = qwen3.init_kv_cache(draft_cfg, b, w + gamma)
    pos = jnp.broadcast_to(jnp.arange(w)[None], (b, w))
    logits, cache = qwen3.forward(
        draft_params, draft_cfg, seq, pos, cache
    )
    first = greedy_argmax(
        logits[:, -1].astype(jnp.float32)
    ).astype(jnp.int32)

    def step(carry, i):
        cache, tok = carry                            # tok [B]
        logits, cache = qwen3.forward(
            draft_params, draft_cfg, tok[:, None],
            jnp.full((b, 1), w, jnp.int32) + i, cache,
        )
        nxt = greedy_argmax(
            logits[:, -1].astype(jnp.float32)
        ).astype(jnp.int32)
        return (cache, nxt), nxt

    # proposal i feeds the cache at position w+i and yields proposal
    # i+1 — gamma-1 single-token advances after the seed proposal
    _, rest = jax.lax.scan(
        step, (cache, first), jnp.arange(gamma - 1, dtype=jnp.int32)
    )
    return jnp.concatenate(
        [first[:, None], rest.T], axis=1
    )                                                 # [B, gamma]
