from .core import (
    apply_rope,
    attention_ref,
    moe_ffn,
    moe_ffn_gshard,
    rms_norm,
    rope_angles,
    swiglu,
)

__all__ = [
    "apply_rope",
    "attention_ref",
    "moe_ffn",
    "moe_ffn_gshard",
    "rms_norm",
    "rope_angles",
    "swiglu",
]
