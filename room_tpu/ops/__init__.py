from .core import (
    apply_rope,
    attention_ref,
    moe_ffn,
    rms_norm,
    rope_angles,
    swiglu,
)

__all__ = [
    "apply_rope",
    "attention_ref",
    "moe_ffn",
    "rms_norm",
    "rope_angles",
    "swiglu",
]
