"""Core model ops, XLA-first.

These are the reference implementations every kernel must match: plain
jnp/lax compositions that XLA fuses well on TPU (bf16 matmuls on the MXU,
elementwise fused into them). Pallas kernels in room_tpu.ops.pallas_*
override the hot paths (paged attention decode) and are tested against
these.

Conventions: activations are [batch, seq, heads, head_dim] ("BSHD"),
weights live in dicts of jnp arrays, everything is jit-traceable with
static shapes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def rms_norm(x: jax.Array, scale: jax.Array, eps: float = 1e-6) -> jax.Array:
    """RMSNorm in fp32 accumulation, cast back to input dtype."""
    orig_dtype = x.dtype
    x32 = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    y = x32 * jax.lax.rsqrt(var + eps)
    return (y * scale.astype(jnp.float32)).astype(orig_dtype)


def rope_angles(
    positions: jax.Array, head_dim: int, theta: float
) -> tuple[jax.Array, jax.Array]:
    """cos/sin tables for rotary embeddings: [..., head_dim//2]."""
    freqs = 1.0 / (
        theta
        ** (np.arange(0, head_dim, 2, dtype=np.float32) / head_dim)
    )
    angles = positions[..., None].astype(jnp.float32) * freqs
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(
    x: jax.Array, cos: jax.Array, sin: jax.Array
) -> jax.Array:
    """Rotate [B, S, H, D] by per-position cos/sin [B, S, D//2].

    Uses the half-split convention (first/second half pairing) used by the
    Qwen/Llama families."""
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    c = cos[:, :, None, :].astype(jnp.float32)
    s = sin[:, :, None, :].astype(jnp.float32)
    x1f, x2f = x1.astype(jnp.float32), x2.astype(jnp.float32)
    out = jnp.concatenate(
        [x1f * c - x2f * s, x2f * c + x1f * s], axis=-1
    )
    return out.astype(x.dtype)


def swiglu(x: jax.Array, w_gate: jax.Array, w_up: jax.Array,
           w_down: jax.Array) -> jax.Array:
    """SwiGLU FFN: down( silu(x@gate) * (x@up) ). Weights may be
    int8 QTensors (quant.qeinsum applies the scale to each output)."""
    from .quant import qeinsum

    g = jax.nn.silu(qeinsum("...d,df->...f", x, w_gate))
    u = qeinsum("...d,df->...f", x, w_up)
    return qeinsum("...f,fd->...d", g * u, w_down)


def attention_ref(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    q_positions: jax.Array | None = None,
    kv_positions: jax.Array | None = None,
    kv_mask: jax.Array | None = None,
    scale: float | None = None,
) -> jax.Array:
    """XLA reference attention with grouped query heads.

    q: [B, Sq, Hq, D]; k, v: [B, Skv, Hkv, D] with Hq a multiple of Hkv.
    Masking uses positions so the same code serves prefill (Sq == Skv) and
    single-token decode against a longer cache (Sq == 1). kv_mask marks
    valid cache slots ([B, Skv] bool).
    """
    b, sq, hq, d = q.shape
    _, skv, hkv, _ = k.shape
    group = hq // hkv
    scale = scale if scale is not None else 1.0 / float(np.sqrt(d))

    qg = q.reshape(b, sq, hkv, group, d)
    logits = jnp.einsum(
        "bqhgd,bkhd->bhgqk", qg.astype(jnp.float32),
        k.astype(jnp.float32),
    ) * scale

    if q_positions is None:
        q_positions = jnp.broadcast_to(jnp.arange(sq)[None], (b, sq))
    if kv_positions is None:
        kv_positions = jnp.broadcast_to(jnp.arange(skv)[None], (b, skv))

    mask = jnp.ones((b, sq, skv), dtype=bool)
    if causal:
        mask &= kv_positions[:, None, :] <= q_positions[:, :, None]
    if kv_mask is not None:
        mask &= kv_mask[:, None, :]
    logits = jnp.where(
        mask[:, None, None, :, :], logits, jnp.float32(-1e30)
    )
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum(
        "bhgqk,bkhd->bqhgd", probs, v.astype(jnp.float32)
    )
    return out.reshape(b, sq, hq, d).astype(q.dtype)


def moe_ffn(
    x: jax.Array,
    router_w: jax.Array,
    w_gate: jax.Array,
    w_up: jax.Array,
    w_down: jax.Array,
    *,
    top_k: int,
    renormalize: bool = True,
    precision: jax.lax.Precision | None = None,
) -> jax.Array:
    """Mixture-of-experts SwiGLU via sort-based dispatch + grouped matmul.

    x: [T, D] tokens; router_w: [D, E]; expert weights [E, D, F] / [E, F, D].
    Tokens are sorted by assigned expert and pushed through
    ``lax.ragged_dot`` (TPU grouped matmul), then combined with router
    weights. All shapes static: T*top_k rows regardless of routing.
    """
    from .quant import qragged_dot

    t, d = x.shape
    e = router_w.shape[-1]
    weights, chosen = _route(x, router_w, top_k, renormalize)

    flat_expert = chosen.reshape(-1)              # [T*K]
    order = jnp.argsort(flat_expert)              # stable
    token_of_row = order // top_k                 # source token per row
    xs = x[token_of_row]                          # [T*K, D] sorted by expert
    group_sizes = jnp.bincount(flat_expert, length=e)
    eid_sorted = flat_expert[order]               # expert of each row

    g = qragged_dot(xs, w_gate, group_sizes, eid_sorted,
                    precision=precision)
    u = qragged_dot(xs, w_up, group_sizes, eid_sorted,
                    precision=precision)
    h = jax.nn.silu(g) * u
    y = qragged_dot(
        h.astype(x.dtype), w_down, group_sizes, eid_sorted,
        precision=precision,
    )

    # scatter-add rows back to their tokens, weighted by router prob
    w_sorted = weights.reshape(-1)[order].astype(y.dtype)
    out = jnp.zeros((t, y.shape[-1]), dtype=jnp.float32)
    out = out.at[token_of_row].add(
        y.astype(jnp.float32) * w_sorted[:, None].astype(jnp.float32)
    )
    return out.astype(x.dtype)


def _route(
    x: jax.Array, router_w: jax.Array, top_k: int, renormalize: bool
) -> tuple[jax.Array, jax.Array]:
    """Shared router: returns (gates [T, K] f32, chosen [T, K] int)."""
    t = x.shape[0]
    logits = jnp.einsum("td,de->te", x.astype(jnp.float32),
                        router_w.astype(jnp.float32))
    top_vals, chosen = jax.lax.top_k(logits, top_k)
    gates = jax.nn.softmax(top_vals, axis=-1) if renormalize else \
        jax.nn.softmax(logits, axis=-1)[
            jnp.arange(t)[:, None], chosen
        ]
    return gates, chosen


GSHARD_GROUP_SIZE = 128


def moe_ffn_gshard(
    x: jax.Array,
    router_w: jax.Array,
    w_gate: jax.Array,
    w_up: jax.Array,
    w_down: jax.Array,
    *,
    top_k: int,
    renormalize: bool = True,
    capacity_factor: float = 2.0,
) -> jax.Array:
    """GShard-style capacity-based MoE: dense dispatch/combine einsums.

    Unlike the sort+ragged_dot path (best on one chip), every tensor here
    carries an explicit expert axis, so sharding the expert weights over
    the ``ep`` mesh axis partitions the expert FFN compute directly and
    the combine einsum reduces to one activation psum — no expert-weight
    all-gather.

    Tokens are processed in fixed-size groups so dispatch memory/compute
    stay LINEAR in token count (capacity is per-group, independent of
    T). Within a group, capacity has a floor of min(group, 8) so
    decode-sized batches never drop on collisions; beyond capacity,
    tokens are dropped (zero FFN contribution), standard Switch/GShard
    semantics.
    """
    t, d = x.shape
    e = router_w.shape[-1]
    group = min(t, GSHARD_GROUP_SIZE)
    n_groups = -(-t // group)
    padded = n_groups * group
    capacity = max(
        int(group * top_k * capacity_factor / e), min(group, 8)
    )

    gates, chosen = _route(x, router_w, top_k, renormalize)
    valid = (jnp.arange(padded) < t)

    if padded != t:
        x = jnp.pad(x, ((0, padded - t), (0, 0)))
        gates = jnp.pad(gates, ((0, padded - t), (0, 0)))
        chosen = jnp.pad(chosen, ((0, padded - t), (0, 0)))
    # padded rows must not claim capacity slots
    gates = gates * valid[:, None]

    xg = x.reshape(n_groups, group, d)
    gates_g = gates.reshape(n_groups, group, top_k)
    assign = jax.nn.one_hot(
        chosen.reshape(n_groups, group, top_k), e, dtype=jnp.float32
    ) * (valid.reshape(n_groups, group, 1, 1))        # [G, g, K, E]

    # per-group slot position of each (token, k) within its expert
    flat = assign.reshape(n_groups, group * top_k, e)
    position = (jnp.cumsum(flat, axis=1) - flat).reshape(
        n_groups, group, top_k, e
    )
    in_capacity = (position < capacity) & (assign > 0)
    slot_onehot = jax.nn.one_hot(
        position.astype(jnp.int32), capacity, dtype=jnp.float32
    ) * in_capacity[..., None]                        # [G, g, K, E, C]

    dispatch = slot_onehot.sum(axis=2)                # [G, g, E, C] 0/1
    combine = jnp.einsum(
        "gtk,gtkec->gtec", gates_g.astype(jnp.float32), slot_onehot
    )

    from .quant import qexpert_einsum

    xe = jnp.einsum(
        "gtec,gtd->gecd", dispatch, xg.astype(jnp.float32)
    ).astype(x.dtype)
    g_ = qexpert_einsum("gecd,edf->gecf", xe, w_gate)
    u = qexpert_einsum("gecd,edf->gecf", xe, w_up)
    h = (jax.nn.silu(g_.astype(jnp.float32)) *
         u.astype(jnp.float32)).astype(x.dtype)
    y = qexpert_einsum("gecf,efd->gecd", h, w_down)
    out = jnp.einsum(
        "gtec,gecd->gtd", combine, y.astype(jnp.float32)
    ).reshape(padded, d)[:t]
    return out.astype(x.dtype)
