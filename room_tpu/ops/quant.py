"""Weight-only int8 quantization for serving.

Decode on TPU is HBM-bandwidth bound: every step streams the full weight
set through the MXU, so halving the bytes per weight is the single
biggest single-chip throughput lever (and halves the chips needed to
*hold* a model — qwen3-coder-30B drops from ~60 GB to ~30 GB, v5e has
16 GB/chip). The reference has no counterpart (its quantization lives
inside Ollama's GGUF files, local-model.ts:3-5); here it is a first-class
transform on the param pytree.

Scheme: symmetric per-output-channel absmax int8. For a weight W
contracted over axes A, the scale s = max|W| over A / 127 (keepdims), so
``x @ W  ≈  (x @ W_q) * s`` exactly commutes — the matmul runs on the
int8 tensor (XLA fuses the int8→bf16 convert into the dot's operand
read, so only int8 bytes leave HBM) and the f32 scale is applied to the
matmul *output*, preserving bf16 activation precision. Activations stay
bf16 throughout (weight-only, AWQ-style without calibration).

A quantized leaf is a ``QTensor`` NamedTuple (a pytree node), so
sharding, scanning over stacked layers, jit, and donation all work
unchanged; ``quantized_decoder_param_specs`` mirrors
``parallel.mesh.decoder_param_specs`` for mesh placement.
"""

from __future__ import annotations

from functools import partial
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

SUPPORTED_MODES = ("int8",)


def validate_quant_mode(mode: str) -> None:
    """Single source of truth for supported ROOM_TPU_QUANT values."""
    if mode not in SUPPORTED_MODES:
        raise ValueError(
            f"unknown ROOM_TPU_QUANT mode {mode!r} "
            f"(supported: {', '.join(SUPPORTED_MODES)})"
        )


class QTensor(NamedTuple):
    """int8 weight + f32 scale. ``s`` keeps the weight's rank with
    size-1 contracted axes, so stacked-layer leaves still lead with L
    and slice correctly under ``lax.scan``."""

    q: jax.Array  # int8, same shape as the original weight
    s: jax.Array  # float32, size-1 on contracted axes


def is_quantized(w: Any) -> bool:
    return isinstance(w, QTensor)


def quantize_tensor(w: jax.Array, contract_axes: tuple[int, ...]) -> QTensor:
    """Symmetric absmax int8 over the contraction axes (keepdims)."""
    a = jnp.max(
        jnp.abs(w.astype(jnp.float32)), axis=contract_axes, keepdims=True
    )
    s = jnp.maximum(a, 1e-12) / 127.0
    q = jnp.clip(
        jnp.round(w.astype(jnp.float32) / s), -127, 127
    ).astype(jnp.int8)
    return QTensor(q=q, s=s)


def dequantize(w: QTensor, dtype=jnp.bfloat16) -> jax.Array:
    return (w.q.astype(jnp.float32) * w.s).astype(dtype)


def qeinsum(sub: str, x: jax.Array, w: Any) -> jax.Array:
    """einsum over a 2D weight [in, out] (contraction on axis 0),
    transparent to quantization: the scale [1, out] lands on the output."""
    if isinstance(w, QTensor):
        y = jnp.einsum(sub, x, w.q.astype(x.dtype))
        return (y.astype(jnp.float32) * w.s.reshape(-1)).astype(x.dtype)
    return jnp.einsum(sub, x, w)


def qragged_dot(
    xs: jax.Array,
    w: Any,
    group_sizes: jax.Array,
    eid_sorted: jax.Array,
    *,
    precision=None,
) -> jax.Array:
    """ragged_dot over expert weights [E, in, out]; for a QTensor the
    per-(expert, out-channel) scale is gathered per row by its expert id
    (``eid_sorted``, aligned with ``xs``).

    On the CPU backend the grouped matmul is computed as a per-row
    gather + einsum instead of ``lax.ragged_dot``: XLA:CPU's GSPMD
    partitioner miscomputes ragged_dot when the expert axis is sharded
    on a multi-axis mesh (verified on jax 0.4.37 with [E,D,F] under
    P('ep', None, 'tp'): O(1) absolute error, the round-1 root cause of
    the CPU-mesh token-identity xfails). The gather formulation is
    mathematically identical row-for-row, and its all-gather of the
    expert weights partitions correctly. CPU is the hermetic-test
    backend, so the extra [rows, in, out] gather memory never ships to
    TPU hardware, where ragged_dot stays the fast path."""
    wq = w.q if isinstance(w, QTensor) else w
    if jax.default_backend() == "cpu":
        y = jnp.einsum(
            "ti,tio->to", xs, wq[eid_sorted].astype(xs.dtype),
            precision=precision,
        )
    else:
        y = jax.lax.ragged_dot(
            xs, wq.astype(xs.dtype), group_sizes, precision=precision
        )
    if isinstance(w, QTensor):
        scale = jnp.squeeze(w.s, axis=1)[eid_sorted]  # [rows, out]
        return (y.astype(jnp.float32) * scale).astype(xs.dtype)
    return y


def qexpert_einsum(sub: str, x: jax.Array, w: Any) -> jax.Array:
    """Expert einsum keeping the E axis in the output (gshard dense
    dispatch, e.g. "gecd,edf->gecf"): scale [E, 1, out] broadcasts as
    [1, E, 1, out] over the output."""
    if isinstance(w, QTensor):
        y = jnp.einsum(sub, x, w.q.astype(x.dtype))
        return (y.astype(jnp.float32) * w.s[None]).astype(x.dtype)
    return jnp.einsum(sub, x, w)


# ---- param-tree transforms ----

# leaf name -> contraction axes, for stacked-layer decoder params
_MOE_AXES = {
    "wq": (1,), "wk": (1,), "wv": (1,), "wo": (1,),
    "w_gate": (2,), "w_up": (2,), "w_down": (2,),
}
_DENSE_AXES = {
    "wq": (1,), "wk": (1,), "wv": (1,), "wo": (1,),
    "w_gate": (1,), "w_up": (1,), "w_down": (1,),
}


@partial(jax.jit, donate_argnums=(0,), static_argnums=(1,))
def _quantize_leaf(w: jax.Array, contract_axes: tuple[int, ...]) -> QTensor:
    return quantize_tensor(w, contract_axes)


def quantize_decoder_params(params: dict, cfg) -> dict:
    """int8-quantize the matmul weights of a qwen3.init_params tree.

    Router and norms stay f32/bf16 (tiny, accuracy-critical). The
    embedding quantizes per-row (exact under gather + scale); lm_head
    per-vocab-column (it is streamed in full every decode step).

    Leaves are popped and quantized one at a time through a DONATED jit
    so the source buffer is released as each leaf converts — peak HBM
    stays near the bf16 tree rather than bf16 + f32 temporaries for the
    whole model (the 30B's stacked expert leaf alone is most of the
    weights). The input tree is consumed."""
    axes = _MOE_AXES if cfg.is_moe else _DENSE_AXES
    layers = params["layers"]
    for name, ax in axes.items():
        w = layers.pop(name)
        layers[name] = _quantize_leaf(w, ax)
        del w
    out = dict(params, layers=layers)
    w = params.pop("embed")
    out["embed"] = _quantize_leaf(w, (1,))
    del w
    if "lm_head" in params:
        w = params.pop("lm_head")
        out["lm_head"] = _quantize_leaf(w, (0,))
        del w
    return out


def quantized_decoder_param_specs(cfg) -> dict:
    """Sharding specs mirroring quantize_decoder_params: q shards like
    the original weight; s drops the sharding on contracted (now size-1)
    axes."""
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import decoder_param_specs

    specs = decoder_param_specs(cfg)
    axes = _MOE_AXES if cfg.is_moe else _DENSE_AXES

    def scale_spec(p: P, contract: tuple[int, ...]) -> P:
        return P(*[
            None if i in contract else ax for i, ax in enumerate(p)
        ])

    layers = dict(specs["layers"])
    for name, ax in axes.items():
        layers[name] = QTensor(q=layers[name],
                               s=scale_spec(layers[name], ax))
    out = dict(specs, layers=layers)
    out["embed"] = QTensor(q=specs["embed"],
                           s=scale_spec(specs["embed"], (1,)))
    if "lm_head" in specs:
        out["lm_head"] = QTensor(q=specs["lm_head"],
                                 s=scale_spec(specs["lm_head"], (0,)))
    return out
