"""Expert-parallel MoE via explicit shard_map all-to-all + grouped
matmuls (SURVEY §2.7 rung 3; the perf path for MoE decode).

The GSPMD paths (`moe_ffn` sort+ragged_dot on one chip, `moe_ffn_gshard`
dense dispatch einsums under annotations) leave the communication
schedule to the compiler. This path writes it by hand, the way TPU MoE
serving stacks do:

  1. tokens are sharded over the ``ep`` axis; each device routes its
     local tokens with the replicated router
  2. one `lax.all_to_all` ships each (token, k) row to the device that
     owns its expert, into fixed-capacity per-peer buffers
  3. the owning device runs ONE grouped matmul (`lax.ragged_dot`) over
     its local expert shard — rows pre-sorted by local expert id
  4. a second all_to_all ships results back; the source device combines
     with router gates

Static shapes throughout: per-peer capacity C bounds the exchange
buffers ([ep, C, D] both ways); rows beyond capacity drop (GShard
semantics), with C sized so decode-shaped batches never drop at the
default factor.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from .core import _route

# mesh registry: the model layer (qwen3._layer) has no mesh argument —
# the host that builds the mesh installs it here before tracing with
# moe_impl="shardmap". Keyed by model/config name so hetero hosts on
# disjoint submeshes don't clobber each other (lazy per-bucket retraces
# would otherwise pick up whichever host registered last); key None is
# the single-model default.
_EP_MESHES: dict[str | None, Mesh] = {}


def set_ep_mesh(mesh: Mesh | None, key: str | None = None) -> None:
    if mesh is None:
        _EP_MESHES.pop(key, None)
    else:
        _EP_MESHES[key] = mesh


def get_ep_mesh(key: str | None = None) -> Mesh:
    mesh = _EP_MESHES.get(key) or _EP_MESHES.get(None)
    if mesh is None:
        raise RuntimeError(
            "moe_impl='shardmap' needs set_ep_mesh(mesh) before tracing"
        )
    return mesh


def moe_ffn_shardmap_padded(
    x: jax.Array, router_w, w_gate, w_up, w_down, *,
    top_k: int, renormalize: bool = True, mesh_key: str | None = None,
) -> jax.Array:
    """Model-layer entry: pads the token axis to a multiple of ep (the
    pad rows route but their outputs are sliced away), mesh from the
    registry (keyed per model for hetero hosts)."""
    mesh = get_ep_mesh(mesh_key)
    ep = mesh.shape["ep"]
    t = x.shape[0]
    padded = -(-t // ep) * ep
    if padded != t:
        x = jnp.pad(x, ((0, padded - t), (0, 0)))
    out = moe_ffn_shardmap(
        x, router_w, w_gate, w_up, w_down,
        top_k=top_k, renormalize=renormalize, mesh=mesh,
    )
    return out[:t]


def moe_ffn_shardmap(
    x: jax.Array,            # [T, D], T divisible by ep (caller pads)
    router_w: jax.Array,     # [D, E] replicated
    w_gate: jax.Array,       # [E, D, F] sharded over ep on axis 0
    w_up: jax.Array,
    w_down: jax.Array,       # [E, F, D]
    *,
    top_k: int,
    renormalize: bool = True,
    mesh: Mesh,
    axis: str = "ep",
    capacity_factor: float = 2.0,
) -> jax.Array:
    t, d = x.shape
    e = router_w.shape[-1]
    ep = mesh.shape[axis]
    if t % ep != 0:
        raise ValueError(f"token count {t} not divisible by ep={ep}")
    if e % ep != 0:
        raise ValueError(f"experts {e} not divisible by ep={ep}")
    e_local = e // ep
    t_local = t // ep
    # per-peer exchange capacity: even routing sends t_local*K/ep rows
    # to each peer; factor covers skew, floor covers tiny decode batches
    cap = max(
        int(t_local * top_k / ep * capacity_factor), min(t_local, 8),
        top_k,
    )

    def local(x_l, router_l, wg_l, wu_l, wd_l):
        # x_l [Tl, D]; wg_l [e_local, D, F]
        tl = x_l.shape[0]
        gates, chosen = _route(x_l, router_l, top_k, renormalize)
        flat_choice = chosen.reshape(-1)              # [Tl*K]
        dest = flat_choice // e_local                 # peer owning expert
        local_eid = flat_choice % e_local

        # slot of each row inside its destination buffer
        dest_onehot = jax.nn.one_hot(dest, ep, dtype=jnp.int32)
        pos = jnp.cumsum(dest_onehot, axis=0) - dest_onehot
        slot = jnp.sum(pos * dest_onehot, axis=1)     # [Tl*K]
        keep = slot < cap

        token_of = jnp.arange(tl * top_k) // top_k
        rows = x_l[token_of]                          # [Tl*K, D]
        safe_dest = jnp.where(keep, dest, 0)
        safe_slot = jnp.where(keep, slot, cap - 1)

        send_x = jnp.zeros((ep, cap, d), x_l.dtype).at[
            safe_dest, safe_slot
        ].set(jnp.where(keep[:, None], rows, 0))
        # empty slots carry expert id 0 with zeroed rows: their FFN
        # output is combined with gate 0, so they are harmless
        send_eid = jnp.zeros((ep, cap), jnp.int32).at[
            safe_dest, safe_slot
        ].set(jnp.where(keep, local_eid, 0))

        recv_x = jax.lax.all_to_all(send_x, axis, 0, 0)   # [ep, cap, D]
        recv_eid = jax.lax.all_to_all(send_eid, axis, 0, 0)

        # grouped matmul over the local expert shard
        from .quant import qragged_dot

        flat_x = recv_x.reshape(ep * cap, d)
        flat_eid = recv_eid.reshape(ep * cap)
        order = jnp.argsort(flat_eid)
        xs = flat_x[order]
        group_sizes = jnp.bincount(flat_eid, length=e_local)
        eid_sorted = flat_eid[order]
        g = qragged_dot(xs, wg_l, group_sizes, eid_sorted)
        u = qragged_dot(xs, wu_l, group_sizes, eid_sorted)
        h = (jax.nn.silu(g.astype(jnp.float32)) *
             u.astype(jnp.float32)).astype(x_l.dtype)
        y_sorted = qragged_dot(h, wd_l, group_sizes, eid_sorted)
        y = jnp.zeros_like(y_sorted).at[order].set(y_sorted)
        y = y.reshape(ep, cap, d)

        y_back = jax.lax.all_to_all(y, axis, 0, 0)    # source layout

        gathered = y_back[safe_dest, safe_slot]       # [Tl*K, D]
        w = (gates.reshape(-1) * keep).astype(jnp.float32)
        out = jnp.zeros((tl, d), jnp.float32).at[token_of].add(
            gathered.astype(jnp.float32) * w[:, None]
        )
        return out.astype(x_l.dtype)

    from .quant import QTensor

    def wspec(w):
        # expert weights shard on E (axis 0); a QTensor's scale keeps
        # the same rank (size-1 contracted axis) so it shards the same
        base = P(axis, None, None)
        if isinstance(w, QTensor):
            return QTensor(q=base, s=base)
        return base

    fn = jax.shard_map(
        local,
        mesh=mesh,
        in_specs=(
            P(axis, None),          # tokens sharded over ep
            P(None, None),          # router replicated
            wspec(w_gate),
            wspec(w_up),
            wspec(w_down),
        ),
        out_specs=P(axis, None),
    )
    return fn(x, router_w, w_gate, w_up, w_down)
