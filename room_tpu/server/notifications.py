"""Keeper notification digests (reference:
src/server/clerk-notifications.ts): pending escalations and unanswered
queen messages are batched into digest messages on a 6 h cadence (1 h
when anything urgent), with per-source cursors so nothing is re-sent.
Delivery lands in clerk_messages (and, when configured, the keeper's
email/telegram relays — gated on settings)."""

from __future__ import annotations

import threading
import time
from typing import Optional

from ..core.events import event_bus
from ..core.messages import get_setting, set_setting
from ..db import Database, utc_now

DIGEST_INTERVAL_S = 6 * 3600.0
URGENT_INTERVAL_S = 3600.0
URGENT_KEYWORDS = ("urgent", "blocked", "failed", "error", "money",
                   "payment")


def _cursor(db: Database, source: str) -> int:
    try:
        return int(get_setting(db, f"notify_cursor_{source}") or 0)
    except ValueError:
        return 0


def _set_cursor(db: Database, source: str, value: int) -> None:
    set_setting(db, f"notify_cursor_{source}", str(value))


def collect_pending(db: Database) -> dict:
    """Unsent escalations + queen->keeper chat since the cursors."""
    esc = db.query(
        "SELECT * FROM escalations WHERE status='pending' AND id > ? "
        "ORDER BY id",
        (_cursor(db, "escalations"),),
    )
    chats = db.query(
        "SELECT c.*, r.name AS room_name FROM chat_messages c "
        "JOIN rooms r ON r.id = c.room_id "
        "WHERE c.role='assistant' AND c.id > ? ORDER BY c.id",
        (_cursor(db, "chat"),),
    )
    urgent = any(
        any(k in (e["question"] or "").lower() for k in URGENT_KEYWORDS)
        for e in esc
    )
    return {"escalations": esc, "chats": chats, "urgent": urgent}


def build_digest(pending: dict) -> Optional[str]:
    parts: list[str] = []
    if pending["escalations"]:
        parts.append(
            f"{len(pending['escalations'])} escalation(s) need you:\n"
            + "\n".join(
                f"  - [{e['id']}] {e['question'][:150]}"
                for e in pending["escalations"][:5]
            )
        )
    if pending["chats"]:
        parts.append(
            f"{len(pending['chats'])} message(s) from your queens:\n"
            + "\n".join(
                f"  - {c['room_name']}: {c['content'][:120]}"
                for c in pending["chats"][:5]
            )
        )
    if not parts:
        return None
    return "Keeper digest:\n" + "\n".join(parts)


def relay_pending(db: Database) -> Optional[str]:
    """One digest pass; advances cursors only for what was included."""
    pending = collect_pending(db)
    digest = build_digest(pending)
    if digest is None:
        return None
    db.insert(
        "INSERT INTO clerk_messages(role, content, source) "
        "VALUES ('assistant', ?, 'digest')",
        (digest,),
    )
    event_bus.emit("keeper:digest", "clerk", {"text": digest})
    _deliver_external(db, digest)
    if pending["escalations"]:
        _set_cursor(db, "escalations", pending["escalations"][-1]["id"])
    if pending["chats"]:
        _set_cursor(db, "chat", pending["chats"][-1]["id"])
    return digest


def _deliver_external(db: Database, digest: str) -> None:
    """Email/Telegram relays, gated on configured settings; failures are
    silent like the reference's cloud relays."""
    from .contacts import (
        ApiError, K_EMAIL, K_EMAIL_VERIFIED_AT, send_email,
    )

    email = (get_setting(db, K_EMAIL) or "").strip()
    if email and (get_setting(db, K_EMAIL_VERIFIED_AT) or "").strip():
        try:
            send_email(db, email, "Keeper digest", digest)
        except ApiError:
            pass

    telegram_token = get_setting(db, "telegram_bot_token")
    telegram_chat = get_setting(db, "telegram_chat_id")
    if telegram_token and telegram_chat:
        try:
            import json
            import urllib.request

            req = urllib.request.Request(
                f"https://api.telegram.org/bot{telegram_token}"
                "/sendMessage",
                data=json.dumps({
                    "chat_id": telegram_chat, "text": digest,
                }).encode(),
                headers={"Content-Type": "application/json"},
            )
            urllib.request.urlopen(req, timeout=10)
        except OSError:
            pass


class NotificationEngine:
    def __init__(self, db: Database) -> None:
        self.db = db
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        def loop():
            while True:
                try:
                    pending = collect_pending(self.db)
                    urgent = pending["urgent"]
                except Exception:
                    urgent = False  # transient DB error must not kill us
                wait = (
                    URGENT_INTERVAL_S if urgent else DIGEST_INTERVAL_S
                )
                if self._stop.wait(timeout=wait):
                    return
                try:
                    relay_pending(self.db)
                except Exception:
                    pass

        self._thread = threading.Thread(
            target=loop, daemon=True, name="keeper-notifications"
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
