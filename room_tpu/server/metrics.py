"""Prometheus text exposition (``GET /metrics``, docs/observability.md).

One scrape unifies every surface the stack already tracks:

- ``core/telemetry`` process counters (fault firings, offload churn,
  engine crashes) as ``room_tpu_events_total{event=...}``;
- ``core/telemetry`` fixed latency histograms (cumulative ``le``
  semantics with ``_count``/``_sum`` — observe_ms's exposition
  contract) as ``room_tpu_latency_ms``;
- per-engine stats (``engines_snapshot()``: decode/prefill counters,
  pipeline depth, offload tiers, degradation rung) as
  ``room_tpu_engine_*`` gauges keyed by ``model`` (fleet replicas keep
  their ``model#rid`` keys);
- per-class SLO gauges from each engine's scheduler block (queue
  depth, TTFT/TPOT EMA vs target, shed counts);
- turnscope per-class SLO attribution (serving/trace.py: where each
  class's latency budget went) as
  ``room_tpu_slo_attribution_ms_total{class,component}``;
- ``HttpProfiler`` endpoint latency snapshots;
- armed chaos fault points.

The format is strict text-format 0.0.4 (``# HELP``/``# TYPE`` once per
family, families contiguous, labels escaped) — CI parses a scrape with
a strict parser (tests/test_trace.py) so a malformed family can't
ship. The endpoint is served pre-auth for scrapers (standard
Prometheus deployment on a private network); ROOM_TPU_METRICS=0
disables it.
"""

from __future__ import annotations

from typing import Iterable, Optional

from ..utils import knobs

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def metrics_enabled() -> bool:
    return knobs.get_bool("ROOM_TPU_METRICS")


def _escape(value: str) -> str:
    return (
        str(value)
        .replace("\\", r"\\")
        .replace("\n", r"\n")
        .replace('"', r'\"')
    )


def _fmt(value) -> str:
    if value is None:
        return "NaN"
    if isinstance(value, bool):
        return "1" if value else "0"
    if isinstance(value, float):
        if value != value:   # NaN
            return "NaN"
        return repr(round(value, 6))
    return str(value)


class _Family:
    """One metric family: TYPE/HELP emitted once, samples contiguous
    (the text-format invariant strict parsers enforce)."""

    def __init__(self, name: str, kind: str, help_: str) -> None:
        self.name = name
        self.kind = kind
        self.help = help_
        self.samples: list[tuple[str, dict, object]] = []

    def add(self, labels: Optional[dict], value, suffix: str = "") -> None:
        self.samples.append((suffix, labels or {}, value))

    def render(self) -> str:
        lines = [
            f"# HELP {self.name} {_escape(self.help)}",
            f"# TYPE {self.name} {self.kind}",
        ]
        for suffix, labels, value in self.samples:
            if labels:
                lab = ",".join(
                    f'{k}="{_escape(v)}"' for k, v in labels.items()
                )
                lines.append(
                    f"{self.name}{suffix}{{{lab}}} {_fmt(value)}"
                )
            else:
                lines.append(f"{self.name}{suffix} {_fmt(value)}")
        return "\n".join(lines)


# engines_snapshot() top-level numeric stats exported per model; the
# allowlist keeps the exposition stable (new nested blocks don't
# silently become malformed gauges)
_ENGINE_GAUGES = (
    "tokens_decoded", "turns_completed", "prefill_tokens",
    "decode_steps", "decode_windows", "window_faults",
    "overshoot_tokens", "host_stall_ms", "steps_per_dispatch",
    "evictions", "prefix_hits", "prefix_tokens_reused",
    "engine_crashes", "stall_events", "requeues", "shed_turns",
    "deadline_timeouts", "fault_retries", "degradation_level",
    "offloads", "offload_restores", "offload_prefetches",
    "offload_resident_fallbacks", "offload_reprefills",
    "prefill_chunks_interleaved", "prefill_chunk_defers",
    "prefill_chunk_faults", "chunk_dispatches", "fused_windows",
    "fused_chunks", "fused_dp_windows",
    "spec_rounds", "spec_proposed", "spec_accepted",
    "spec_throttles", "spec_rows_sequential",
    "queued", "sessions", "free_pages", "max_batch", "active_slots",
    # shared prefix store + disagg ships (docs/disagg.md)
    "prefix_store_hits", "prefix_store_tokens_reused",
    "prefix_store_pull_fallbacks", "prefix_store_publishes",
    "sessions_shipped",
)


def render_metrics() -> str:
    """One Prometheus scrape of the whole process. Engine snapshots
    are best-effort: a cold / import-failed provider layer yields the
    process-level families only."""
    from ..core.telemetry import counters_snapshot, histograms_snapshot

    families: list[_Family] = []

    # ---- process counters + histograms ----
    events = _Family(
        "room_tpu_events_total", "counter",
        "Process event counters (core/telemetry.py): fault firings, "
        "offload churn, crash/recovery events.",
    )
    for name, n in sorted(counters_snapshot().items()):
        events.add({"event": name}, n)
    families.append(events)

    hist = _Family(
        "room_tpu_latency_ms", "histogram",
        "Fixed latency histograms (telemetry.observe_ms): cumulative "
        "le buckets.",
    )
    for name, h in sorted(histograms_snapshot().items()):
        for edge, cum in zip(h["buckets"], h["cumulative"]):
            hist.add({"name": name, "le": f"{edge:g}"}, cum,
                     suffix="_bucket")
        hist.add({"name": name, "le": "+Inf"}, h["count"],
                 suffix="_bucket")
        hist.add({"name": name}, h["sum"], suffix="_sum")
        hist.add({"name": name}, h["count"], suffix="_count")
    families.append(hist)

    # ---- engine / fleet / scheduler / offload ----
    try:
        from ..providers.tpu import engines_snapshot

        engines = engines_snapshot()
    except Exception:
        engines = {}
    eng_fam = _Family(
        "room_tpu_engine", "gauge",
        "Per-engine serving stats (engines_snapshot), keyed by model "
        "(fleet replicas as model#rid) and stat.",
    )
    healthy_fam = _Family(
        "room_tpu_engine_healthy", "gauge",
        "1 = engine healthy, 0 = crash-restart budget exhausted.",
    )
    cls_fams = {
        "queued": _Family(
            "room_tpu_class_queue_depth", "gauge",
            "Queued turns per SLO class (docs/scheduler.md).",
        ),
        "ttft_ema_s": _Family(
            "room_tpu_class_ttft_ema_seconds", "gauge",
            "Observed TTFT EMA per class.",
        ),
        "ttft_target_s": _Family(
            "room_tpu_class_ttft_target_seconds", "gauge",
            "TTFT target per class (ROOM_TPU_CLASS_TARGETS).",
        ),
        "tpot_ema_s": _Family(
            "room_tpu_class_tpot_ema_seconds", "gauge",
            "Observed per-token interval EMA per class.",
        ),
        "tpot_target_s": _Family(
            "room_tpu_class_tpot_target_seconds", "gauge",
            "TPOT target per class.",
        ),
        "shed": _Family(
            "room_tpu_class_shed_total", "counter",
            "Turns shed per class by the degradation ladder.",
        ),
        "completed": _Family(
            "room_tpu_class_completed_total", "counter",
            "Turns completed per class.",
        ),
        "rung": _Family(
            "room_tpu_class_rung", "gauge",
            "Degradation-ladder rung each class experiences.",
        ),
    }
    spec_fam = _Family(
        "room_tpu_spec_class", "gauge",
        "Per-traffic-class speculative decoding state "
        "(scheduler.SpecTuner, docs/serving.md): live gamma, adapted "
        "gamma, acceptance EMA, lifetime acceptance, proposal/accept "
        "counters, spec-off flag, throttle/probe events.",
    )
    pfx_fam = _Family(
        "room_tpu_prefix_store", "gauge",
        "Fleet-global shared prefix store counters per engine "
        "(docs/disagg.md).",
    )
    ship_fam = _Family(
        "room_tpu_disagg_ships_total", "counter",
        "Prefill->decode KV shipments per fleet, by outcome "
        "(docs/disagg.md).",
    )
    shard_fam = _Family(
        "room_tpu_router_shard", "gauge",
        "Sharded router tier per-shard state (docs/podnet.md): rooms "
        "owned, journal bytes, adoptions, serving flag, keyed by "
        "model and shard; plus fleet-wide placement epoch/crash "
        "counters under shard=\"all\".",
    )
    offload_fams = {
        "host_entries": _Family(
            "room_tpu_offload_host_entries", "gauge",
            "Hibernated sessions resident in the host-RAM tier.",
        ),
        "host_bytes": _Family(
            "room_tpu_offload_host_bytes", "gauge",
            "Host-RAM tier bytes.",
        ),
        "disk_entries": _Family(
            "room_tpu_offload_disk_entries", "gauge",
            "Hibernated sessions in the disk spool tier.",
        ),
        "disk_bytes": _Family(
            "room_tpu_offload_disk_bytes", "gauge",
            "Disk spool tier bytes.",
        ),
    }
    for model, e in sorted(engines.items()):
        for stat in _ENGINE_GAUGES:
            v = e.get(stat)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                eng_fam.add({"model": model, "stat": stat}, v)
        if "healthy" in e:
            healthy_fam.add({"model": model}, bool(e["healthy"]))
        sched = e.get("scheduler") or {}
        for cls, row in sorted((sched.get("classes") or {}).items()):
            for key, fam in cls_fams.items():
                if row.get(key) is not None:
                    fam.add({"model": model, "class": cls}, row[key])
        spec = e.get("spec") or {}
        for cls, row in sorted((spec.get("classes") or {}).items()):
            for key in ("gamma", "gamma_adapted", "accept_ema",
                        "acceptance", "proposed", "accepted",
                        "emitted", "off", "throttles", "probes"):
                v = row.get(key)
                if v is None:
                    continue
                spec_fam.add(
                    {"model": model, "class": cls, "stat": key},
                    float(v) if isinstance(v, bool) else v,
                )
        off = e.get("offload") or {}
        for key, fam in offload_fams.items():
            if off.get(key) is not None:
                fam.add({"model": model}, off[key])
        pfx = e.get("prefix_store") or {}
        for key in ("publishes", "hits", "misses", "evictions",
                    "pull_errors", "publish_errors",
                    "bytes_published", "bytes_pulled", "entries"):
            v = pfx.get(key)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                pfx_fam.add({"model": model, "stat": key}, v)
        dis = (e.get("fleet") or {}).get("disagg") or {}
        for key in ("ships", "ships_warm", "ships_reprefill",
                    "ships_deferred", "ships_refused", "ship_wire",
                    "wire_errors"):
            v = dis.get(key)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                ship_fam.add({"model": model, "outcome": key}, v)
        shards = (e.get("fleet") or {}).get("router_shards") or {}
        for key in ("count", "serving", "epoch", "crashes",
                    "adoptions", "sessions_adopted",
                    "placement_refusals"):
            v = shards.get(key)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                shard_fam.add(
                    {"model": model, "shard": "all", "stat": key}, v,
                )
        for sk, blk in (shards.get("shards") or {}).items():
            if not isinstance(blk, dict):
                continue
            shard_fam.add(
                {"model": model, "shard": str(sk),
                 "stat": "serving"},
                1 if blk.get("state") == "serving" else 0,
            )
            for key in ("rooms", "journal_bytes", "adoptions"):
                v = blk.get(key)
                if isinstance(v, (int, float)) and \
                        not isinstance(v, bool):
                    shard_fam.add(
                        {"model": model, "shard": str(sk),
                         "stat": key}, v,
                    )
    families.append(eng_fam)
    families.append(healthy_fam)
    families.extend(cls_fams.values())
    families.append(spec_fam)
    families.extend(offload_fams.values())
    families.append(pfx_fam)
    families.append(ship_fam)
    families.append(shard_fam)

    # ---- swarm shard tier (docs/swarmshard.md) ----
    try:
        from ..swarm import maybe_default_router

        swarm_router = maybe_default_router()
    except Exception:
        swarm_router = None
    swarm_fam = _Family(
        "room_tpu_swarm_shard", "gauge",
        "Room-partitioned swarm-runtime shards (docs/swarmshard.md): "
        "per-shard state/rooms/events/cross-shard traffic keyed by "
        "shard; fleet-wide placement epoch, crash/adoption/dedup "
        "counters under shard=\"all\".",
    )
    if swarm_router is not None:
        snap = swarm_router.snapshot()
        for key in ("n_shards", "cross_shard_messages",
                    "cross_shard_escalations", "dedup_skips",
                    "shard_crashes", "adoptions", "sheds", "resizes"):
            v = snap.get(key)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                swarm_fam.add({"shard": "all", "stat": key}, v)
        swarm_fam.add(
            {"shard": "all", "stat": "epoch"},
            (snap.get("placement") or {}).get("epoch", 0),
        )
        swarm_fam.add(
            {"shard": "all", "stat": "serving"},
            sum(1 for s in snap["shards"]
                if s.get("state") == "serving"),
        )
        for s in snap["shards"]:
            sk = str(s.get("shard"))
            swarm_fam.add(
                {"shard": sk, "stat": "serving"},
                1 if s.get("state") == "serving" else 0,
            )
            for key in ("events", "messages_in", "messages_out",
                        "escalations", "adoptions", "dedup_skips",
                        "rooms_created"):
                v = s.get(key)
                if isinstance(v, (int, float)) and \
                        not isinstance(v, bool):
                    swarm_fam.add({"shard": sk, "stat": key}, v)
    families.append(swarm_fam)

    # ---- multi-process swarm shards (docs/swarmshard.md) ----
    try:
        from ..swarm import maybe_default_proc

        swarm_proc = maybe_default_proc()
    except Exception:
        swarm_proc = None
    proc_fam = _Family(
        "room_tpu_swarm_proc", "gauge",
        "Supervised swarm shard child processes (docs/swarmshard.md "
        "\"Process mode\"): per-child state/restarts/traffic keyed by "
        "shard; dispatch/restart/adoption/orphan counters under "
        "shard=\"all\".",
    )
    proc_slo_fam = _Family(
        "room_tpu_proc_slo_attribution_ms_total", "counter",
        "Process-spanning per-class latency attribution: the parent's "
        "recorder merged with the latest stats frame from every shard "
        "child (turnscope over N processes).",
    )
    proc_turns_fam = _Family(
        "room_tpu_proc_turns_total", "counter",
        "Finished turns per class summed across shard child "
        "processes, by outcome.",
    )
    if swarm_proc is not None:
        snap = swarm_proc.snapshot()
        for key in ("n_shards", "dispatches", "dedup_skips",
                    "restarts", "adoptions", "proc_kills",
                    "wire_retries", "sheds", "orphans_reaped",
                    "forced_kills"):
            v = snap.get(key)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                proc_fam.add({"shard": "all", "stat": key}, v)
        proc_fam.add(
            {"shard": "all", "stat": "epoch"},
            (snap.get("placement") or {}).get("epoch", 0),
        )
        proc_fam.add(
            {"shard": "all", "stat": "serving"},
            sum(1 for c in snap["children"]
                if c.get("state") == "serving"),
        )
        for c in snap["children"]:
            sk = str(c.get("shard"))
            proc_fam.add(
                {"shard": sk, "stat": "serving"},
                1 if c.get("state") == "serving" else 0,
            )
            for key in ("restarts_in_window", "frames",
                        "messages_in", "messages_out", "escalations",
                        "dedup_skips", "rooms_created",
                        "journal_bytes"):
                v = c.get(key)
                if isinstance(v, (int, float)) and \
                        not isinstance(v, bool):
                    proc_fam.add({"shard": sk, "stat": key}, v)
        try:
            from ..serving import trace as _trace_mod

            _components = _trace_mod.ATTRIBUTION_COMPONENTS
        except Exception:
            _components = ()
        merged = snap.get("slo") or {}
        for cls, a in sorted((merged.get("classes") or {}).items()):
            for comp in _components:
                proc_slo_fam.add(
                    {"class": cls, "component": comp[:-3]},
                    a.get(comp, 0),
                )
            proc_turns_fam.add({"class": cls, "outcome": "all"},
                               a.get("turns", 0))
            proc_turns_fam.add({"class": cls, "outcome": "error"},
                               a.get("errors", 0))
            proc_turns_fam.add({"class": cls, "outcome": "shed"},
                               a.get("shed", 0))
            proc_turns_fam.add({"class": cls, "outcome": "faulted"},
                               a.get("faulted", 0))
    families.append(proc_fam)
    families.append(proc_slo_fam)
    families.append(proc_turns_fam)

    # ---- turnscope SLO attribution (serving/trace.py) ----
    try:
        from ..serving import trace as trace_mod

        attribution = trace_mod.recorder.attribution()
        components = trace_mod.ATTRIBUTION_COMPONENTS
    except Exception:
        attribution, components = {"classes": {}}, ()
    attr_fam = _Family(
        "room_tpu_slo_attribution_ms_total", "counter",
        "Where each class's latency budget went, summed over finished "
        "turns (turnscope, docs/observability.md).",
    )
    viol_fam = _Family(
        "room_tpu_slo_violations_total", "counter",
        "Turns finishing over their class TTFT/TPOT target.",
    )
    turns_fam = _Family(
        "room_tpu_turns_total", "counter",
        "Finished turns per class (turnscope), by outcome.",
    )
    for cls, a in sorted(attribution.get("classes", {}).items()):
        for comp in components:
            attr_fam.add(
                {"class": cls, "component": comp[:-3]}, a[comp]
            )
        viol_fam.add({"class": cls, "kind": "ttft"},
                     a["ttft_violations"])
        viol_fam.add({"class": cls, "kind": "tpot"},
                     a["tpot_violations"])
        turns_fam.add({"class": cls, "outcome": "all"}, a["turns"])
        turns_fam.add({"class": cls, "outcome": "error"}, a["errors"])
        turns_fam.add({"class": cls, "outcome": "shed"}, a["shed"])
        turns_fam.add({"class": cls, "outcome": "faulted"},
                      a["faulted"])
    families.append(attr_fam)
    families.append(viol_fam)
    families.append(turns_fam)

    # ---- HTTP endpoint latency (utils/profiling.HttpProfiler) ----
    from ..utils.profiling import http_profiler

    http_n = _Family(
        "room_tpu_http_requests_total", "counter",
        "HTTP requests per normalized endpoint (ROOM_TPU_PROFILE_HTTP "
        "sampling window of 500 per endpoint).",
    )
    http_ms = _Family(
        "room_tpu_http_latency_ms", "gauge",
        "HTTP endpoint latency over the profiler's sampling window.",
    )
    for key, row in sorted(http_profiler.snapshot().items()):
        http_n.add({"endpoint": key}, row["count"])
        http_ms.add({"endpoint": key, "stat": "mean"}, row["mean_ms"])
        http_ms.add({"endpoint": key, "stat": "p95"}, row["p95_ms"])
    families.append(http_n)
    families.append(http_ms)

    # ---- armed chaos fault points (docs/chaos.md) ----
    try:
        from ..serving import faults as faults_mod

        armed = faults_mod.snapshot()
    except Exception:
        armed = {}
    fault_fam = _Family(
        "room_tpu_fault_armed", "gauge",
        "Armed chaos fault points (1 = armed); firing counts ride "
        "room_tpu_events_total{event=\"fault.<point>\"}.",
    )
    fired_fam = _Family(
        "room_tpu_fault_fired_total", "counter",
        "Firings of currently-armed fault points.",
    )
    for point, spec in sorted(armed.items()):
        fault_fam.add({"point": point}, 1)
        fired_fam.add({"point": point}, spec["fired"])
    families.append(fault_fam)
    families.append(fired_fam)

    # ---- invariant witness (docs/chaosfuzz.md) ----
    try:
        from ..chaos import invariants as invariants_mod

        inv_armed = invariants_mod.enabled()
        inv_snap = invariants_mod.snapshot() if inv_armed else None
    except Exception:
        inv_armed, inv_snap = False, None
    if inv_armed and inv_snap is not None:
        inv_fam = _Family(
            "room_tpu_invariant_violations_total", "counter",
            "Runtime invariant-witness violations by invariant "
            "(ROOM_TPU_INVARIANTS; zero samples present while armed "
            "so alerts can rate() on them).",
        )
        by = inv_snap.get("by_invariant", {})
        for name in invariants_mod.INVARIANTS:
            inv_fam.add({"invariant": name}, by.get(name, 0))
        families.append(inv_fam)

    return "\n".join(f.render() for f in families) + "\n"


def render_families(names: Iterable[str]) -> str:
    """Subset render for tests: only families whose name is in
    ``names`` (keeps strict-parser fixtures small)."""
    full = render_metrics()
    keep = set(names)
    out = []
    current_keep = False
    for line in full.splitlines():
        if line.startswith("# HELP "):
            current_keep = line.split()[2] in keep
        if current_keep:
            out.append(line)
    return "\n".join(out) + "\n"
