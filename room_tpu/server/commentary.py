"""Clerk commentary engine (reference: src/server/clerk-commentary.ts):
subscribes to cycle events, buffers what the swarm is doing, and
periodically narrates it to the 'clerk' WS channel in a lively
commentator voice — active pace while cycles flow, light pace when
quiet, paused while the keeper is chatting."""

from __future__ import annotations

import threading
import time
from typing import Optional

from ..core.events import event_bus
from ..core.messages import get_setting
from ..db import Database
from ..providers import ExecutionRequest, get_model_provider
from ..utils import locks

ACTIVE_PACE_S = (8.0, 30.0)
LIGHT_PACE_S = 2 * 3600.0
KEEPER_SILENCE_RESUME_S = 60.0

COMMENTARY_PROMPT = (
    "You are the live commentator for an agent swarm. Narrate the "
    "recent activity below in 1-2 punchy sentences — present tense, "
    "energetic, concrete. Never invent events. No preamble, no quotes."
)


class CommentaryEngine:
    def __init__(
        self, db: Database, model: Optional[str] = None
    ) -> None:
        self.db = db
        self._model = model
        self._buffer: list[str] = []
        self._lock = locks.make_lock("commentary")
        self._last_keeper_msg = 0.0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._unsubs = []

    # ---- event intake ----

    def _on_event(self, event) -> None:
        if event.type == "cycle:log":
            data = event.data or {}
            if data.get("entry_type") in ("assistant", "tool_call"):
                with self._lock:
                    self._buffer.append(
                        f"{event.channel}: {str(data.get('content'))[:200]}"
                    )
                    del self._buffer[:-40]
        elif event.type in ("cycle:started", "run:created",
                            "decision", "escalation:created"):
            with self._lock:
                self._buffer.append(f"{event.type} on {event.channel}")
                del self._buffer[:-40]
        elif event.type == "chat:message":
            self._last_keeper_msg = time.monotonic()

    # ---- narration ----

    def narrate_once(self) -> Optional[str]:
        with self._lock:
            events, self._buffer = self._buffer, []
        if not events:
            return None
        if time.monotonic() - self._last_keeper_msg < \
                KEEPER_SILENCE_RESUME_S:
            return None  # keeper is talking; stay quiet

        model = self._model or get_setting(
            self.db, "clerk_model", "echo"
        ) or "echo"
        provider = get_model_provider(model, self.db)
        ready, _ = provider.is_ready()
        if not ready:
            return None
        result = provider.execute(ExecutionRequest(
            prompt="\n".join(events[-20:]),
            system_prompt=COMMENTARY_PROMPT,
            max_turns=1, max_new_tokens=120, timeout_s=60,
            turn_class="background",
        ))
        self.db.insert(
            "INSERT INTO clerk_usage(source, model, input_tokens, "
            "output_tokens, total_tokens, success) VALUES "
            "('commentary', ?,?,?,?,?)",
            (model, result.input_tokens, result.output_tokens,
             result.input_tokens + result.output_tokens,
             int(result.success)),
        )
        if not (result.success and result.text):
            return None
        self.db.insert(
            "INSERT INTO clerk_messages(role, content, source) "
            "VALUES ('commentary', ?, 'commentary')",
            (result.text,),
        )
        event_bus.emit("clerk:commentary", "clerk",
                       {"text": result.text})
        return result.text

    # ---- lifecycle ----

    def start(self) -> None:
        self._unsubs.append(event_bus.subscribe(None, self._on_event))

        def loop():
            while not self._stop.is_set():
                with self._lock:
                    busy = len(self._buffer) > 0
                pace = ACTIVE_PACE_S[1] if busy else LIGHT_PACE_S
                if self._stop.wait(timeout=pace):
                    return
                try:
                    self.narrate_once()
                except Exception:
                    pass

        self._thread = threading.Thread(
            target=loop, daemon=True, name="clerk-commentary"
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        for unsub in self._unsubs:
            unsub()
        if self._thread:
            self._thread.join(timeout=5)
