"""Provider auth + install sessions: run `<cli> login` or the npm
install server-side and stream output to the dashboard (reference:
src/server/provider-auth.ts — session store, line ring buffer,
verification-URL/device-code extraction, one active session per
provider, timeout + TTL cleanup; src/server/provider-install.ts — same
machinery around `npm install -g <package>`).
"""

from __future__ import annotations

import re
import subprocess
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Optional

from ..providers.cli import _clean_env, resolve_cli_path
from ..utils import knobs, locks

MAX_LINES = max(
    50, knobs.get_int("ROOM_TPU_PROVIDER_AUTH_MAX_LINES")
)
SESSION_TIMEOUT_S = max(
    30.0,
    knobs.get_float("ROOM_TPU_PROVIDER_AUTH_TIMEOUT_S"),
)
SESSION_TTL_S = max(
    60.0,
    knobs.get_float("ROOM_TPU_PROVIDER_AUTH_TTL_S"),
)

_URL_RE = re.compile(r"https://\S+", re.IGNORECASE)
_CODE_RE = re.compile(r"\b([A-Z0-9]{4}-?[A-Z0-9]{4,6})\b")

ACTIVE_STATUSES = ("starting", "running")


@dataclass
class AuthSession:
    session_id: str
    provider: str
    command: str
    status: str = "starting"  # starting|running|completed|failed|canceled|timeout
    started_at: float = field(default_factory=time.time)
    updated_at: float = field(default_factory=time.time)
    ended_at: Optional[float] = None
    exit_code: Optional[int] = None
    verification_url: Optional[str] = None
    device_code: Optional[str] = None
    lines: list[dict] = field(default_factory=list)
    _proc: Optional[subprocess.Popen] = None
    _seq: int = 0
    _stop_reason: Optional[str] = None

    def view(self) -> dict:
        return {
            "sessionId": self.session_id,
            "provider": self.provider,
            "status": self.status,
            "command": self.command,
            "startedAt": self.started_at,
            "updatedAt": self.updated_at,
            "endedAt": self.ended_at,
            "exitCode": self.exit_code,
            "verificationUrl": self.verification_url,
            "deviceCode": self.device_code,
            "active": self.status in ACTIVE_STATUSES,
            "lines": list(self.lines),
        }


class ProviderAuthManager:
    def __init__(self) -> None:
        self._sessions: dict[str, AuthSession] = {}
        self._active_by_provider: dict[str, str] = {}
        self._lock = locks.make_lock("provider_auth")

    def _command_for(self, provider: str) -> list[str]:
        path = resolve_cli_path(provider)
        if not path:
            raise FileNotFoundError(f"{provider} CLI not installed")
        return [path, "login"]

    def _label_for(self, provider: str) -> str:
        return f"{provider} login"

    # ---- public API ----

    def start(self, provider: str) -> dict:
        if provider not in ("claude", "codex"):
            raise ValueError(f"unknown provider {provider!r}")
        command = self._command_for(provider)

        with self._lock:
            self._cleanup_locked()
            active_id = self._active_by_provider.get(provider)
            if active_id:
                active = self._sessions.get(active_id)
                if active and active.status in ACTIVE_STATUSES:
                    return active.view()  # one login flow at a time

            sess = AuthSession(
                session_id=uuid.uuid4().hex,
                provider=provider,
                command=self._label_for(provider),
            )
            try:
                sess._proc = subprocess.Popen(
                    command,
                    stdin=subprocess.DEVNULL,
                    stdout=subprocess.PIPE,
                    stderr=subprocess.PIPE,
                    text=True,
                    env=_clean_env(),
                )
            except OSError as e:
                sess.status = "failed"
                sess.ended_at = time.time()
                self._append_line(sess, "system", f"spawn failed: {e}")
                self._sessions[sess.session_id] = sess
                return sess.view()
            sess.status = "running"
            self._sessions[sess.session_id] = sess
            self._active_by_provider[provider] = sess.session_id

        threading.Thread(
            target=self._pump, args=(sess, "stdout"), daemon=True,
        ).start()
        threading.Thread(
            target=self._pump, args=(sess, "stderr"), daemon=True,
        ).start()
        threading.Thread(
            target=self._reap, args=(sess,), daemon=True,
        ).start()
        return sess.view()

    def get(self, session_id: str) -> Optional[dict]:
        with self._lock:
            sess = self._sessions.get(session_id)
            return sess.view() if sess else None

    def active_for(self, provider: str) -> Optional[dict]:
        with self._lock:
            sid = self._active_by_provider.get(provider)
            sess = self._sessions.get(sid) if sid else None
            if sess and sess.status in ACTIVE_STATUSES:
                return sess.view()
            return None

    def cancel(self, session_id: str) -> Optional[dict]:
        with self._lock:
            sess = self._sessions.get(session_id)
            if sess is None:
                return None
            if sess.status in ACTIVE_STATUSES and sess._proc:
                sess._stop_reason = "canceled"
                try:
                    sess._proc.terminate()
                except OSError:
                    pass
            return sess.view()

    def shutdown(self) -> None:
        with self._lock:
            sessions = list(self._sessions.values())
        for sess in sessions:
            if sess.status in ACTIVE_STATUSES and sess._proc:
                sess._stop_reason = "canceled"
                try:
                    sess._proc.kill()
                except OSError:
                    pass

    # ---- internals ----

    def _append_line(self, sess: AuthSession, stream: str,
                     text: str) -> None:
        sess._seq += 1
        sess.lines.append({
            "id": sess._seq, "stream": stream, "text": text,
            "timestamp": time.time(),
        })
        if len(sess.lines) > MAX_LINES:
            del sess.lines[: len(sess.lines) - MAX_LINES]
        sess.updated_at = time.time()
        if sess.verification_url is None:
            m = _URL_RE.search(text)
            if m:
                sess.verification_url = m.group(0).rstrip(".,)")
        if sess.device_code is None:
            m = _CODE_RE.search(text)
            if m:
                sess.device_code = m.group(1)

    def _pump(self, sess: AuthSession, which: str) -> None:
        pipe = getattr(sess._proc, which)
        for line in iter(pipe.readline, ""):
            line = line.rstrip("\n")
            if not line.strip():
                continue
            with self._lock:
                self._append_line(sess, which, line)

    def _reap(self, sess: AuthSession) -> None:
        deadline = sess.started_at + SESSION_TIMEOUT_S
        while True:
            code = sess._proc.poll()
            if code is not None:
                break
            if time.time() >= deadline:
                sess._stop_reason = sess._stop_reason or "timeout"
                try:
                    sess._proc.terminate()
                except OSError:
                    pass
                try:
                    sess._proc.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    try:
                        sess._proc.kill()
                    except OSError:
                        pass
                code = sess._proc.wait()
                break
            time.sleep(0.1)
        with self._lock:
            sess.exit_code = code
            sess.ended_at = time.time()
            if sess._stop_reason == "canceled":
                sess.status = "canceled"
            elif sess._stop_reason == "timeout":
                sess.status = "timeout"
            else:
                sess.status = "completed" if code == 0 else "failed"
            self._append_line(
                sess, "system",
                f"{sess.command} exited with {code} ({sess.status})",
            )
            if self._active_by_provider.get(sess.provider) == \
                    sess.session_id:
                del self._active_by_provider[sess.provider]

    def _cleanup_locked(self) -> None:
        cutoff = time.time() - SESSION_TTL_S
        for sid in [
            sid for sid, s in self._sessions.items()
            if s.status not in ACTIVE_STATUSES
            and (s.ended_at or s.started_at) < cutoff
        ]:
            del self._sessions[sid]


INSTALL_PACKAGES = {
    "claude": "@anthropic-ai/claude-code",
    "codex": "@openai/codex",
}


class ProviderInstallManager(ProviderAuthManager):
    """Same session machinery around `npm install -g <package>`
    (reference: provider-install.ts). ROOM_TPU_NPM overrides the npm
    binary (the test seam; also how deployments pin a package
    manager)."""

    def _command_for(self, provider: str) -> list[str]:
        import shutil

        npm = knobs.get_str("ROOM_TPU_NPM") or shutil.which("npm")
        if not npm:
            raise FileNotFoundError(
                "npm not found; install Node.js to install provider "
                "CLIs"
            )
        return [npm, "install", "-g", INSTALL_PACKAGES[provider]]

    def _label_for(self, provider: str) -> str:
        return f"npm install -g {INSTALL_PACKAGES[provider]}"


_manager: Optional[ProviderAuthManager] = None
_install_manager: Optional[ProviderInstallManager] = None
_manager_lock = locks.make_lock("provider_auth_manager")


def get_auth_manager() -> ProviderAuthManager:
    global _manager
    with _manager_lock:
        if _manager is None:
            _manager = ProviderAuthManager()
        return _manager


def get_install_manager() -> ProviderInstallManager:
    global _install_manager
    with _manager_lock:
        if _install_manager is None:
            _install_manager = ProviderInstallManager()
        return _install_manager
