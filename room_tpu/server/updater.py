"""Update checker + staged auto-update + safe restart (reference:
src/server/updateChecker.ts — 4h poll, cloud source with GitHub-releases
fallback, exponential backoff, diagnostics; src/server/autoUpdate.ts —
lightweight bundle downloaded to a staging dir, sha256 checksums from
version.json, promote-on-restart, `.booting` marker with a 3-strike
crash rollback; src/server/index.ts:526-576 — localhost-only
restart / update-restart endpoints).

The update *source* is an HTTP JSON endpoint
(ROOM_TPU_UPDATE_SOURCE_URL -> {"version", "updateBundleUrl",
"releaseUrl"}) or the GitHub releases API (ROOM_TPU_UPDATE_GITHUB_REPO,
"owner/name"); with neither configured the checker idles — this image
has no egress, so tests stub the source with a local HTTP server.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import shutil
import sys
import tarfile
import threading
import time
import urllib.error
import urllib.request
from typing import Any, Callable, Optional

from .. import __version__
from ..utils import knobs, locks

DEFAULT_POLL_S = 4 * 3600.0
INITIAL_DELAY_S = 15.0
BACKOFF_BASE_S = 30.0
BACKOFF_MAX_S = 30 * 60.0
BOOT_GRACE_S = 30.0
CRASH_ROLLBACK_THRESHOLD = 3


def data_dir() -> str:
    return os.path.expanduser(knobs.get_str("ROOM_TPU_DATA_DIR"))


def app_dir() -> str:
    return os.path.join(data_dir(), "app")


def staging_dir() -> str:
    return os.path.join(data_dir(), "app-staging")


def _version_file(base: str) -> str:
    return os.path.join(base, "version.json")


# ---- semver ----

def parse_semver(tag: str) -> Optional[tuple[int, int, int]]:
    m = re.match(
        r"^(\d+)\.(\d+)\.(\d+)(?:[-+].*)?$",
        tag.strip().lstrip("vV"),
    )
    return (int(m[1]), int(m[2]), int(m[3])) if m else None


def semver_gt(a: str, b: str) -> bool:
    pa, pb = parse_semver(a), parse_semver(b)
    if pa is None or pb is None:
        return False
    return pa > pb


# ---- checker ----

class UpdateChecker:
    def __init__(
        self,
        poll_s: float = DEFAULT_POLL_S,
        on_ready_update: Optional[Callable[[str], None]] = None,
    ) -> None:
        self.poll_s = poll_s
        self.on_ready_update = on_ready_update
        self.cached: Optional[dict] = None
        self.auto_status: dict = {"state": "idle"}
        self.diagnostics = {
            "lastCheckAt": None, "lastSuccessAt": None,
            "lastErrorAt": None, "lastErrorCode": None,
            "lastErrorMessage": None, "updateSource": None,
            "nextCheckAt": None, "consecutiveFailures": 0,
        }
        self._failures = 0
        self._backoff_until = 0.0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = locks.make_lock("updater")

    # -- sources --

    def _cloud_source(self) -> Optional[dict]:
        url = (knobs.get_str("ROOM_TPU_UPDATE_SOURCE_URL")
               or "").strip()
        if not url:
            return None
        token = (knobs.get_str("ROOM_TPU_UPDATE_SOURCE_TOKEN")
                 or "").strip() or None
        return {"url": url, "token": token}

    def _github_repo(self) -> Optional[str]:
        return (knobs.get_str("ROOM_TPU_UPDATE_GITHUB_REPO")
                or "").strip() or None

    def _fetch_json(self, url: str,
                    headers: Optional[dict] = None) -> Any:
        req = urllib.request.Request(
            url,
            headers={
                "User-Agent": "room-tpu-update-checker",
                "Accept": "application/json",
                **(headers or {}),
            },
        )
        with urllib.request.urlopen(req, timeout=10) as resp:
            return json.loads(resp.read())

    def _resolve(self) -> tuple[dict, str]:
        cloud = self._cloud_source()
        cloud_err: Optional[str] = None
        if cloud:
            try:
                raw = self._fetch_json(
                    cloud["url"],
                    {"Authorization": f"Bearer {cloud['token']}"}
                    if cloud["token"] else {},
                )
                version = str(raw.get("version") or "").lstrip("v")
                bundle = str(raw.get("updateBundleUrl") or "").strip()
                if not version:
                    raise ValueError("cloud source missing version")
                if not bundle:
                    raise ValueError("cloud source missing bundle url")
                return {
                    "latestVersion": version,
                    "releaseUrl": raw.get("releaseUrl"),
                    "updateBundle": bundle,
                }, "cloud"
            except Exception as e:
                cloud_err = str(e)
        repo = self._github_repo()
        if repo:
            raw = self._fetch_json(
                f"https://api.github.com/repos/{repo}/releases"
                "?per_page=100"
            )
            info = self._parse_github(raw)
            if info:
                return info, "github"
            raise ValueError("no stable release with a bundle asset")
        if cloud_err:
            raise ValueError(f"cloud source failed: {cloud_err}")
        raise ValueError("no update source configured")

    @staticmethod
    def _parse_github(releases: Any) -> Optional[dict]:
        best = None
        best_ver = None
        for r in releases or []:
            if r.get("draft") or r.get("prerelease"):
                continue
            if "-test" in (r.get("tag_name") or "").lower():
                continue
            ver = parse_semver(r.get("tag_name") or "")
            if ver and (best_ver is None or ver > best_ver):
                best, best_ver = r, ver
        if best is None:
            return None
        bundle = None
        for a in best.get("assets") or []:
            name = a.get("name") or ""
            if name.startswith("room-tpu-update-") and \
                    name.endswith(".tar.gz"):
                bundle = a.get("browser_download_url")
        return {
            "latestVersion": (best.get("tag_name") or "").lstrip("v"),
            "releaseUrl": best.get("html_url"),
            "updateBundle": bundle,
        }

    # -- check loop --

    def force_check(self, ignore_backoff: bool = False) -> None:
        with self._lock:
            now = time.time()
            self.diagnostics["lastCheckAt"] = now
            if not ignore_backoff and self._backoff_until > now:
                self.diagnostics["nextCheckAt"] = self._backoff_until
                return
            try:
                info, source = self._resolve()
                self.cached = info
                self.diagnostics.update(
                    updateSource=source, lastSuccessAt=time.time(),
                    lastErrorAt=None, lastErrorCode=None,
                    lastErrorMessage=None, nextCheckAt=None,
                    consecutiveFailures=0,
                )
                self._failures = 0
                self._backoff_until = 0.0
            except Exception as e:
                self._failures += 1
                backoff = 0.0 if self._failures <= 1 else min(
                    BACKOFF_MAX_S,
                    BACKOFF_BASE_S * 2 ** min(8, self._failures - 2),
                )
                self._backoff_until = (
                    time.time() + backoff if backoff else 0.0
                )
                self.diagnostics.update(
                    lastErrorAt=time.time(),
                    lastErrorCode=type(e).__name__,
                    lastErrorMessage=str(e),
                    consecutiveFailures=self._failures,
                    nextCheckAt=self._backoff_until or None,
                )
                return

        info = self.cached
        if info and info.get("updateBundle") and \
                semver_gt(info["latestVersion"], __version__):
            before = get_ready_update_version()
            try:
                self.download_and_stage(
                    info["updateBundle"], info["latestVersion"]
                )
            except Exception as e:
                self.auto_status = {"state": "error", "error": str(e)}
                return
            after = get_ready_update_version()
            if self.on_ready_update and after and after != before:
                try:
                    self.on_ready_update(after)
                except Exception:
                    pass

    def download_and_stage(self, bundle_url: str, version: str) -> None:
        """Download → extract → verify checksums → mark ready. The
        staged tree is promoted on the next (update-)restart."""
        if get_ready_update_version() == version:
            return
        self.auto_status = {"state": "downloading", "version": version}
        # build + verify in a scratch dir; staging_dir only ever comes
        # into existence via the atomic rename AFTER checksums pass, so
        # a crash mid-download/mid-verify can never leave a tree that
        # get_ready_update_version would report as ready
        scratch = staging_dir() + ".tmp"
        try:
            self._download_and_stage_inner(bundle_url, version, scratch)
            shutil.rmtree(staging_dir(), ignore_errors=True)
            os.rename(scratch, staging_dir())
        except Exception:
            shutil.rmtree(scratch, ignore_errors=True)
            raise

    def _download_and_stage_inner(
        self, bundle_url: str, version: str, stage: str
    ) -> None:
        shutil.rmtree(stage, ignore_errors=True)
        os.makedirs(stage, exist_ok=True)
        tarball = os.path.join(stage, "update.tar.gz")
        req = urllib.request.Request(
            bundle_url,
            headers={"User-Agent": "room-tpu-auto-updater/1.0"},
        )
        with urllib.request.urlopen(req, timeout=60) as resp, \
                open(tarball, "wb") as f:
            shutil.copyfileobj(resp, f)

        self.auto_status = {"state": "verifying", "version": version}
        with tarfile.open(tarball, "r:gz") as tf:
            tf.extractall(stage, filter="data")
        os.unlink(tarball)

        vf = _version_file(stage)
        if not os.path.exists(vf):
            raise ValueError("missing version.json in update bundle")
        with open(vf) as f:
            vinfo = json.load(f)
        if not vinfo.get("version"):
            raise ValueError("version.json missing version field")
        for rel, expected in (vinfo.get("checksums") or {}).items():
            path = os.path.join(stage, rel)
            if not os.path.exists(path):
                raise ValueError(f"missing file in update: {rel}")
            h = hashlib.sha256()
            with open(path, "rb") as f:
                for chunk in iter(lambda: f.read(65536), b""):
                    h.update(chunk)
            if h.hexdigest() != expected:
                raise ValueError(f"checksum mismatch for {rel}")
        self.auto_status = {
            "state": "ready", "version": vinfo["version"],
        }

    def start(self) -> None:
        def loop() -> None:
            if self._stop.wait(INITIAL_DELAY_S):
                return
            while not self._stop.is_set():
                try:
                    self.force_check()
                except Exception:
                    pass
                if self._stop.wait(self.poll_s):
                    return

        self._thread = threading.Thread(
            target=loop, daemon=True, name="update-checker"
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)

    def status_view(self) -> dict:
        auto = dict(self.auto_status)
        if auto.get("state") == "idle":
            ready = get_ready_update_version()
            if ready:
                auto = {"state": "ready", "version": ready}
        return {
            "currentVersion": __version__,
            "updateInfo": self.cached,
            "autoUpdate": auto,
            "diagnostics": dict(self.diagnostics),
        }


# ---- staged-update promotion + crash rollback ----

def get_ready_update_version() -> Optional[str]:
    vf = _version_file(staging_dir())
    try:
        with open(vf) as f:
            version = json.load(f).get("version")
        return version if version and \
            semver_gt(version, __version__) else None
    except (OSError, json.JSONDecodeError):
        return None


def promote_staged_update() -> Optional[str]:
    """Move the verified staging tree into the live app dir (called on
    the way into an update-restart)."""
    version = get_ready_update_version()
    if not version:
        return None
    target = app_dir()
    shutil.rmtree(target, ignore_errors=True)
    os.rename(staging_dir(), target)
    return version


def init_boot_health_check(grace_s: float = BOOT_GRACE_S) -> None:
    """On startup: roll back a crash-looping user-space update (3
    strikes), clean a stale one (bundled version >= staged), then arm
    the boot marker that the crash counter rides on."""
    target = app_dir()
    marker = os.path.join(target, ".booting")
    crash_file = os.path.join(target, ".crash_count")
    if not os.path.isdir(target):
        return
    vf = _version_file(target)
    if not os.path.exists(vf):
        shutil.rmtree(target, ignore_errors=True)  # legacy/unversioned
        return
    try:
        with open(vf) as f:
            staged_version = json.load(f).get("version") or ""
    except (OSError, json.JSONDecodeError):
        staged_version = ""
    if not semver_gt(staged_version, __version__):
        shutil.rmtree(target, ignore_errors=True)  # stale
        return

    crashes = 0
    if os.path.exists(marker):
        # previous boot never survived the grace window
        try:
            with open(crash_file) as f:
                crashes = int(f.read().strip() or 0)
        except (OSError, ValueError):
            crashes = 0
        crashes += 1
        if crashes >= CRASH_ROLLBACK_THRESHOLD:
            shutil.rmtree(target, ignore_errors=True)
            return
        try:
            with open(crash_file, "w") as f:
                f.write(str(crashes))
        except OSError:
            pass
    try:
        with open(marker, "w") as f:
            json.dump({"pid": os.getpid(), "at": time.time()}, f)
    except OSError:
        return

    def clear() -> None:
        for path in (marker, crash_file):
            try:
                os.unlink(path)
            except OSError:
                pass

    t = threading.Timer(grace_s, clear)
    t.daemon = True
    t.start()


# ---- restart ----

_restart_hook: Optional[Callable[[], None]] = None


def set_restart_hook(hook: Optional[Callable[[], None]]) -> None:
    """Tests and embedders override what 'restart' means."""
    global _restart_hook
    _restart_hook = hook


def schedule_self_restart(delay_s: float = 0.12) -> bool:
    """Exec a fresh copy of this process after a short delay so the
    HTTP response gets out first (reference: scheduleSelfRestart)."""
    def do_restart() -> None:
        if _restart_hook is not None:
            _restart_hook()
            return
        os.execv(sys.executable, [sys.executable] + sys.argv)

    try:
        t = threading.Timer(delay_s, do_restart)
        t.daemon = True
        t.start()
        return True
    except Exception:
        return False


_checker: Optional[UpdateChecker] = None


def get_update_checker() -> UpdateChecker:
    global _checker
    if _checker is None:
        _checker = UpdateChecker()
    return _checker


def reset_update_checker() -> None:
    global _checker
    if _checker is not None:
        _checker.stop()
    _checker = None
