"""WebSocket server on the stdlib HTTP stack (reference:
src/server/ws.ts): ?token= upgrade auth, channel subscribe/unsubscribe
protocol, 30 s ping heartbeat, event-bus fan-out to subscribed channels.

RFC 6455 implemented directly (no external ws dependency): handshake
accept key, masked client frames, server text/ping/pong/close frames."""

from __future__ import annotations

import base64
import hashlib
import json
import queue
import socket
import struct
import threading
import urllib.parse
from typing import Optional

from ..core.events import event_bus
from .auth import get_token_principal
from ..utils import locks

_WS_GUID = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"
HEARTBEAT_S = 30.0


def _accept_key(key: str) -> str:
    return base64.b64encode(
        hashlib.sha1((key + _WS_GUID).encode()).digest()
    ).decode()


def _encode_frame(opcode: int, payload: bytes) -> bytes:
    header = bytearray([0x80 | opcode])
    n = len(payload)
    if n < 126:
        header.append(n)
    elif n < 65536:
        header.append(126)
        header += struct.pack(">H", n)
    else:
        header.append(127)
        header += struct.pack(">Q", n)
    return bytes(header) + payload


class _Client:
    """One connected socket. All writes ride a bounded queue drained by
    a dedicated writer thread: the event bus fans out on EMITTER
    threads (agent loop, runtime, engine), so a stalled browser must
    cost one dropped client, never a blocked emitter. A full queue
    (consumer not draining ~512 frames behind) kills the client —
    backpressure by disconnection, the same contract the reference's
    ws library applies."""

    MAX_QUEUE = 512

    def __init__(self, sock) -> None:
        self.sock = sock
        self.channels: set[str] = set()
        self.alive = True
        self._q: "queue.Queue[Optional[bytes]]" = queue.Queue(
            self.MAX_QUEUE
        )
        threading.Thread(
            target=self._drain, daemon=True, name="ws-writer"
        ).start()

    def _drain(self) -> None:
        while True:
            frame = self._q.get()
            if frame is None:
                break
            try:
                self.sock.sendall(frame)
            except OSError:
                break
        self.alive = False
        try:
            self.sock.close()
        except OSError:
            pass

    def _enqueue(self, frame: bytes) -> bool:
        if not self.alive:
            return False
        try:
            self._q.put_nowait(frame)
            return True
        except queue.Full:
            # slow consumer: shutdown() only — it unblocks a writer
            # stuck mid-sendall on a full TCP buffer, and unlike
            # close() it cannot race the writer into a reused fd. The
            # writer's sendall then errors and _drain (the fd's sole
            # owner) closes the socket.
            self.alive = False
            try:
                self.sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            return False

    def send_text(self, text: str) -> bool:
        return self._enqueue(_encode_frame(0x1, text.encode()))

    def ping(self) -> bool:
        return self._enqueue(_encode_frame(0x9, b""))

    def pong(self, payload: bytes) -> bool:
        return self._enqueue(_encode_frame(0xA, payload))

    def close(self) -> None:
        # alive goes False FIRST so no data frame can land behind the
        # close frame (RFC 6455: nothing follows Close)
        self.alive = False
        try:
            self._q.put_nowait(_encode_frame(0x8, b""))
            self._q.put_nowait(None)   # writer flushes, then closes
        except queue.Full:
            # writer is wedged behind a full queue: unblock it; its
            # sendall error ends _drain, which closes the fd
            try:
                self.sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass


class WebSocketHub:
    """Fan-out is subscription-driven, not a firehose: the hub holds
    one ref-counted event-bus subscription per channel a client has
    asked for (``"*"`` maps to the bus wildcard), so an event on a
    channel nobody watches never reaches the hub at all. With swarm
    shards emitting every room's traffic onto the global bus, the old
    subscribe-everything handler made every WS hub pay O(events) for
    O(subscribed) interest."""

    def __init__(self, server) -> None:
        self.server = server
        self._clients: list[_Client] = []
        self._lock = locks.make_lock("ws_hub")
        self._stop = threading.Event()
        # channel -> [bus_unsubscribe, client_refcount]
        self._subs: dict[str, list] = {}

    def start(self) -> None:
        threading.Thread(
            target=self._heartbeat, daemon=True, name="ws-heartbeat"
        ).start()

    def stop(self) -> None:
        self._stop.set()
        with self._lock:
            subs, self._subs = self._subs, {}
            clients, self._clients = self._clients, []
        for unsub, _ in subs.values():
            if unsub is not None:
                unsub()
        for c in clients:
            c.close()

    # ---- per-channel bus subscriptions (ref-counted) ----

    def _acquire_channel(self, channel: str) -> None:
        with self._lock:
            entry = self._subs.get(channel)
            if entry is not None:
                entry[1] += 1
                return
            # claim the channel with a placeholder; the bus subscribe
            # happens OUTSIDE the hub lock because its handler
            # (_fanout) re-enters it
            entry = [None, 1]
            self._subs[channel] = entry
        bus_channel = None if channel == "*" else channel
        unsub = event_bus.subscribe(
            bus_channel,
            lambda ev, ch=channel: self._fanout(ev, ch),
        )
        with self._lock:
            if self._subs.get(channel) is entry:
                entry[0] = unsub
                return
        # lost a race with release/stop while subscribing: undo
        unsub()

    def _release_channel(self, channel: str) -> None:
        with self._lock:
            entry = self._subs.get(channel)
            if entry is None:
                return
            entry[1] -= 1
            if entry[1] > 0:
                return
            del self._subs[channel]
            unsub = entry[0]
        # unsub is None when the acquirer is still mid-subscribe; it
        # sees its entry gone and undoes the subscription itself
        if unsub is not None:
            unsub()

    def _drop_client(self, client: _Client) -> None:
        """Unregister a client and release its channel refs (must run
        exactly once per removal path)."""
        with self._lock:
            if client not in self._clients:
                return
            self._clients.remove(client)
        for channel in list(client.channels):
            self._release_channel(channel)
        client.channels.clear()

    @property
    def subscribed_channels(self) -> list[str]:
        with self._lock:
            return sorted(self._subs)

    @property
    def client_count(self) -> int:
        with self._lock:
            return len(self._clients)

    # ---- upgrade + per-connection loop ----

    def handle_upgrade(self, handler) -> None:
        parsed = urllib.parse.urlparse(handler.path)
        if parsed.path != "/ws":
            handler.send_response(404)
            handler.end_headers()
            return
        query = urllib.parse.parse_qs(parsed.query)
        token = (query.get("token") or [None])[0]
        if get_token_principal(token, self.server.tokens) is None:
            handler.send_response(401)
            handler.end_headers()
            return
        key = handler.headers.get("Sec-WebSocket-Key")
        if not key:
            handler.send_response(400)
            handler.end_headers()
            return

        handler.send_response(101, "Switching Protocols")
        handler.send_header("Upgrade", "websocket")
        handler.send_header("Connection", "Upgrade")
        handler.send_header("Sec-WebSocket-Accept", _accept_key(key))
        handler.end_headers()

        sock = handler.connection
        sock.settimeout(None)
        client = _Client(sock)
        with self._lock:
            self._clients.append(client)
        try:
            self._reader_loop(client, handler)
        finally:
            self._drop_client(client)
            # full close (sentinel included) — a TCP EOF with no WS
            # close frame must still end the writer thread, or every
            # dropped tab leaks one blocked thread
            client.close()
        handler.close_connection = True

    def _reader_loop(self, client: _Client, handler) -> None:
        rfile = handler.rfile
        while client.alive and not self._stop.is_set():
            frame = self._read_frame(rfile)
            if frame is None:
                return
            opcode, payload = frame
            if opcode == 0x8:        # close
                client.close()
                return
            if opcode == 0x9:        # ping -> pong
                if not client.pong(payload):
                    return
                continue
            if opcode == 0xA:        # pong
                continue
            if opcode != 0x1:
                continue
            try:
                msg = json.loads(payload)
            except json.JSONDecodeError:
                continue
            action = msg.get("type")
            channel = msg.get("channel")
            if action == "subscribe" and channel:
                if channel not in client.channels:
                    client.channels.add(channel)
                    self._acquire_channel(channel)
                client.send_text(json.dumps(
                    {"type": "subscribed", "channel": channel}
                ))
            elif action == "unsubscribe" and channel:
                if channel in client.channels:
                    client.channels.discard(channel)
                    self._release_channel(channel)
                client.send_text(json.dumps(
                    {"type": "unsubscribed", "channel": channel}
                ))

    @staticmethod
    def _read_frame(rfile) -> Optional[tuple[int, bytes]]:
        try:
            head = rfile.read(2)
            if len(head) < 2:
                return None
            opcode = head[0] & 0x0F
            masked = bool(head[1] & 0x80)
            length = head[1] & 0x7F
            if length == 126:
                length = struct.unpack(">H", rfile.read(2))[0]
            elif length == 127:
                length = struct.unpack(">Q", rfile.read(8))[0]
            if length > 1_000_000:
                return None
            mask = rfile.read(4) if masked else b"\x00" * 4
            payload = bytearray(rfile.read(length))
            if masked:
                for i in range(len(payload)):
                    payload[i] ^= mask[i % 4]
            return opcode, bytes(payload)
        except (OSError, struct.error):
            return None

    # ---- fan-out ----

    def _fanout(self, event, sub_channel: str) -> None:
        """Deliver one event to the clients holding ``sub_channel``.
        A client subscribed to both ``"*"`` and the event's channel is
        reached by the exact-channel handler; the wildcard handler
        skips it, so it still sees each event once."""
        text = json.dumps({
            "type": event.type,
            "channel": event.channel,
            "data": event.data,
            "timestamp": event.timestamp,
        })
        with self._lock:
            clients = list(self._clients)
        dead = []
        for c in clients:
            if sub_channel not in c.channels:
                continue
            if sub_channel == "*" and event.channel in c.channels:
                continue
            if not c.send_text(text):
                dead.append(c)
        for c in dead:
            self._drop_client(c)

    def _heartbeat(self) -> None:
        while not self._stop.wait(timeout=HEARTBEAT_S):
            with self._lock:
                clients = list(self._clients)
            for c in clients:
                if not c.ping():
                    self._drop_client(c)
