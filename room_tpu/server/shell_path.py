"""Shell PATH inheritance + port reclamation (reference:
src/server/shell-path.ts inheritShellPath — GUI-launched processes get
a minimal PATH, so ask the user's login shell for the real one;
src/server/index.ts killProcessListeningOnPort — on EADDRINUSE, kill
the stale instance and retry).
"""

from __future__ import annotations

import os
import signal
import subprocess
import time
from typing import Optional

_PROBE_TIMEOUT_S = 3.0


def inherit_shell_path() -> bool:
    """Merge the login shell's PATH into this process's PATH. Returns
    True when new entries were added. Never raises."""
    try:
        shell = os.environ.get("SHELL")
        if not shell or os.name == "nt":
            return False
        out = subprocess.run(
            [shell, "-l", "-c", "echo -n \"$PATH\""],
            capture_output=True, text=True, timeout=_PROBE_TIMEOUT_S,
        )
        if out.returncode != 0:
            return False
        shell_path = out.stdout.strip()
        if not shell_path:
            return False
        current = os.environ.get("PATH", "").split(os.pathsep)
        merged = list(current)
        added = False
        for entry in shell_path.split(os.pathsep):
            if entry and entry not in merged:
                merged.append(entry)
                added = True
        if added:
            os.environ["PATH"] = os.pathsep.join(merged)
        return added
    except Exception:
        return False


# ---- port reclamation (linux /proc, no psutil) ----

def _hex_port(port: int) -> str:
    return f"{port:04X}"


def find_pid_listening_on(port: int) -> Optional[int]:
    """Walk /proc/net/tcp{,6} for a LISTEN socket on the port, then map
    its inode to a pid via /proc/*/fd."""
    inodes = set()
    want = _hex_port(port)
    for table in ("/proc/net/tcp", "/proc/net/tcp6"):
        try:
            with open(table) as f:
                next(f)
                for line in f:
                    parts = line.split()
                    local, state, inode = parts[1], parts[3], parts[9]
                    if state == "0A" and local.endswith(f":{want}"):
                        inodes.add(inode)
        except OSError:
            continue
    if not inodes:
        return None
    for pid in os.listdir("/proc"):
        if not pid.isdigit():
            continue
        fd_dir = f"/proc/{pid}/fd"
        try:
            for fd in os.listdir(fd_dir):
                try:
                    target = os.readlink(os.path.join(fd_dir, fd))
                except OSError:
                    continue
                if target.startswith("socket:[") and \
                        target[8:-1] in inodes:
                    return int(pid)
        except OSError:
            continue
    return None


def kill_process_listening_on(port: int, grace_s: float = 2.0) -> bool:
    """SIGTERM (then SIGKILL) whatever holds the port. Returns True if
    the port was freed."""
    pid = find_pid_listening_on(port)
    if pid is None or pid == os.getpid():
        return False
    try:
        os.kill(pid, signal.SIGTERM)
    except OSError:
        return False
    deadline = time.monotonic() + grace_s
    while time.monotonic() < deadline:
        if find_pid_listening_on(port) is None:
            return True
        time.sleep(0.1)
    try:
        os.kill(pid, signal.SIGKILL)
    except OSError:
        pass
    time.sleep(0.2)
    return find_pid_listening_on(port) is None
