"""Tokened webhook endpoints, dispatched before auth (reference:
src/server/webhooks.ts): POST /api/hooks/task/:token runs the matching
task; POST /api/hooks/queen/:token files an escalation and wakes the
queen. 30 req/min per token."""

from __future__ import annotations

import threading
import time
from typing import Any
from ..utils import locks

WEBHOOK_RATE_PER_MIN = 30

_hits: dict[str, list[float]] = {}
_lock = locks.make_lock("webhooks")


MAX_TRACKED_TOKENS = 4096


def _rate_ok(token: str) -> bool:
    now = time.monotonic()
    with _lock:
        # bounded memory on a pre-auth endpoint: evict stale tokens
        # before tracking yet another attacker-supplied key
        if len(_hits) >= MAX_TRACKED_TOKENS:
            for key in [
                k for k, v in _hits.items()
                if not v or now - v[-1] >= 60
            ]:
                del _hits[key]
            if len(_hits) >= MAX_TRACKED_TOKENS:
                return False  # fully saturated: fail closed
        hits = [t for t in _hits.get(token, []) if now - t < 60]
        if len(hits) >= WEBHOOK_RATE_PER_MIN:
            _hits[token] = hits
            return False
        hits.append(now)
        _hits[token] = hits
        return True


def handle_webhook_request(
    server, method: str, path: str, body: Any
) -> tuple[int, dict]:
    if method != "POST":
        return 405, {"error": "POST only"}
    parts = path.strip("/").split("/")
    # api/hooks/<kind>/<token>
    if len(parts) != 4:
        return 404, {"error": "not found"}
    kind, token = parts[2], parts[3]
    if not _rate_ok(token):
        return 429, {"error": "rate limited"}

    db = server.db
    if kind == "task":
        task = db.query_one(
            "SELECT * FROM tasks WHERE webhook_token=?", (token,)
        )
        if task is None:
            return 404, {"error": "unknown token"}
        if server.runtime is None:
            return 503, {"error": "runtime not running"}
        queued = server.runtime.run_task_now(task["id"])
        return 200, {"status": 200,
                     "data": {"taskId": task["id"], "queued": queued}}

    if kind == "telegram":
        # the bot relays a /start deep-link token back; the path token
        # is the raw verification token itself (single-use, hashed at
        # rest — reference: contacts.ts telegram flow)
        from .contacts import confirm_telegram_verification

        info = body if isinstance(body, dict) else {}
        ok = confirm_telegram_verification(
            db, token,
            telegram_id=str(info.get("id") or ""),
            username=str(info.get("username") or ""),
            first_name=str(info.get("firstName") or ""),
        )
        if not ok:
            return 404, {"error": "unknown or expired token"}
        return 200, {"status": 200, "data": {"verified": True}}

    if kind == "queen":
        room = db.query_one(
            "SELECT * FROM rooms WHERE webhook_token=?", (token,)
        )
        if room is None:
            return 404, {"error": "unknown token"}
        question = ""
        if isinstance(body, dict):
            question = str(
                body.get("message") or body.get("question") or body
            )[:2000]
        from ..core.agent_loop import trigger_agent
        from ..core.escalations import create_escalation

        eid = create_escalation(
            db, room["id"], question or "webhook ping"
        )
        if room["queen_worker_id"]:
            trigger_agent(db, room["id"], room["queen_worker_id"])
        return 200, {"status": 200, "data": {"escalationId": eid}}

    return 404, {"error": "not found"}
