"""TPU host manager (reference: src/server/local-model.ts — the Ollama
host gate + one-click install session, re-targeted at TPU serving):

- hardware gate: device platform/count, HBM headroom vs the model's
  weight footprint, host RAM/disk floors
- provisioning session: bring up a ModelHost (weights load / random
  init) with line-streamed progress over the event bus, the same UX the
  reference used for its install session
- apply-to-all: point the clerk, every queen, and every room's
  worker_model at the tpu: provider in one transaction
"""

from __future__ import annotations

import shutil
import threading
import time
import uuid
from typing import Optional

from ..core.events import event_bus
from ..core.messages import set_setting
from ..db import Database, utc_now
from ..providers.tpu import MODEL_CONFIGS, checkpoint_dir, get_model_host
from ..utils import knobs, locks

MIN_HOST_RAM_GB = 8
MIN_FREE_DISK_GB = 10

_sessions: dict[str, dict] = {}
_lock = locks.make_lock("tpu_manager")


def _bytes_per_param(dtype: str) -> int:
    return 2 if dtype == "bfloat16" else 4


def model_param_count(name: str) -> int:
    cfg = MODEL_CONFIGS[name]()
    D, L = cfg.hidden, cfg.n_layers
    attn = D * (cfg.q_dim + 2 * cfg.kv_dim) + cfg.q_dim * D
    if cfg.is_moe:
        ffn = cfg.n_experts * 3 * D * cfg.moe_intermediate
    else:
        ffn = 3 * D * cfg.intermediate
    embed = cfg.vocab_size * D * 2  # embed + lm head
    return L * (attn + ffn) + embed


def model_weight_bytes(name: str) -> int:
    cfg = MODEL_CONFIGS[name]()
    return model_param_count(name) * _bytes_per_param(cfg.dtype)


# ---- hetero capacity planner (VERDICT r2 #6) ----
# v5e: 16 GB HBM per chip. Overheads are explicit so the arithmetic is
# testable: the XLA allocator keeps a slice of HBM for itself, and
# decode/prefill activations + collective scratch need a workspace.
V5E_HBM_PER_CHIP_GB = 16.0
HBM_USABLE_FRACTION = 0.92
WORKSPACE_FRACTION = 0.08
# int8 weights carry one f32 scale per output channel (ops/quant.py):
# ~1/16 overhead at the 128-wide granularity the quantizer uses
QUANT_BYTES_PER_PARAM = {"bf16": 2.0, "int8": 1.0625}
KV_DTYPE_BYTES = 2  # pages are bf16


def model_kv_bytes_per_token(
    name: str, kv_quant: Optional[str] = None
) -> int:
    cfg = MODEL_CONFIGS[name]()
    if kv_quant == "int8":
        # int8 payload + one f32 scale per (token, kv head), k and v
        return cfg.n_layers * 2 * (cfg.kv_dim + 4 * cfg.n_kv_heads)
    return cfg.n_layers * 2 * cfg.kv_dim * KV_DTYPE_BYTES


def configured_kv_quant() -> Optional[str]:
    """Mirrors serving.kv_pages.kv_quant_mode (same whitelist, same
    ValueError) without importing the jax-heavy serving stack into the
    status gate — a value the engine would refuse must fail the gate,
    not read as bf16 and pass it."""
    mode = knobs.get_str("ROOM_TPU_KV_QUANT").strip() or None
    if mode not in (None, "int8"):
        raise ValueError(f"unknown ROOM_TPU_KV_QUANT {mode!r}")
    return mode


def configured_kv_tokens() -> int:
    """The page pool the engine will actually allocate (providers/tpu.py
    reads the same env vars) — the status gate must plan with this, not
    the planner's 131k default, or a deployment tuned to a smaller pool
    reads as not fitting."""
    return knobs.get_int("ROOM_TPU_N_PAGES") * \
        knobs.get_int("ROOM_TPU_PAGE_SIZE")


def plan_placement(
    model: str,
    chips: int,
    quant: str = "bf16",
    kv_tokens: int = 131_072,
    hbm_per_chip_gb: Optional[float] = None,
    kv_quant: Optional[str] = None,
) -> dict:
    """Does ``model`` at ``quant`` fit a ``chips``-device submesh with a
    ``kv_tokens`` page pool?  Returns the arithmetic and, when it does
    not fit, what would: the int8 ladder first (ops/quant.py serves it),
    then the minimum chip count at the requested quant.

    The motivating case (BASELINE config #5): qwen2.5-72b bf16 is
    ~145 GB of weights — more than a whole v5e-8 — so a planner must
    force int8 (or more chips) rather than let provisioning OOM."""
    if quant not in QUANT_BYTES_PER_PARAM:
        raise ValueError(f"unknown quant {quant!r}")
    if model not in MODEL_CONFIGS:
        raise ValueError(f"unknown model {model!r}")
    hbm = (hbm_per_chip_gb or V5E_HBM_PER_CHIP_GB) * 1e9
    usable = chips * hbm * HBM_USABLE_FRACTION
    weights = model_param_count(model) * QUANT_BYTES_PER_PARAM[quant]
    kv = kv_tokens * model_kv_bytes_per_token(model, kv_quant)
    workspace = usable * WORKSPACE_FRACTION
    need = weights + kv + workspace
    fits = need <= usable

    suggestion = None
    if not fits:
        if quant == "bf16":
            int8_plan = plan_placement(
                model, chips, "int8", kv_tokens, hbm_per_chip_gb,
                kv_quant,
            )
            if int8_plan["fits"]:
                suggestion = "int8"
        if suggestion is None:
            # minimum chips at this quant (workspace scales with chips)
            per_chip_usable = hbm * HBM_USABLE_FRACTION
            denom = per_chip_usable * (1 - WORKSPACE_FRACTION)
            min_chips = max(1, -(-int(weights + kv) // int(denom)))
            suggestion = f"chips>={min_chips}"
    return {
        "model": model,
        "chips": chips,
        "quant": quant,
        "kv_tokens": kv_tokens,
        "weight_gb": round(weights / 1e9, 2),
        "kv_gb": round(kv / 1e9, 2),
        "workspace_gb": round(workspace / 1e9, 2),
        "usable_hbm_gb": round(usable / 1e9, 2),
        "fits": fits,
        "suggestion": suggestion,
    }


def plan_mesh(
    placements: list[dict],
    total_chips: int,
    hbm_per_chip_gb: Optional[float] = None,
) -> dict:
    """Plan a hetero mesh (e.g. 72b queen + 30b workers on disjoint
    submeshes): every placement must fit its submesh AND the submeshes
    must fit the pod."""
    plans = [
        plan_placement(
            p["model"], int(p["chips"]), p.get("quant", "bf16"),
            int(p.get("kv_tokens", 131_072)), hbm_per_chip_gb,
            p.get("kv_quant"),
        )
        for p in placements
    ]
    chips_used = sum(p["chips"] for p in plans)
    return {
        "placements": plans,
        "chips_used": chips_used,
        "total_chips": total_chips,
        "ok": chips_used <= total_chips
        and all(p["fits"] for p in plans),
    }


def get_tpu_status(model: str = "qwen3-coder-30b") -> dict:
    """The hardware gate (reference LIMITS:22-30 reimagined for TPU)."""
    checks: list[dict] = []

    def check(name: str, okay: bool, detail: str) -> None:
        checks.append({"name": name, "ok": bool(okay), "detail": detail})

    try:
        import jax

        platform = jax.default_backend()
        n_devices = jax.device_count()
        check(
            "accelerator", platform in ("tpu", "cpu"),
            f"{n_devices}x {platform}",
        )
        hbm_bytes = 0
        try:
            stats = jax.devices()[0].memory_stats() or {}
            hbm_bytes = stats.get("bytes_limit", 0)
        except Exception:
            pass
        if hbm_bytes:
            plan = plan_placement(
                model, n_devices,
                kv_tokens=configured_kv_tokens(),
                hbm_per_chip_gb=hbm_bytes / 1e9,
                kv_quant=configured_kv_quant(),
            )
            detail = (
                f"weights {plan['weight_gb']} GB + kv {plan['kv_gb']} "
                f"GB + workspace {plan['workspace_gb']} GB vs usable "
                f"{plan['usable_hbm_gb']} GB"
            )
            if plan["suggestion"]:
                detail += f" — try {plan['suggestion']}"
            check("hbm", plan["fits"], detail)
        else:
            check("hbm", True, "memory stats unavailable; unchecked")
    except Exception as e:
        check("accelerator", False, f"jax backend error: {e}")

    try:
        with open("/proc/meminfo") as f:
            mem_kb = int(f.readline().split()[1])
        check(
            "host_ram", mem_kb / 1e6 >= MIN_HOST_RAM_GB,
            f"{mem_kb/1e6:.1f} GB total",
        )
    except (OSError, ValueError, IndexError):
        check("host_ram", True, "unknown; unchecked")

    free_gb = shutil.disk_usage("/").free / 1e9
    check("disk", free_gb >= MIN_FREE_DISK_GB, f"{free_gb:.1f} GB free")

    ckpt = checkpoint_dir(model)
    check(
        "weights",
        bool(ckpt) or knobs.get_bool("ROOM_TPU_ALLOW_RANDOM_INIT")
        or model.startswith("tiny"),
        ckpt or "no checkpoint (set ROOM_TPU_CKPT_DIR or allow "
        "random init)",
    )

    return {
        "model": model,
        "ready": all(c["ok"] for c in checks),
        "checks": checks,
    }


MAX_SESSIONS_KEPT = 20


def start_provision_session(
    model: str = "qwen3-coder-30b",
) -> str:
    """Async weight-load session with streamed logs on channel
    'tpu-model' (reference install-session pattern,
    local-model.ts:427-519). One session per model at a time; repeat
    requests return the running session instead of double-loading
    weights."""
    with _lock:
        for s in _sessions.values():
            if s["model"] == model and s["status"] == "running":
                return s["id"]
    session_id = uuid.uuid4().hex[:12]
    with _lock:
        # bounded history: drop the oldest finished sessions
        finished = [
            k for k, s in _sessions.items() if s["status"] != "running"
        ]
        for k in finished[: max(0, len(_sessions) - MAX_SESSIONS_KEPT)]:
            del _sessions[k]
        _sessions[session_id] = {
            "id": session_id, "model": model, "status": "running",
            "log": [], "started_at": utc_now(),
        }

    def log(line: str) -> None:
        with _lock:
            _sessions[session_id]["log"].append(line)
        event_bus.emit(
            "tpu:provision", "tpu-model",
            {"session": session_id, "line": line},
        )

    def run() -> None:
        try:
            log(f"checking hardware gate for {model}...")
            status = get_tpu_status(model)
            for c in status["checks"]:
                log(f"  [{'ok' if c['ok'] else 'FAIL'}] {c['name']}: "
                    f"{c['detail']}")
            if not status["ready"]:
                raise RuntimeError("hardware gate failed")
            log("bringing up model host (mesh + weights)...")
            t0 = time.monotonic()
            host = get_model_host(model)
            host.engine()  # builds params, shards, starts scheduler
            log(f"model host ready in {time.monotonic()-t0:.1f}s")
            with _lock:
                _sessions[session_id]["status"] = "success"
            event_bus.emit("tpu:provisioned", "tpu-model",
                           {"session": session_id, "model": model})
        except Exception as e:
            log(f"provisioning failed: {e}")
            with _lock:
                _sessions[session_id]["status"] = "error"

    threading.Thread(target=run, daemon=True,
                     name=f"provision-{session_id}").start()
    return session_id


def get_provision_session(session_id: str) -> Optional[dict]:
    with _lock:
        s = _sessions.get(session_id)
        return dict(s) if s else None


def apply_tpu_model_to_all(
    db: Database, model: str = "qwen3-coder-30b"
) -> dict:
    """Atomically point clerk + queens + room worker models at tpu:
    (reference applyLocalModelToAll:568-619)."""
    model_str = f"tpu:{model}"
    with db.transaction():
        set_setting(db, "clerk_model", model_str)
        set_setting(db, "worker_model", model_str)
        rooms = db.execute(
            "UPDATE rooms SET worker_model=?, updated_at=?",
            (model_str, utc_now()),
        ).rowcount
        queens = db.execute(
            "UPDATE workers SET model=?, updated_at=? WHERE id IN "
            "(SELECT queen_worker_id FROM rooms WHERE queen_worker_id "
            "IS NOT NULL)",
            (model_str, utc_now()),
        ).rowcount
    return {"model": model_str, "rooms": rooms, "queens": queens}
