"""Minimal pattern router (reference: src/server/router.ts — ':param'
patterns compiled to regex; handlers return an ApiResponse dict)."""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Any, Callable, Optional

# handler(ctx) -> {"status": int, "data": ..., "error": ...}
Handler = Callable[["RequestContext"], dict]


@dataclass
class RequestContext:
    method: str
    path: str
    params: dict[str, str]
    query: dict[str, str]
    body: Any
    principal: Optional[dict] = None  # {"role": agent|user|member}
    db: Any = None
    runtime: Any = None


def ok(data: Any = None, status: int = 200) -> dict:
    return {"status": status, "data": data}


def err(error: str, status: int = 400) -> dict:
    return {"status": status, "error": error}


class Router:
    def __init__(self) -> None:
        self._routes: list[
            tuple[str, re.Pattern, list[str], Handler, str]
        ] = []

    def add(self, method: str, pattern: str, handler: Handler) -> None:
        names: list[str] = []

        def sub(m: re.Match) -> str:
            names.append(m.group(1))
            return r"([^/]+)"

        regex = re.sub(r":(\w+)", sub, pattern)
        self._routes.append(
            (method.upper(), re.compile(f"^{regex}$"), names, handler,
             pattern)
        )

    def routes(self) -> list[tuple[str, str, Handler]]:
        """(method, pattern, handler) triples — the coverage surface."""
        return [(m, pattern, handler)
                for m, _rx, _n, handler, pattern in self._routes]

    def get(self, pattern: str, handler: Handler) -> None:
        self.add("GET", pattern, handler)

    def post(self, pattern: str, handler: Handler) -> None:
        self.add("POST", pattern, handler)

    def put(self, pattern: str, handler: Handler) -> None:
        self.add("PUT", pattern, handler)

    def delete(self, pattern: str, handler: Handler) -> None:
        self.add("DELETE", pattern, handler)

    def match(
        self, method: str, path: str
    ) -> Optional[tuple[Handler, dict[str, str]]]:
        for m, regex, names, handler, _pattern in self._routes:
            if m != method.upper():
                continue
            match = regex.match(path)
            if match:
                return handler, dict(zip(names, match.groups()))
        return None
