"""Minimal pattern router (reference: src/server/router.ts — ':param'
patterns compiled to regex; handlers return an ApiResponse dict)."""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Any, Callable, Optional

# handler(ctx) -> {"status": int, "data": ..., "error": ...,
#                  "headers": {...}?}
Handler = Callable[["RequestContext"], dict]


class BadRequest(ValueError):
    """Request-parameter parsing failure — the CLIENT's fault, mapped
    to HTTP 400 by the server. Raised only by the RequestContext
    coercion helpers (and handlers validating client input); any other
    ValueError/TypeError escaping a handler is a server bug and
    surfaces as a logged 500 (ADVICE r5: the old blanket 400 hid real
    handler bugs from error visibility)."""


@dataclass
class RequestContext:
    method: str
    path: str
    params: dict[str, str]
    query: dict[str, str]
    body: Any
    principal: Optional[dict] = None  # {"role": agent|user|member}
    db: Any = None
    runtime: Any = None

    def int_param(self, name: str) -> int:
        """Path-parameter int coercion; a non-integer segment (e.g.
        /api/rooms/NaN) is a 400, never a 500."""
        try:
            return int(self.params[name])
        except (KeyError, ValueError, TypeError):
            raise BadRequest(
                f"path parameter {name!r} must be an integer, got "
                f"{self.params.get(name)!r}"
            ) from None

    def int_query(self, name: str, default: int) -> int:
        raw = self.query.get(name)
        if raw is None:
            return default
        try:
            return int(raw)
        except (ValueError, TypeError):
            raise BadRequest(
                f"query parameter {name!r} must be an integer, got "
                f"{raw!r}"
            ) from None

    def _body_number(self, name: str, default, caster, kind: str):
        body = self.body if isinstance(self.body, dict) else {}
        if name not in body:
            if default is not None:
                return default
            raise BadRequest(f"body field {name!r} is required")
        try:
            return caster(body[name])
        except (ValueError, TypeError):
            raise BadRequest(
                f"body field {name!r} must be {kind}, got "
                f"{body[name]!r}"
            ) from None

    def int_body(self, name: str, default: Optional[int] = None) -> int:
        """Body-field int coercion: malformed client JSON scalars are a
        400, never a logged 500."""
        return self._body_number(name, default, int, "an integer")

    def float_body(self, name: str,
                   default: Optional[float] = None) -> float:
        return self._body_number(name, default, float, "a number")


def ok(data: Any = None, status: int = 200) -> dict:
    return {"status": status, "data": data}


def err(error: str, status: int = 400) -> dict:
    return {"status": status, "error": error}


class Router:
    def __init__(self) -> None:
        self._routes: list[
            tuple[str, re.Pattern, list[str], Handler, str]
        ] = []

    def add(self, method: str, pattern: str, handler: Handler) -> None:
        names: list[str] = []

        def sub(m: re.Match) -> str:
            names.append(m.group(1))
            return r"([^/]+)"

        regex = re.sub(r":(\w+)", sub, pattern)
        self._routes.append(
            (method.upper(), re.compile(f"^{regex}$"), names, handler,
             pattern)
        )

    def routes(self) -> list[tuple[str, str, Handler]]:
        """(method, pattern, handler) triples — the coverage surface."""
        return [(m, pattern, handler)
                for m, _rx, _n, handler, pattern in self._routes]

    def get(self, pattern: str, handler: Handler) -> None:
        self.add("GET", pattern, handler)

    def post(self, pattern: str, handler: Handler) -> None:
        self.add("POST", pattern, handler)

    def put(self, pattern: str, handler: Handler) -> None:
        self.add("PUT", pattern, handler)

    def delete(self, pattern: str, handler: Handler) -> None:
        self.add("DELETE", pattern, handler)

    def match(
        self, method: str, path: str
    ) -> Optional[tuple[Handler, dict[str, str]]]:
        for m, regex, names, handler, _pattern in self._routes:
            if m != method.upper():
                continue
            match = regex.match(path)
            if match:
                return handler, dict(zip(names, match.groups()))
        return None
