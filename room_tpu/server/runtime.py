"""Server runtime loops (reference: src/server/runtime.ts): scheduler
tick (cron + due one-time tasks), maintenance sweep (stale runs/cycles),
queen inbox poll, with in-flight flags so a slow tick never stacks.

Thread-per-loop replaces node timers; all loops stop via one event.

Also the process-lifecycle authority (docs/lifecycle.md): boot consumes
the previous process's clean-shutdown marker (so journal recovery can
tell a rolling restart from a crash), the phase
(starting/warming/serving/draining) is exported to /api/tpu/health and
the TPU panel, and ``begin_graceful_drain`` runs the SIGTERM sequence —
engines flip to 503 admission, the swarm loops quiesce, engines spool
their sessions to the drain manifest, and the marker is written last."""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from datetime import datetime
from typing import Optional

from ..db import Database, utc_now
from ..core import journal as journal_mod
from ..core import messages as messages_mod
from ..core import rooms as rooms_mod
from ..core import task_runner
from ..core.agent_loop import (
    is_room_launched, reset_supervision, running_workers,
    set_room_launch_enabled, stop_room_loops, supervise_loops,
    trigger_agent,
)
from ..core.cron import cron_matches
from ..core.events import event_bus
from ..utils import locks

SCHEDULER_TICK_S = 15.0
MAINTENANCE_TICK_S = 60.0
INBOX_POLL_S = 2.5
SUPERVISION_TICK_S = 10.0
STALE_RUN_MINUTES = 120

# ---- process lifecycle (docs/lifecycle.md) ----
# starting -> warming (boot recovery running) -> serving -> draining.
# Module-global because exactly one server process owns the lifecycle;
# snapshotted by /api/tpu/health from route threads.
_lifecycle_lock = locks.make_lock("lifecycle")
_lifecycle = {
    "phase": "starting",
    "last_shutdown": None,      # clean | crash | first_boot
    "drain": None,              # per-model drain summaries, once drained
    "drain_started_at": None,
    "drain_ms": None,
}


def set_lifecycle_phase(phase: str) -> None:
    with _lifecycle_lock:
        _lifecycle["phase"] = phase


def lifecycle_snapshot() -> dict:
    with _lifecycle_lock:
        return dict(_lifecycle)


def note_drain_started() -> None:
    with _lifecycle_lock:
        _lifecycle["phase"] = "draining"
        _lifecycle["drain_started_at"] = time.time()


def note_drain_result(summaries: dict) -> None:
    with _lifecycle_lock:
        _lifecycle["drain"] = summaries
        started = _lifecycle.get("drain_started_at")
        if started:
            _lifecycle["drain_ms"] = round(
                (time.time() - started) * 1000.0, 1
            )
    event_bus.emit("lifecycle:drained", "runtime",
                   {"engines": list(summaries)})


def install_lifecycle_signal_handlers(graceful_stop) -> threading.Event:
    """SIGTERM/SIGINT → graceful drain (docs/lifecycle.md): admission
    flips to 503 + Retry-After, the decode window flushes, sessions
    spool to the drain manifest, the clean-shutdown marker lands last.
    Returns the event set once shutdown completes (the serve loop waits
    on it). A second signal during the drain restores the default
    disposition and re-raises itself — the drain deadline bounds the
    common exits, but an operator's repeated Ctrl-C (or a supervisor's
    escalating SIGTERM) must always be able to take the process down
    even if some drain step wedges past the deadline's reach."""
    import signal

    done = threading.Event()
    state = {"stopping": False}

    def handler(signum, frame):
        if state["stopping"]:
            signal.signal(signum, signal.SIG_DFL)
            signal.raise_signal(signum)
            return
        state["stopping"] = True
        try:
            graceful_stop()
        finally:
            done.set()

    signal.signal(signal.SIGINT, handler)
    signal.signal(signal.SIGTERM, handler)
    return done


@dataclass
class ServerRuntime:
    db: Database
    stop_event: threading.Event = field(default_factory=threading.Event)
    threads: list[threading.Thread] = field(default_factory=list)
    _pending_tasks: set[int] = field(default_factory=set)
    _pending_lock: threading.Lock = field(
        default_factory=lambda: locks.make_lock("runtime_pending")
    )
    _last_cron_minute: Optional[str] = None

    # ---- lifecycle ----

    indexer: Optional[object] = None
    watch_runtime: Optional[object] = None
    commentary: Optional[object] = None
    notifications: Optional[object] = None
    cloud: Optional[object] = None

    def start(self) -> None:
        set_lifecycle_phase("warming")
        # how did the last process die? The clean-shutdown marker
        # (written after a full graceful drain) distinguishes a rolling
        # restart from a crash; journal recovery below is idempotent
        # and runs either way, but after a clean drain it finds nothing
        # to repair.
        from ..serving import lifecycle as lifecycle_helpers

        last = lifecycle_helpers.consume_clean_marker()
        lifecycle_helpers.record_boot()
        with _lifecycle_lock:
            _lifecycle["last_shutdown"] = last
            # a same-process reboot (tests, embedders) must not report
            # the previous incarnation's drain summary as its own
            _lifecycle["drain"] = None
            _lifecycle["drain_started_at"] = None
            _lifecycle["drain_ms"] = None
        event_bus.emit("lifecycle:boot", "runtime",
                       {"last_shutdown": last})
        # crash recovery FIRST: resolve journal-open work to terminal
        # states (and flag committed side effects against replay)
        # before the stale sweep or the scheduler can touch it —
        # every swarm shard's journal when sharded
        for db, _dom in self._targets():
            journal_mod.recover(db)
        self.cleanup_stale(startup=True)
        self.scheduler_tick()
        from ..core.embedding_indexer import EmbeddingIndexer
        from ..core.watches import WatchRuntime
        from .cloud_sync import CloudSync
        from .commentary import CommentaryEngine
        from .notifications import NotificationEngine

        self.indexer = EmbeddingIndexer(self.db)
        self.indexer.start()
        # precompile the encoder's input buckets off the serving path so
        # the first cycles after boot don't stall on XLA compiles
        threading.Thread(
            target=self._warm_embedder, daemon=True,
            name="embed-warmup",
        ).start()
        self.watch_runtime = WatchRuntime(self.db)
        self.watch_runtime.start()
        self.commentary = CommentaryEngine(self.db)
        self.commentary.start()
        self.notifications = NotificationEngine(self.db)
        self.notifications.start()
        self.cloud = CloudSync(self.db)
        self.cloud.start()
        self._schedule_contact_checks()
        for target, interval in (
            (self.scheduler_tick, SCHEDULER_TICK_S),
            (self.maintenance_tick, MAINTENANCE_TICK_S),
            (self.inbox_poll, INBOX_POLL_S),
            (self.supervision_tick, SUPERVISION_TICK_S),
        ):
            t = threading.Thread(
                target=self._loop, args=(target, interval),
                daemon=True, name=f"runtime-{target.__name__}",
            )
            t.start()
            self.threads.append(t)
        set_lifecycle_phase("serving")

    def _schedule_contact_checks(self) -> None:
        """First-boot keeper contact checks at day 1 and day 7
        (reference: runtime.ts:217-242)."""
        from datetime import datetime, timedelta, timezone

        from ..core.messages import get_setting, set_setting
        from ..core.task_runner import create_task

        if get_setting(self.db, "contact_checks_scheduled"):
            return
        for days in (1, 7):
            at = (
                datetime.now(timezone.utc) + timedelta(days=days)
            ).strftime("%Y-%m-%dT%H:%M:%S.%f")[:-3] + "Z"
            create_task(
                self.db,
                name=f"keeper contact check (day {days})",
                prompt="verify the keeper has a reachable contact",
                trigger_type="once",
                scheduled_at=at,
                executor="keeper_contact_check",
            )
        set_setting(self.db, "contact_checks_scheduled", utc_now())

    def stop(self) -> None:
        self.stop_event.set()
        for aux in (self.indexer, self.watch_runtime, self.commentary,
                    self.notifications, self.cloud):
            if aux is not None:
                aux.stop()
        # shard children first: graceful drain (journaled in-flight
        # halves commit) + forced-kill sweep, BEFORE the generic
        # managed-process SIGTERM pass, so the parent never writes
        # its clean marker over children still holding shard files
        proc = self._swarm_proc()
        if proc is not None:
            try:
                proc.stop()
            except Exception as e:
                event_bus.emit("runtime:error", "runtime",
                               {"loop": "swarm_proc_stop",
                                "error": str(e)})
        from ..core.supervisor import terminate_managed_processes

        terminate_managed_processes()
        for t in self.threads:
            t.join(timeout=5)

    def _loop(self, tick, interval: float) -> None:
        busy = threading.Lock()
        while not self.stop_event.wait(timeout=interval):
            if not busy.acquire(blocking=False):
                continue  # previous tick still running
            try:
                tick()
            except Exception as e:
                event_bus.emit("runtime:error", "runtime",
                               {"loop": tick.__name__, "error": str(e)})
            finally:
                busy.release()

    # ---- swarm shards (docs/swarmshard.md) ----

    @staticmethod
    def _swarm():
        from ..swarm import maybe_default_router

        return maybe_default_router()

    @staticmethod
    def _swarm_proc():
        from ..swarm import maybe_default_proc

        return maybe_default_proc()

    def _targets(self) -> list:
        """The ``(db, domain)`` pairs every tick iterates. Unsharded
        that is the classic ``(self.db, None)`` (None = the default
        supervision domain); with ``ROOM_TPU_SWARM_SHARDS`` > 1 it is
        each serving shard's DB — plus the DBs it adopted from dead
        siblings — under that shard's own LoopDomain, so one shard's
        supervision verdicts never touch another's threads."""
        router = self._swarm()
        if router is None:
            return [(self.db, None)]
        out = []
        for shard in router.shards:
            if shard.state != "serving":
                continue
            out.append((shard.db, shard.domain))
            for adb in shard.adopted.values():
                out.append((adb, shard.domain))
        return out or [(self.db, None)]

    def _room_target(self, room_id: int):
        """(db, domain) owning one room — placement-map routed when
        sharded (raises ShardDownError during a dead shard's lease)."""
        router = self._swarm()
        if router is None:
            return self.db, None
        shard = router.shard_for(room_id)
        return router.db_for(room_id), shard.domain

    # ---- ticks ----

    def scheduler_tick(self) -> None:
        now = datetime.now()
        minute_key = now.strftime("%Y-%m-%dT%H:%M")
        fire_cron = minute_key != self._last_cron_minute
        if fire_cron:
            self._last_cron_minute = minute_key

        for db, _dom in self._targets():
            if fire_cron:
                for task in db.query(
                    "SELECT * FROM tasks WHERE status='active' AND "
                    "trigger_type='cron' AND cron_expression IS NOT NULL"
                ):
                    try:
                        due = cron_matches(task["cron_expression"], now)
                    except Exception:
                        continue
                    if due:
                        self.queue_task_execution(task["id"], db=db)

            for task in db.query(
                "SELECT * FROM tasks WHERE status='active' AND "
                "trigger_type='once' AND scheduled_at IS NOT NULL AND "
                "scheduled_at <= ?",
                (utc_now(),),
            ):
                # archiving happens in _finish_run, after the run
                # completes; archiving here would race the worker's
                # active-status check
                self.queue_task_execution(task["id"], db=db)

    def maintenance_tick(self) -> None:
        self.cleanup_stale()
        for db, _dom in self._targets():
            journal_mod.prune(db)

    def supervision_tick(self) -> None:
        """Restart dead/hung agent-loop threads under budget; past
        budget the worker goes unhealthy + keeper-escalated
        (docs/swarm_recovery.md). Sharded, each shard's domain is
        supervised separately and the swarm router runs its own
        supervise pass (shard_crash fault + dead-shard adoption)."""
        router = self._swarm()
        if router is not None:
            router.supervise()
        proc = self._swarm_proc()
        if proc is not None:
            # process mode: heartbeat detector + restart budget +
            # sibling adoption run on the parent's supervision cadence
            proc.supervise()
        for db, dom in self._targets():
            supervise_loops(db, domain=dom)

    def inbox_poll(self) -> None:
        """Unanswered keeper chat wakes the room's queen (reference:
        runtime.ts:47-61)."""
        for db, dom in self._targets():
            for room in rooms_mod.list_rooms(db, status="active"):
                if not is_room_launched(room["id"], domain=dom):
                    continue
                if not room["queen_worker_id"]:
                    continue
                if messages_mod.unanswered_keeper_messages(
                    db, room["id"]
                ):
                    trigger_agent(
                        db, room["id"], room["queen_worker_id"],
                        domain=dom,
                    )

    # ---- operations ----

    def queue_task_execution(
        self, task_id: int, db: Optional[Database] = None,
    ) -> bool:
        """Dedupe + background execution (reference:
        queueTaskExecution:96-150). Shard ID striding keeps task ids
        globally unique, so one pending set covers every shard."""
        if db is None:
            db = self._find_task_db(task_id)
        with self._pending_lock:
            if task_id in self._pending_tasks:
                return False
            self._pending_tasks.add(task_id)

        def run() -> None:
            try:
                task_runner.execute_task(
                    db, task_id, abort=self.stop_event
                )
            finally:
                with self._pending_lock:
                    self._pending_tasks.discard(task_id)

        threading.Thread(
            target=run, daemon=True, name=f"task-{task_id}"
        ).start()
        return True

    def _find_task_db(self, task_id: int) -> Database:
        """Shard holding a task (API callers pass only the id)."""
        for db, _dom in self._targets():
            if db.query_one(
                "SELECT id FROM tasks WHERE id=?", (task_id,)
            ):
                return db
        return self.db

    def run_task_now(self, task_id: int) -> bool:
        return self.queue_task_execution(task_id)

    def start_room(self, room_id: int) -> bool:
        """POST /rooms/:id/start semantics (reference:
        routes/rooms.ts:336-359): enable launch, reset runtime, cold-start
        the queen."""
        db, dom = self._room_target(room_id)
        room = rooms_mod.get_room(db, room_id)
        if room is None or not room["queen_worker_id"]:
            return False
        rooms_mod.restart_room(db, room_id)
        # a deliberate keeper restart re-arms the loop restart budget
        # and clears unhealthy flags for the room's workers
        team = db.query(
            "SELECT id FROM workers WHERE room_id=?", (room_id,)
        )
        reset_supervision([w["id"] for w in team], domain=dom)
        db.execute(
            "UPDATE workers SET agent_state='idle', updated_at=? "
            "WHERE room_id=? AND agent_state='unhealthy'",
            (utc_now(), room_id),
        )
        set_room_launch_enabled(room_id, True, domain=dom)
        stop_room_loops(db, room_id, "runtime reset", domain=dom)
        trigger_agent(
            db, room_id, room["queen_worker_id"],
            allow_cold_start=True, domain=dom,
        )
        event_bus.emit("room:started", f"room:{room_id}", {})
        return True

    def stop_room(self, room_id: int) -> int:
        db, dom = self._room_target(room_id)
        n = stop_room_loops(db, room_id, "stopped by keeper",
                            domain=dom)
        task_runner.cancel_running_tasks_for_room(db, room_id)
        event_bus.emit("room:stopped", f"room:{room_id}", {})
        return n

    def _warm_embedder(self) -> None:
        try:
            from ..serving.embed_service import get_embed_host

            get_embed_host().warmup()
        except Exception:
            pass  # warmup is best-effort; first embed still compiles

    def cleanup_stale(self, startup: bool = False) -> int:
        """Mark long-running/orphaned runs and cycles failed (reference:
        db-queries.ts:544-573, runtime.ts:336), and reset workers
        stranded mid-state by a crash. Crash-interrupted work with a
        journal entry is resolved immediately by journal recovery; this
        sweep catches whatever predates the journal."""
        n = 0
        cutoff = f"-{STALE_RUN_MINUTES} minutes"
        for db, dom in self._targets():
            for table, col in (("task_runs", "started_at"),
                               ("worker_cycles", "started_at")):
                cur = db.execute(
                    f"UPDATE {table} SET status='error', "
                    "error_message='stale: abandoned run', "
                    "finished_at=? "
                    f"WHERE status='running' AND ({col} < "
                    "strftime('%Y-%m-%dT%H:%M:%fZ','now', ?) OR ?)",
                    (utc_now(), cutoff, 1 if startup else 0),
                )
                n += cur.rowcount
            # workers stuck in 'running'/'rate_limited' with no loop
            # thread behind them: at startup no loop exists yet, so
            # reset them all; during operation only those whose loop is
            # gone (a live loop legitimately holds these states for the
            # whole backoff window)
            live = set() if startup else \
                set(running_workers(domain=dom))
            stranded = db.query(
                "SELECT id FROM workers WHERE agent_state IN "
                "('running','rate_limited')"
            )
            for w in stranded:
                if w["id"] in live:
                    continue
                db.execute(
                    "UPDATE workers SET agent_state='idle', "
                    "updated_at=? WHERE id=?",
                    (utc_now(), w["id"]),
                )
                n += 1
        return n


_runtime: Optional[ServerRuntime] = None


def start_server_runtime(db: Database) -> ServerRuntime:
    global _runtime
    if _runtime is not None:
        return _runtime
    _runtime = ServerRuntime(db=db)
    _runtime.start()
    return _runtime


def get_server_runtime() -> Optional[ServerRuntime]:
    return _runtime


def stop_server_runtime() -> None:
    global _runtime
    if _runtime is not None:
        _runtime.stop()
        _runtime = None
