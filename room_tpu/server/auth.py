"""Authentication (reference: src/server/auth.ts): dual persistent
bearer tokens — agent (full) and user (keeper) — stored 0600; timing-safe
comparison; optional cloud-mode HS256 JWT validation mapping to the
member role; origin allow-lists for local vs cloud deployments."""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import os
import secrets
import time
from typing import Optional
from ..utils import knobs

TOKENS_FILE = "auth.tokens.json"


def data_dir() -> str:
    d = os.path.expanduser(knobs.get_str("ROOM_TPU_DATA_DIR"))
    os.makedirs(d, exist_ok=True)
    return d


def _tokens_path() -> str:
    return os.path.join(data_dir(), TOKENS_FILE)


def load_or_create_tokens() -> dict[str, str]:
    path = _tokens_path()
    if os.path.exists(path):
        try:
            with open(path) as f:
                tokens = json.load(f)
            if tokens.get("agent") and tokens.get("user"):
                return tokens
        except (json.JSONDecodeError, OSError):
            pass
    tokens = {
        "agent": secrets.token_urlsafe(32),
        "user": secrets.token_urlsafe(32),
    }
    fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
    with os.fdopen(fd, "w") as f:
        json.dump(tokens, f)
    return tokens


def write_runtime_files(port: int, tokens: dict[str, str]) -> None:
    """api.port / api.token files other processes (MCP nudge) read
    (reference: writeTokenFile:275, mcp/nudge.ts:14-43)."""
    d = data_dir()
    with open(os.path.join(d, "api.port"), "w") as f:
        f.write(str(port))
    fd = os.open(
        os.path.join(d, "api.token"),
        os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600,
    )
    with os.fdopen(fd, "w") as f:
        f.write(tokens["agent"])


def _safe_equal(a: str, b: str) -> bool:
    return hmac.compare_digest(a.encode(), b.encode())


def get_token_principal(
    token: Optional[str], tokens: dict[str, str]
) -> Optional[dict]:
    """token -> {"role": ...} or None. Roles: agent (full), user
    (keeper full), member (cloud read-mostly)."""
    if not token:
        return None
    if _safe_equal(token, tokens["agent"]):
        return {"role": "agent"}
    if _safe_equal(token, tokens["user"]):
        return {"role": "user"}
    principal = validate_cloud_jwt(token)
    if principal:
        return principal
    return None


# ---- cloud JWT (HS256, stdlib) ----

JWT_ISS = "room-tpu-cloud"
JWT_AUD = "room-tpu-runtime"


def _b64url_decode(s: str) -> bytes:
    return base64.urlsafe_b64decode(s + "=" * (-len(s) % 4))


def _b64url_encode(b: bytes) -> str:
    return base64.urlsafe_b64encode(b).rstrip(b"=").decode()


def sign_cloud_jwt(
    claims: dict, secret: str, header: Optional[dict] = None
) -> str:
    header = header or {"alg": "HS256", "typ": "JWT"}
    signing = (
        _b64url_encode(json.dumps(header).encode())
        + "."
        + _b64url_encode(json.dumps(claims).encode())
    )
    sig = hmac.new(
        secret.encode(), signing.encode(), hashlib.sha256
    ).digest()
    return signing + "." + _b64url_encode(sig)


def validate_cloud_jwt(token: str) -> Optional[dict]:
    """Validate iss/aud/exp/nbf + instance binding against the deployment
    secret (reference: validateCloudJwt:106-165). Returns a member/user
    principal or None."""
    secret = knobs.get_str("ROOM_TPU_CLOUD_JWT_SECRET")
    if not secret or token.count(".") != 2:
        return None
    head_s, claims_s, sig_s = token.split(".")
    try:
        header = json.loads(_b64url_decode(head_s))
        claims = json.loads(_b64url_decode(claims_s))
        sig = _b64url_decode(sig_s)
    except (ValueError, json.JSONDecodeError):
        return None
    if header.get("alg") != "HS256":
        return None
    expected = hmac.new(
        secret.encode(), f"{head_s}.{claims_s}".encode(), hashlib.sha256
    ).digest()
    if not hmac.compare_digest(sig, expected):
        return None

    now = time.time()
    if claims.get("iss") != JWT_ISS or claims.get("aud") != JWT_AUD:
        return None
    # exp is mandatory: a token without one would never expire
    exp = claims.get("exp")
    if not isinstance(exp, (int, float)) or now >= float(exp):
        return None
    if claims.get("nbf") is not None and now < float(claims["nbf"]):
        return None
    # a principal must have an identity for auditing (reference checks
    # sub is a non-empty string); tokens minted before this check existed
    # are deliberately invalidated — reissue via POST /api/invites
    sub = claims.get("sub")
    if not isinstance(sub, str) or not sub:
        return None
    instance = knobs.get_str("ROOM_TPU_INSTANCE_ID")
    if instance and claims.get("instanceId") != instance:
        return None
    role = claims.get("role", "member")
    return {"role": role if role in ("user", "member") else "member",
            "claims": claims}


# ---- origins ----

def allowed_origin(origin: Optional[str], port: int) -> bool:
    if not origin:
        return True  # same-origin / non-browser
    local = {
        f"http://localhost:{port}",
        f"http://127.0.0.1:{port}",
    }
    if origin in local:
        return True
    extra = knobs.get_str("ROOM_TPU_ALLOWED_ORIGINS")
    return origin in {o.strip() for o in extra.split(",") if o.strip()}
