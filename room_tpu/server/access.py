"""Role-based access control (reference: src/server/access.ts):
agent/user get everything; member is read-only plus a small write
whitelist (votes, keeper votes, escalation resolution, message replies,
mark-read)."""

from __future__ import annotations

import re

# keyed on "METHOD path" (reference keys `${method} ${pathname}`) so a
# future PUT/DELETE at a whitelisted path doesn't silently become
# member-writable
MEMBER_WRITE_WHITELIST = [
    re.compile(p)
    for p in (
        r"^POST /api/decisions/\d+/vote$",
        r"^POST /api/decisions/\d+/keeper-vote$",
        r"^POST /api/escalations/\d+/answer$",
        r"^POST /api/messages/\d+/reply$",
        r"^POST /api/messages/\d+/read$",
    )
]

MEMBER_READ_BLOCKLIST = [
    re.compile(p)
    for p in (
        r"^/api/credentials",      # secret material stays hidden
        r"^/api/rooms/\d+/credentials",
    )
]


def is_allowed_for_role(role: str, method: str, path: str) -> bool:
    if role in ("agent", "user"):
        return True
    if role != "member":
        return False
    if method in ("GET", "HEAD"):
        return not any(p.match(path) for p in MEMBER_READ_BLOCKLIST)
    key = f"{method} {path}"
    return any(p.match(key) for p in MEMBER_WRITE_WHITELIST)
