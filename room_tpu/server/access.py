"""Role-based access control (reference: src/server/access.ts):
agent/user get everything; member is read-only plus a small write
whitelist (votes, keeper votes, escalation resolution, message replies,
mark-read)."""

from __future__ import annotations

import re

MEMBER_WRITE_WHITELIST = [
    re.compile(p)
    for p in (
        r"^/api/decisions/\d+/vote$",
        r"^/api/decisions/\d+/keeper-vote$",
        r"^/api/escalations/\d+/answer$",
        r"^/api/messages/\d+/reply$",
        r"^/api/messages/\d+/read$",
    )
]

MEMBER_READ_BLOCKLIST = [
    re.compile(p)
    for p in (
        r"^/api/credentials",      # secret material stays hidden
        r"^/api/rooms/\d+/credentials",
    )
]


def is_allowed_for_role(role: str, method: str, path: str) -> bool:
    if role in ("agent", "user"):
        return True
    if role != "member":
        return False
    if method in ("GET", "HEAD"):
        return not any(p.match(path) for p in MEMBER_READ_BLOCKLIST)
    return any(p.match(path) for p in MEMBER_WRITE_WHITELIST)
