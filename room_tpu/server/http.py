"""HTTP API server (reference: src/server/index.ts): CORS/origin
validation, localhost-only auth handshake, tokened webhooks before auth,
bearer auth + RBAC + router dispatch, security headers, rate limiting for
cloud deployments, SPA static serving with a traversal guard, graceful
shutdown — on a threading stdlib server instead of node:http."""

from __future__ import annotations

import json
import mimetypes
import os
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Optional

from ..db import Database
from .access import is_allowed_for_role
from .auth import (
    allowed_origin, get_token_principal, load_or_create_tokens,
    write_runtime_files,
)
from .router import BadRequest, RequestContext, Router
from .routes import register_all_routes
from .webhooks import handle_webhook_request
from .ws import WebSocketHub
from ..utils import knobs, locks

RATE_LIMIT_GET_PER_MIN = 300
RATE_LIMIT_WRITE_PER_MIN = 120


class _RateLimiter:
    def __init__(self) -> None:
        self._hits: dict[tuple[str, str], list[float]] = {}
        self._lock = locks.make_lock("http_rate_limiter")

    def allow(self, ip: str, kind: str, limit: int) -> bool:
        now = time.monotonic()
        key = (ip, kind)
        with self._lock:
            hits = [t for t in self._hits.get(key, []) if now - t < 60]
            if len(hits) >= limit:
                self._hits[key] = hits
                return False
            hits.append(now)
            self._hits[key] = hits
            return True


class ApiServer:
    def __init__(
        self,
        db: Database,
        runtime=None,
        host: str = "127.0.0.1",
        port: int = 0,
        static_dir: Optional[str] = None,
        cloud_mode: bool = False,
    ) -> None:
        self.db = db
        self.runtime = runtime
        self.router = Router()
        register_all_routes(self.router)
        self.tokens = load_or_create_tokens()
        self.static_dir = static_dir
        self.cloud_mode = cloud_mode
        self.rate_limiter = _RateLimiter()
        self.ws_hub = WebSocketHub(self)

        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # quiet
                pass

            def _drain_unread_body(self) -> None:
                """Keep-alive framing: early-exit responses (403 origin,
                401, 429, 404...) must consume the request body, or the
                leftover bytes get parsed as the next request on the
                persistent connection."""
                if getattr(self, "_body_consumed", False):
                    return
                self._body_consumed = True
                try:
                    remaining = int(
                        self.headers.get("Content-Length") or 0
                    )
                except ValueError:
                    self.close_connection = True
                    return
                if remaining > 5_000_000:
                    # don't let an unauthenticated client stream GBs at a
                    # rejection response; drop the connection instead
                    self.close_connection = True
                    return
                while remaining > 0:
                    chunk = self.rfile.read(min(remaining, 65536))
                    if not chunk:
                        break
                    remaining -= len(chunk)

            def _respond(self, status: int, payload: dict,
                         headers: Optional[dict] = None) -> None:
                # /v1 (OpenAI-compatible) errors use the OpenAI error
                # object with a type SDK retry logic understands — this
                # covers pre-handler rejections (401/403/404/429) and
                # the catch-all 500 too
                if self.path.startswith("/v1/") and isinstance(
                    payload.get("error"), str
                ):
                    etype = (
                        "overloaded_error" if status == 503
                        else "server_error" if status >= 500
                        else "rate_limit_error" if status == 429
                        else "authentication_error" if status == 401
                        else "permission_error" if status == 403
                        else "invalid_request_error"
                    )
                    payload = {"error": {
                        "message": payload["error"], "type": etype,
                    }}
                self._drain_unread_body()
                body = json.dumps(payload).encode()
                self.send_response(status)
                self._common_headers()
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, str(v))
                self.end_headers()
                self.wfile.write(body)

            def _respond_text(self, status: int, body: str,
                              content_type: str = "text/plain") -> None:
                """Plain-text response (the Prometheus exposition —
                JSON envelopes would break scrapers)."""
                self._drain_unread_body()
                raw = body.encode("utf-8")
                self.send_response(status)
                self._common_headers()
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(raw)))
                self.end_headers()
                self.wfile.write(raw)

            def _common_headers(self) -> None:
                origin = self.headers.get("Origin")
                if origin and allowed_origin(origin, server.port):
                    self.send_header("Access-Control-Allow-Origin", origin)
                    self.send_header(
                        "Access-Control-Allow-Headers",
                        "Authorization, Content-Type",
                    )
                    self.send_header(
                        "Access-Control-Allow-Methods",
                        "GET, POST, PUT, DELETE, OPTIONS",
                    )
                self.send_header("X-Content-Type-Options", "nosniff")
                self.send_header("X-Frame-Options", "DENY")
                self.send_header("Referrer-Policy", "no-referrer")

            def do_OPTIONS(self):
                self.send_response(204)
                self._common_headers()
                self.send_header("Content-Length", "0")
                self.end_headers()

            def do_GET(self):
                if self.headers.get("Upgrade", "").lower() == "websocket":
                    server.ws_hub.handle_upgrade(self)
                    return
                self._handle()

            def do_POST(self):
                self._handle()

            def do_PUT(self):
                self._handle()

            def do_DELETE(self):
                self._handle()

            # ---- core dispatch ----

            def _client_ip(self) -> str:
                return self.client_address[0]

            def _is_localhost(self) -> bool:
                return self._client_ip() in ("127.0.0.1", "::1")

            def _read_body(self) -> Any:
                self._body_consumed = True
                try:
                    length = int(
                        self.headers.get("Content-Length") or 0
                    )
                except ValueError:
                    self.close_connection = True
                    return None
                if length <= 0:
                    return None
                if length > 5_000_000:
                    # drain so the keep-alive stream stays framed
                    remaining = length
                    while remaining > 0:
                        chunk = self.rfile.read(min(remaining, 65536))
                        if not chunk:
                            break
                        remaining -= len(chunk)
                    return None
                raw = self.rfile.read(length)
                try:
                    return json.loads(raw)
                except json.JSONDecodeError:
                    return None

            def _handle(self) -> None:
                from ..utils.profiling import (
                    http_profiler, http_profiling_enabled,
                )

                t0 = time.perf_counter()
                self._body_consumed = False
                try:
                    self._handle_inner()
                except BrokenPipeError:
                    pass
                except BadRequest as e:
                    # request-PARAMETER parsing failures only (the
                    # RequestContext coercion helpers): the client's
                    # fault, 400. Any other ValueError/TypeError from
                    # a handler is a server bug and falls through to
                    # the logged 500 below (ADVICE r5: the old blanket
                    # 400 hid real handler bugs)
                    try:
                        self._respond(400, {"error": f"bad request: {e}"})
                    except Exception:
                        pass
                except Exception as e:
                    import sys
                    import traceback

                    print(
                        f"[http] 500 on {self.command} {self.path}: "
                        f"{type(e).__name__}: {e}\n"
                        + traceback.format_exc(),
                        file=sys.stderr,
                    )
                    try:
                        self._respond(500, {"error": str(e)})
                    except Exception:
                        pass
                finally:
                    if http_profiling_enabled():
                        http_profiler.record(
                            self.command, self.path.split("?")[0],
                            (time.perf_counter() - t0) * 1000,
                        )

            def _handle_inner(self) -> None:
                parsed = urllib.parse.urlparse(self.path)
                path = parsed.path
                query = {
                    k: v[0]
                    for k, v in urllib.parse.parse_qs(parsed.query).items()
                }
                origin = self.headers.get("Origin")
                if origin and not allowed_origin(origin, server.port):
                    self._respond(403, {"error": "origin not allowed"})
                    return

                # localhost-only auth handshake (reference :504-522)
                if path == "/api/auth/handshake":
                    if not self._is_localhost():
                        self._respond(403, {"error": "localhost only"})
                        return
                    self._respond(200, {
                        "status": 200,
                        "data": {"userToken": server.tokens["user"]},
                    })
                    return

                # localhost-only restart endpoints, before auth
                # (reference index.ts:526-576)
                if path in ("/api/server/restart",
                            "/api/server/update-restart") and \
                        self.command == "POST":
                    from .updater import (
                        get_ready_update_version,
                        promote_staged_update, schedule_self_restart,
                    )

                    if not self._is_localhost():
                        self._respond(403, {
                            "error": "restart allowed only from "
                                     "localhost clients"
                        })
                        return
                    payload = {"ok": True, "restarting": True}
                    if path.endswith("update-restart"):
                        version = get_ready_update_version()
                        if not version:
                            self._respond(404, {
                                "error": "no update ready to apply"
                            })
                            return
                        promote_staged_update()
                        payload["version"] = version
                    if not schedule_self_restart():
                        self._respond(500, {
                            "error": "failed to schedule restart"
                        })
                        return
                    self._respond(202, payload)
                    return

                # tokened webhooks, before auth (reference :602-608)
                if path.startswith("/api/hooks/"):
                    self._respond(*handle_webhook_request(
                        server, self.command, path, self._read_body()
                    ))
                    return

                # Prometheus exposition, before auth (scrapers on a
                # private network don't carry bearer tokens; the
                # payload is operational counters only —
                # docs/observability.md). ROOM_TPU_METRICS=0 disables.
                if path == "/metrics" and self.command == "GET":
                    from .metrics import (
                        CONTENT_TYPE, metrics_enabled, render_metrics,
                    )

                    if not metrics_enabled():
                        self._respond(404, {"error": "not found"})
                        return
                    self._respond_text(
                        200, render_metrics(), CONTENT_TYPE
                    )
                    return

                if not path.startswith(("/api/", "/v1/")):
                    self._serve_static(path)
                    return

                # cloud rate limiting (reference :384-415)
                if server.cloud_mode:
                    kind = "r" if self.command in ("GET", "HEAD") else "w"
                    limit = (
                        RATE_LIMIT_GET_PER_MIN if kind == "r"
                        else RATE_LIMIT_WRITE_PER_MIN
                    )
                    if not server.rate_limiter.allow(
                        self._client_ip(), kind, limit
                    ):
                        self._respond(429, {"error": "rate limited"})
                        return

                auth = self.headers.get("Authorization", "")
                token = auth[7:] if auth.startswith("Bearer ") else None
                principal = get_token_principal(token, server.tokens)
                if principal is None:
                    self._respond(401, {"error": "unauthorized"})
                    return
                if not is_allowed_for_role(
                    principal["role"], self.command, path
                ):
                    self._respond(403, {"error": "forbidden"})
                    return

                matched = server.router.match(self.command, path)
                if matched is None:
                    self._respond(404, {"error": "not found"})
                    return
                handler, params = matched
                ctx = RequestContext(
                    method=self.command,
                    path=path,
                    params=params,
                    query=query,
                    body=self._read_body(),
                    principal=principal,
                    db=server.db,
                    runtime=server.runtime,
                )
                out = handler(ctx)
                status = out.get("status", 200)
                extra_headers = out.get("headers")
                if "sse" in out:
                    self._respond_sse(status, out["sse"])
                    return
                if path.startswith("/v1/"):
                    # OpenAI wire shapes, not the internal envelope
                    # (_respond converts string errors to the OpenAI
                    # error object, typed by status)
                    if out.get("error"):
                        payload = {"error": out["error"]}
                    else:
                        payload = out.get("data", {})
                    self._respond(status, payload, extra_headers)
                    return
                payload = {"status": status}
                if "data" in out:
                    payload["data"] = out["data"]
                if out.get("error"):
                    payload["error"] = out["error"]
                self._respond(status, payload, extra_headers)

            def _respond_sse(self, status: int, events) -> None:
                """Server-sent events (OpenAI streaming): no
                Content-Length, connection closes to delimit."""
                self._drain_unread_body()
                self.send_response(status)
                self._common_headers()
                self.send_header("Content-Type", "text/event-stream")
                self.send_header("Cache-Control", "no-cache")
                self.send_header("Connection", "close")
                self.close_connection = True
                self.end_headers()
                try:
                    for item in events:
                        data = item if isinstance(item, str) \
                            else json.dumps(item)
                        self.wfile.write(f"data: {data}\n\n".encode())
                        self.wfile.flush()
                except BrokenPipeError:
                    pass

            def _serve_static(self, path: str) -> None:
                """SPA static serving with traversal guard (reference
                serveStatic:322-368)."""
                root = server.static_dir
                if not root:
                    self._respond(404, {"error": "not found"})
                    return
                rel = path.lstrip("/") or "index.html"
                real_root = os.path.realpath(root)
                full = os.path.realpath(os.path.join(real_root, rel))
                if full != real_root and not full.startswith(
                    real_root + os.sep
                ):
                    self._respond(403, {"error": "forbidden"})
                    return
                if not os.path.isfile(full):
                    full = os.path.join(root, "index.html")  # SPA routes
                    if not os.path.isfile(full):
                        self._respond(404, {"error": "not found"})
                        return
                if full.endswith((".js", ".mjs")):
                    # pinned: host mimetypes DBs disagree
                    # (application/javascript vs the WHATWG-standard
                    # text/javascript) across distros
                    ctype = "text/javascript"
                else:
                    ctype = mimetypes.guess_type(full)[0] or \
                        "application/octet-stream"
                with open(full, "rb") as f:
                    body = f.read()
                self._drain_unread_body()
                self.send_response(200)
                self._common_headers()
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._handler_cls = Handler
        bind_host = knobs.get_str("ROOM_TPU_BIND_HOST", default=host)
        # explicit-port conflicts reclaim the port from a stale
        # instance, kill-and-retry up to 3 times (reference:
        # index.ts:944-962)
        for attempt in range(3):
            try:
                self._httpd = ThreadingHTTPServer(
                    (bind_host, port), Handler
                )
                break
            except OSError as e:
                if port == 0 or attempt == 2 or \
                        getattr(e, "errno", None) not in (48, 98):
                    raise
                from .shell_path import kill_process_listening_on

                kill_process_listening_on(port)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        write_runtime_files(self.port, self.tokens)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="api-server",
        )
        self._thread.start()
        self.ws_hub.start()

    def stop(self) -> None:
        self.ws_hub.stop()
        if self._thread is not None:
            # shutdown() handshakes with serve_forever and deadlocks
            # if the serve loop never started
            self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)
