"""Cloud sync (reference: src/shared/cloud-sync.ts + runtime.ts:290-329):
optional registration + heartbeats to a cloud endpoint, per-room tokens
persisted 0600, and an inter-room message relay so rooms on different
machines can talk. Every network failure is silent; nothing here is on
any critical path."""

from __future__ import annotations

import json
import os
import threading
import urllib.request
from typing import Optional

from ..core import messages as messages_mod
from ..core.telemetry import get_machine_id
from ..db import Database
from ..utils import knobs

HEARTBEAT_S = 5 * 60.0
MESSAGE_SYNC_S = 60.0
TOKENS_FILE = "cloud-room-tokens.json"


def cloud_api_base() -> Optional[str]:
    return knobs.get_str("ROOM_TPU_CLOUD_API")


def _tokens_path() -> str:
    from .auth import data_dir

    return os.path.join(data_dir(), TOKENS_FILE)


def _load_tokens() -> dict[str, str]:
    try:
        with open(_tokens_path()) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return {}


def _save_tokens(tokens: dict[str, str]) -> None:
    fd = os.open(
        _tokens_path(), os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600
    )
    with os.fdopen(fd, "w") as f:
        json.dump(tokens, f)


def _post(path: str, payload: dict, token: Optional[str] = None
          ) -> Optional[dict]:
    base = cloud_api_base()
    if not base:
        return None
    headers = {"Content-Type": "application/json"}
    if token:
        headers["Authorization"] = f"Bearer {token}"
    try:
        req = urllib.request.Request(
            base.rstrip("/") + path,
            data=json.dumps(payload).encode(),
            headers=headers,
        )
        with urllib.request.urlopen(req, timeout=10) as resp:
            return json.loads(resp.read())
    except (OSError, json.JSONDecodeError):
        return None  # all cloud failures are silent


def ensure_cloud_room_token(db: Database, room_id: int) -> Optional[str]:
    tokens = _load_tokens()
    key = str(room_id)
    if key in tokens:
        return tokens[key]
    room = db.query_one("SELECT * FROM rooms WHERE id=?", (room_id,))
    if room is None:
        return None
    out = _post("/rooms/register", {
        "machine": get_machine_id(),
        "roomId": room_id,
        "name": room["name"],
        "visibility": room["visibility"],
    })
    if not out or "token" not in out:
        return None
    tokens[key] = out["token"]
    _save_tokens(tokens)
    return out["token"]


def send_heartbeat(db: Database, room_id: int) -> bool:
    token = ensure_cloud_room_token(db, room_id)
    if not token:
        return False
    return _post(
        "/rooms/heartbeat", {"roomId": room_id}, token
    ) is not None


def sync_cloud_messages(db: Database) -> int:
    """Push queued outbound messages to remote rooms; pull inbound.
    Returns how many messages moved."""
    moved = 0
    for room in db.query(
        "SELECT id FROM rooms WHERE visibility='public'"
    ):
        token = ensure_cloud_room_token(db, room["id"])
        if not token:
            continue
        out = _post("/rooms/messages/pull", {"roomId": room["id"]}, token)
        for msg in (out or {}).get("messages", []):
            messages_mod.receive_external_message(
                db, room["id"], str(msg.get("fromRoomId", "cloud")),
                msg.get("subject", ""), msg.get("body", ""),
            )
            moved += 1
    return moved


class CloudSync:
    def __init__(self, db: Database) -> None:
        self.db = db
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []

    @property
    def enabled(self) -> bool:
        return cloud_api_base() is not None

    def start(self) -> None:
        if not self.enabled:
            return

        def heartbeat_loop():
            while not self._stop.wait(timeout=HEARTBEAT_S):
                try:
                    for room in self.db.query(
                        "SELECT id FROM rooms WHERE status='active'"
                    ):
                        send_heartbeat(self.db, room["id"])
                except Exception:
                    pass  # transient DB error must not kill heartbeats

        def message_loop():
            while not self._stop.wait(timeout=MESSAGE_SYNC_S):
                try:
                    sync_cloud_messages(self.db)
                except Exception:
                    pass

        for fn in (heartbeat_loop, message_loop):
            t = threading.Thread(target=fn, daemon=True,
                                 name=f"cloud-{fn.__name__}")
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=5)
