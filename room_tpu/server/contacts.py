"""Keeper contact channels: email + Telegram verification state
machines, and clerk-originated email sending (reference:
src/server/routes/contacts.ts — code issuance with HMAC'd 6-digit
codes, TTL/resend-cooldown/hourly rate window, telegram deep-link token
flow; src/server/keeper-email.ts — clerk sends email and records it as
a clerk message).

State lives in the settings table under the same keys the reference
uses, so the status endpoint is a pure read. Delivery is transport-
pluggable: a file outbox (ROOM_TPU_EMAIL_OUTBOX — also the test seam),
SMTP (ROOM_TPU_SMTP_HOST/PORT/USER/PASS/FROM), or the cloud relay; all
absent -> ApiError 502 fail-closed like the reference's cloud misses.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import os
import re
import secrets
import time
from typing import Optional

from ..core.messages import get_setting, set_setting
from ..db import Database
from ..utils import knobs

EMAIL_CODE_TTL_MIN = 15
EMAIL_RESEND_COOLDOWN_S = 60
EMAIL_MAX_SENDS_PER_HOUR = 6
TELEGRAM_TTL_MIN = 20
DEFAULT_TELEGRAM_BOT = "room_tpu_bot"

K_EMAIL = "contact_email"
K_EMAIL_VERIFIED_AT = "contact_email_verified_at"
K_EMAIL_CODE_HASH = "contact_email_verify_code_hash"
K_EMAIL_CODE_EXPIRES = "contact_email_verify_code_expires_at"
K_EMAIL_LAST_SENT = "contact_email_verify_last_sent_at"
K_EMAIL_RATE_START = "contact_email_verify_rate_window_start"
K_EMAIL_RATE_COUNT = "contact_email_verify_rate_window_count"
K_TG_ID = "contact_telegram_id"
K_TG_USERNAME = "contact_telegram_username"
K_TG_FIRST_NAME = "contact_telegram_first_name"
K_TG_VERIFIED_AT = "contact_telegram_verified_at"
K_TG_PENDING_HASH = "contact_telegram_pending_hash"
K_TG_PENDING_EXPIRES = "contact_telegram_pending_expires_at"
K_TG_BOT = "contact_telegram_bot_username"

_EMAIL_RE = re.compile(r"^[^@\s]+@[^@\s]+\.[^@\s]+$")


class ApiError(RuntimeError):
    def __init__(self, message: str, status: int = 400,
                 retry_after_s: Optional[int] = None) -> None:
        super().__init__(message)
        self.status = status
        self.retry_after_s = retry_after_s


def _get(db: Database, key: str) -> str:
    return (get_setting(db, key) or "").strip()


def _clear(db: Database, key: str) -> None:
    set_setting(db, key, "")


def is_valid_email(email: str) -> bool:
    return bool(_EMAIL_RE.fullmatch(email)) and len(email) <= 254


def _contact_secret(db: Database) -> bytes:
    """Stable per-install HMAC key for code hashes."""
    existing = _get(db, "contact_secret")
    if existing:
        return existing.encode()
    fresh = secrets.token_hex(32)
    set_setting(db, "contact_secret", fresh)
    return fresh.encode()


def hash_email_code(db: Database, email: str, code: str) -> str:
    return hmac.new(
        _contact_secret(db),
        f"email:{email.lower()}\ncode:{code}".encode(),
        hashlib.sha256,
    ).hexdigest()


def hash_telegram_token(token: str) -> str:
    return hashlib.sha256(token.encode()).hexdigest()


def _hashes_equal(a: str, b: str) -> bool:
    if not re.fullmatch(r"[a-f0-9]{64}", a or "") or \
            not re.fullmatch(r"[a-f0-9]{64}", b or ""):
        return False
    return hmac.compare_digest(bytes.fromhex(a), bytes.fromhex(b))


# ---- email transport ----

def send_email(db: Database, to: str, subject: str, body: str) -> None:
    """Raises ApiError(502) when no transport is configured/working."""
    outbox = knobs.get_str("ROOM_TPU_EMAIL_OUTBOX")
    if outbox:
        os.makedirs(outbox, exist_ok=True)
        name = f"{int(time.time() * 1000)}-{secrets.token_hex(4)}.json"
        with open(os.path.join(outbox, name), "w") as f:
            json.dump({"to": to, "subject": subject, "body": body}, f)
        return

    host = knobs.get_str("ROOM_TPU_SMTP_HOST")
    if host:
        import smtplib
        from email.message import EmailMessage

        msg = EmailMessage()
        msg["From"] = knobs.get_str("ROOM_TPU_SMTP_FROM")
        msg["To"] = to
        msg["Subject"] = subject
        msg.set_content(body)
        try:
            port = knobs.get_int("ROOM_TPU_SMTP_PORT")
            with smtplib.SMTP(host, port, timeout=12) as smtp:
                smtp.starttls()
                user = knobs.get_str("ROOM_TPU_SMTP_USER")
                if user:
                    smtp.login(
                        user, knobs.get_str("ROOM_TPU_SMTP_PASS")
                    )
                smtp.send_message(msg)
            return
        except (OSError, smtplib.SMTPException) as e:
            raise ApiError(f"SMTP send failed: {e}", 502) from e

    raise ApiError(
        "no email transport configured (set ROOM_TPU_EMAIL_OUTBOX or "
        "ROOM_TPU_SMTP_HOST)", 502,
    )


# ---- email verification ----

def issue_email_verification(db: Database, email: str) -> dict:
    """6-digit code: cooldown + hourly window enforced, HMAC hash +
    expiry persisted, code delivered by the transport (reference:
    contacts.ts issueEmailVerification)."""
    now = time.time()

    last_sent = _get(db, K_EMAIL_LAST_SENT)
    if last_sent:
        elapsed = now - float(last_sent)
        if elapsed < EMAIL_RESEND_COOLDOWN_S:
            wait = int(EMAIL_RESEND_COOLDOWN_S - elapsed) + 1
            raise ApiError(
                f"Please wait {wait}s before requesting another code",
                429, retry_after_s=wait,
            )

    window_start = float(_get(db, K_EMAIL_RATE_START) or 0)
    count = int(_get(db, K_EMAIL_RATE_COUNT) or 0)
    if now - window_start >= 3600:
        window_start, count = now, 0
    if count >= EMAIL_MAX_SENDS_PER_HOUR:
        wait = int(3600 - (now - window_start)) + 1
        raise ApiError(
            "Too many verification emails; try again later", 429,
            retry_after_s=wait,
        )

    code = f"{secrets.randbelow(1_000_000):06d}"
    expires_at = now + EMAIL_CODE_TTL_MIN * 60
    send_email(
        db, email, "Your verification code",
        f"Your verification code is {code}. It expires in "
        f"{EMAIL_CODE_TTL_MIN} minutes.",
    )
    set_setting(db, K_EMAIL, email)
    _clear(db, K_EMAIL_VERIFIED_AT)
    set_setting(db, K_EMAIL_CODE_HASH, hash_email_code(db, email, code))
    set_setting(db, K_EMAIL_CODE_EXPIRES, str(expires_at))
    set_setting(db, K_EMAIL_LAST_SENT, str(now))
    set_setting(db, K_EMAIL_RATE_START, str(window_start))
    set_setting(db, K_EMAIL_RATE_COUNT, str(count + 1))
    return {
        "sentTo": email,
        "expiresAt": expires_at,
        "retryAfterSec": EMAIL_RESEND_COOLDOWN_S,
    }


def verify_email_code(db: Database, code: str) -> dict:
    if not re.fullmatch(r"\d{6}", code or ""):
        raise ApiError("Verification code must be 6 digits")
    email = _get(db, K_EMAIL).lower()
    stored = _get(db, K_EMAIL_CODE_HASH).lower()
    expires_raw = _get(db, K_EMAIL_CODE_EXPIRES)
    if not is_valid_email(email) or not stored or not expires_raw:
        raise ApiError(
            "No pending verification code. Request a new code first."
        )
    if float(expires_raw) <= time.time():
        _clear(db, K_EMAIL_CODE_HASH)
        _clear(db, K_EMAIL_CODE_EXPIRES)
        raise ApiError("Verification code expired. Request a new code.")
    if not _hashes_equal(stored, hash_email_code(db, email, code)):
        raise ApiError("Invalid verification code")
    verified_at = time.time()
    set_setting(db, K_EMAIL_VERIFIED_AT, str(verified_at))
    _clear(db, K_EMAIL_CODE_HASH)
    _clear(db, K_EMAIL_CODE_EXPIRES)
    return {"email": email, "verifiedAt": verified_at}


# ---- telegram verification ----

def telegram_bot_username() -> str:
    configured = (
        knobs.get_str("ROOM_TPU_TELEGRAM_BOT").strip().lstrip("@")
    )
    return configured or DEFAULT_TELEGRAM_BOT


def start_telegram_verification(db: Database) -> dict:
    token = base64.urlsafe_b64encode(secrets.token_bytes(24)) \
        .decode().rstrip("=")
    expires_at = time.time() + TELEGRAM_TTL_MIN * 60
    bot = telegram_bot_username()
    set_setting(db, K_TG_PENDING_HASH, hash_telegram_token(token))
    set_setting(db, K_TG_PENDING_EXPIRES, str(expires_at))
    set_setting(db, K_TG_BOT, bot)
    return {
        "pending": True,
        "expiresAt": expires_at,
        "botUsername": bot,
        "deepLink": f"https://t.me/{bot}?start=tv1_{token}",
    }


def check_telegram_verification(db: Database) -> dict:
    """Poll step. Without the cloud relay the pending state just ages
    out; the webhook path (confirm_telegram_verification) completes it
    when the bot calls back."""
    token_hash = _get(db, K_TG_PENDING_HASH).lower()
    expires_raw = _get(db, K_TG_PENDING_EXPIRES)
    if not re.fullmatch(r"[a-f0-9]{64}", token_hash) or not expires_raw:
        if _get(db, K_TG_VERIFIED_AT):
            return {"status": "verified", "telegram": telegram_view(db)}
        return {"status": "not_pending"}
    if float(expires_raw) <= time.time():
        _clear(db, K_TG_PENDING_HASH)
        _clear(db, K_TG_PENDING_EXPIRES)
        return {"status": "expired"}
    return {"status": "pending", "botUsername": _get(db, K_TG_BOT)}


def confirm_telegram_verification(
    db: Database, token: str, telegram_id: str,
    username: str = "", first_name: str = "",
) -> bool:
    """Webhook-side completion: the bot relays the /start token back."""
    token_hash = _get(db, K_TG_PENDING_HASH).lower()
    expires_raw = _get(db, K_TG_PENDING_EXPIRES)
    if not token_hash or not expires_raw or \
            float(expires_raw) <= time.time():
        return False
    if not _hashes_equal(token_hash, hash_telegram_token(token)):
        return False
    set_setting(db, K_TG_ID, str(telegram_id))
    set_setting(db, K_TG_USERNAME, username or "")
    set_setting(db, K_TG_FIRST_NAME, first_name or "")
    set_setting(db, K_TG_VERIFIED_AT, str(time.time()))
    _clear(db, K_TG_PENDING_HASH)
    _clear(db, K_TG_PENDING_EXPIRES)
    return True


def disconnect_telegram(db: Database) -> None:
    for key in (K_TG_ID, K_TG_USERNAME, K_TG_FIRST_NAME,
                K_TG_VERIFIED_AT, K_TG_PENDING_HASH,
                K_TG_PENDING_EXPIRES):
        _clear(db, key)


def telegram_view(db: Database) -> Optional[dict]:
    if not _get(db, K_TG_VERIFIED_AT):
        return None
    return {
        "id": _get(db, K_TG_ID),
        "username": _get(db, K_TG_USERNAME) or None,
        "firstName": _get(db, K_TG_FIRST_NAME) or None,
        "verifiedAt": float(_get(db, K_TG_VERIFIED_AT)),
    }


# ---- status ----

def contacts_status(db: Database) -> dict:
    email = _get(db, K_EMAIL)
    email_verified = _get(db, K_EMAIL_VERIFIED_AT)
    pending_code = bool(
        _get(db, K_EMAIL_CODE_HASH)
        and float(_get(db, K_EMAIL_CODE_EXPIRES) or 0) > time.time()
    )
    tg_pending = bool(
        _get(db, K_TG_PENDING_HASH)
        and float(_get(db, K_TG_PENDING_EXPIRES) or 0) > time.time()
    )
    return {
        "email": {
            "address": email or None,
            "verified": bool(email_verified),
            "verifiedAt": float(email_verified) if email_verified
            else None,
            "pendingCode": pending_code,
        },
        "telegram": {
            "connected": bool(_get(db, K_TG_VERIFIED_AT)),
            "details": telegram_view(db),
            "pending": tg_pending,
            "botUsername": _get(db, K_TG_BOT) or telegram_bot_username(),
        },
    }


# ---- keeper email (reference: keeper-email.ts sendKeeperEmail) ----

def send_keeper_email(
    db: Database, to: str, content: str, subject: Optional[str] = None,
) -> bool:
    """Send from the clerk to any address ("admin" resolves to the
    verified keeper email). Records a clerk message on success."""
    if to == "admin":
        to = _get(db, K_EMAIL)
        if not to or not _get(db, K_EMAIL_VERIFIED_AT):
            return False
    if not is_valid_email(to):
        return False
    try:
        send_email(db, to, subject or "Message from Clerk", content)
    except ApiError:
        return False
    db.insert(
        "INSERT INTO clerk_messages(role, content, source) "
        "VALUES ('assistant', ?, 'email')",
        (content,),
    )
    return True
