"""Server boot orchestration (reference: src/server/index.ts
startServer:867): open the DB, start runtime loops, bring up the API
server (+WS), write token/port files, and tear it all down in reverse
order on shutdown.

Two shutdown shapes (docs/lifecycle.md): ``stop()`` is the hard path
(tests, crash handling) — engines reset, nothing spooled; ``stop(
graceful=True)`` is the SIGTERM path — admission 503s immediately, the
swarm quiesces, every warm engine drains its sessions to the lifecycle
manifest, and the clean-shutdown marker is written last so the next
boot knows this was a rolling restart, not a crash."""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field
from typing import Optional

from ..db import Database, get_database
from ..utils import knobs
from .http import ApiServer
from .runtime import (
    ServerRuntime, install_lifecycle_signal_handlers,
    note_drain_result, note_drain_started, set_lifecycle_phase,
    start_server_runtime, stop_server_runtime,
)


@dataclass
class ServerApp:
    db: Database
    runtime: ServerRuntime
    api: ApiServer
    _stopped: threading.Event = field(default_factory=threading.Event)

    @property
    def port(self) -> int:
        return self.api.port

    def stop(self, graceful: bool = False) -> None:
        # reverse boot order: stop loops, stop serving, close DB
        if self._stopped.is_set():
            return
        self._stopped.set()
        from ..providers.tpu import (
            begin_drain_model_hosts, drain_model_hosts,
            end_drain_model_hosts, reset_model_hosts,
        )
        from ..serving import lifecycle as lifecycle_helpers
        from .updater import reset_update_checker

        if graceful:
            # close engine admission FIRST: in-flight turns finish (or
            # hit the drain deadline), everything new gets 503 +
            # Retry-After while the rest of the teardown proceeds
            note_drain_started()
            begin_drain_model_hosts()
        reset_update_checker()
        stop_server_runtime()
        drain_ok = True
        if graceful:
            summaries = drain_model_hosts()
            note_drain_result(summaries)
            drain_ok = all(
                s.get("manifest_written", False)
                for s in summaries.values()
            )
        self.api.stop()
        if not graceful:
            reset_model_hosts()
        elif drain_ok:
            # marker LAST, and only when every engine's drain actually
            # landed its manifest: it attests that every step above
            # completed. A crash mid-drain OR a failed manifest write
            # leaves no marker — the next boot reports "crash" and its
            # journal recovery treats the lost state as the loss it was
            # instead of a green "clean" pill over vanished sessions.
            # NOT gated on ROOM_TPU_LIFECYCLE: the knob disables
            # drains/manifests, but the marker records HOW the process
            # exited, which the (unconditional) boot check reads either
            # way — without it every graceful stop of a
            # lifecycle-disabled deployment reads as a crash
            lifecycle_helpers.write_clean_marker(summaries=summaries)
        if graceful:
            # API is down, drains are landed: builds may resume so a
            # same-process start_server() can warm-restart
            end_drain_model_hosts()
        self.db.close()
        set_lifecycle_phase("stopped")


def start_server(
    port: int = 0,
    db: Optional[Database] = None,
    static_dir: Optional[str] = None,
    install_signal_handlers: bool = False,
) -> ServerApp:
    from .shell_path import inherit_shell_path
    from .updater import get_update_checker, init_boot_health_check

    # GUI launches get a minimal PATH: merge the login shell's before
    # probing provider CLIs (reference inheritShellPath)
    inherit_shell_path()
    # crash-rollback check before anything serves (reference
    # initBootHealthCheck), then the background update checker
    init_boot_health_check()

    db = db or get_database()
    runtime = start_server_runtime(db)

    # register our MCP server with installed AI clients (reference
    # registerMcpGlobally; never breaks startup)
    if knobs.get_bool("ROOM_TPU_MCP_AUTOREGISTER"):
        from ..mcp.autoregister import register_mcp_globally

        register_mcp_globally(db.path or "")

    get_update_checker().start()
    if static_dir is None:
        static_dir = knobs.get_str("ROOM_TPU_STATIC_DIR")
    if static_dir is None:
        bundled = os.path.join(
            os.path.dirname(
                os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
            ),
            "ui",
        )
        if os.path.isdir(bundled):
            static_dir = bundled
    api = ApiServer(
        db,
        runtime=runtime,
        port=port,
        static_dir=static_dir,
        cloud_mode=knobs.get_str("ROOM_TPU_DEPLOYMENT_MODE") == "cloud",
    )
    api.start()
    app = ServerApp(db=db, runtime=runtime, api=api)

    if install_signal_handlers:
        # SIGTERM/SIGINT take the graceful path: drain + manifest +
        # clean-shutdown marker (docs/lifecycle.md)
        done = install_lifecycle_signal_handlers(
            lambda: app.stop(graceful=True)
        )
        app._done = done  # type: ignore[attr-defined]
    return app
