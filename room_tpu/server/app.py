"""Server boot orchestration (reference: src/server/index.ts
startServer:867): open the DB, start runtime loops, bring up the API
server (+WS), write token/port files, and tear it all down in reverse
order on shutdown."""

from __future__ import annotations

import os
import signal
import threading
from dataclasses import dataclass
from typing import Optional

from ..db import Database, get_database
from .http import ApiServer
from .runtime import ServerRuntime, start_server_runtime, stop_server_runtime


@dataclass
class ServerApp:
    db: Database
    runtime: ServerRuntime
    api: ApiServer

    @property
    def port(self) -> int:
        return self.api.port

    def stop(self) -> None:
        # reverse boot order: stop loops, stop serving, close DB
        from .updater import reset_update_checker

        reset_update_checker()
        stop_server_runtime()
        self.api.stop()
        from ..providers.tpu import reset_model_hosts

        reset_model_hosts()
        self.db.close()


def start_server(
    port: int = 0,
    db: Optional[Database] = None,
    static_dir: Optional[str] = None,
    install_signal_handlers: bool = False,
) -> ServerApp:
    from .shell_path import inherit_shell_path
    from .updater import get_update_checker, init_boot_health_check

    # GUI launches get a minimal PATH: merge the login shell's before
    # probing provider CLIs (reference inheritShellPath)
    inherit_shell_path()
    # crash-rollback check before anything serves (reference
    # initBootHealthCheck), then the background update checker
    init_boot_health_check()

    db = db or get_database()
    runtime = start_server_runtime(db)

    # register our MCP server with installed AI clients (reference
    # registerMcpGlobally; never breaks startup)
    if os.environ.get("ROOM_TPU_MCP_AUTOREGISTER", "1") != "0":
        from ..mcp.autoregister import register_mcp_globally

        register_mcp_globally(db.path or "")

    get_update_checker().start()
    if static_dir is None:
        static_dir = os.environ.get("ROOM_TPU_STATIC_DIR")
    if static_dir is None:
        bundled = os.path.join(
            os.path.dirname(
                os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
            ),
            "ui",
        )
        if os.path.isdir(bundled):
            static_dir = bundled
    api = ApiServer(
        db,
        runtime=runtime,
        port=port,
        static_dir=static_dir,
        cloud_mode=os.environ.get("ROOM_TPU_DEPLOYMENT_MODE") == "cloud",
    )
    api.start()
    app = ServerApp(db=db, runtime=runtime, api=api)

    if install_signal_handlers:
        done = threading.Event()

        def shutdown(signum, frame):
            app.stop()
            done.set()

        signal.signal(signal.SIGINT, shutdown)
        signal.signal(signal.SIGTERM, shutdown)
        app._done = done  # type: ignore[attr-defined]
    return app
