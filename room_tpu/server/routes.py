"""REST route registration (reference: src/server/routes/ — 20 modules;
grouped here by domain, same /api contract shape {status,data,error})."""

from __future__ import annotations

import json
import os
import re
from typing import Any

from .. import __version__
from ..core import (
    activity as activity_mod,
    agent_loop,
    credentials as credentials_mod,
    escalations as escalations_mod,
    goals as goals_mod,
    memory as memory_mod,
    messages as messages_mod,
    quorum as quorum_mod,
    rooms as rooms_mod,
    selfmod as selfmod_mod,
    skills as skills_mod,
    task_runner,
    wallet as wallet_mod,
    workers as workers_mod,
)
from ..core.cycle_logs import get_cycle_logs
from ..providers import get_model_auth_status
from .router import RequestContext, Router, err, ok
from ..utils import knobs


def _room_or_404(ctx: RequestContext):
    room = rooms_mod.get_room(ctx.db, ctx.int_param("id"))
    if room is None:
        return None, err("room not found", 404)
    return room, None


def register_all_routes(r: Router) -> None:
    # literal paths that would otherwise be shadowed by ':param'
    # patterns (e.g. /api/memory/entities vs /api/memory/:id) register
    # first — the router matches in registration order
    register_extended_routes(r)
    register_room_routes(r)
    register_worker_routes(r)
    register_goal_routes(r)
    register_task_routes(r)
    register_memory_routes(r)
    register_decision_routes(r)
    register_skill_routes(r)
    register_escalation_routes(r)
    register_message_routes(r)
    register_credential_routes(r)
    register_wallet_routes(r)
    register_settings_routes(r)
    register_status_routes(r)
    register_clerk_routes(r)
    register_provider_routes(r)
    register_contact_routes(r)
    register_aux_routes(r)
    register_openai_routes(r)


def register_openai_routes(r: Router) -> None:
    """OpenAI-compatible inference surface — the drop-in equivalent of
    the Ollama endpoint the reference points every OpenAI-style client
    at (reference: src/shared/local-model.ts:3-5 pins
    127.0.0.1:11434/v1/chat/completions; agent-executor.ts:327-338).
    Any OpenAI SDK pointed at this server with its API token chats with
    the TPU-served models. http.py unwraps /v1/ responses from the
    internal envelope and streams ``sse`` payloads as server-sent
    events."""

    def models(ctx):
        from ..providers.tpu import MODEL_CONFIGS, get_model_host

        data = []
        for name in sorted(MODEL_CONFIGS):
            ready, _ = get_model_host(name).readiness()
            data.append({
                "id": f"tpu:{name}", "object": "model",
                "owned_by": "room_tpu", "ready": ready,
            })
        return ok({"object": "list", "data": data})

    def chat(ctx):
        import queue as queue_mod
        import time as time_mod
        import uuid

        from ..providers.base import ProviderError
        from ..providers.tpu import MODEL_CONFIGS, get_model_host
        from ..serving import (
            SamplingParams, extract_tool_call, faults, render_chat,
        )

        b = ctx.body or {}
        raw_model = b.get("model") or "tpu:qwen3-coder-30b"
        name = raw_model[4:] if raw_model.startswith("tpu:") \
            else raw_model
        if name not in MODEL_CONFIGS:
            return err(f"unknown model {raw_model!r}", 404)
        messages = b.get("messages")
        if not isinstance(messages, list) or not messages:
            return err("messages (a non-empty list) is required")
        try:
            engine = get_model_host(name).engine()
        except ProviderError as e:
            # cold-failed OR crash-looped engine: tell SDK retry logic
            # when to come back instead of letting it hammer
            return {"status": 503, "error": str(e),
                    "headers": {"Retry-After": "30"}}

        tok = engine.tokenizer
        tools = b.get("tools")
        prompt_tokens = tok.encode(render_chat(messages, tools))

        def num(key, default):
            # OpenAI treats an explicit JSON null as "use the default"
            v = b.get(key)
            return default if v is None else v

        try:
            presence = float(num("presence_penalty", 0.0))
            frequency = float(num("frequency_penalty", 0.0))
            sampling = SamplingParams(
                temperature=float(num("temperature", 0.7)),
                top_p=float(num("top_p", 1.0)),
                # top_k is the Ollama/openai-compat extension the
                # reference relied on (agent-executor.ts passthrough)
                top_k=int(num("top_k", 0)),
                max_new_tokens=int(
                    num("max_completion_tokens", None)
                    or num("max_tokens", None) or 1024
                ),
                presence_penalty=presence,
                frequency_penalty=frequency,
            )
        except (TypeError, ValueError):
            return err("sampling parameters must be numbers")
        if not (-2.0 <= presence <= 2.0):
            return err("presence_penalty must be in [-2, 2]")
        if not (-2.0 <= frequency <= 2.0):
            return err("frequency_penalty must be in [-2, 2]")
        stop_raw = b.get("stop")
        if isinstance(stop_raw, str):
            stop_list = [stop_raw]
        elif isinstance(stop_raw, list):
            if not all(isinstance(x, str) for x in stop_raw):
                return err("stop must be a string or list of strings")
            stop_list = [x for x in stop_raw if x]
        elif stop_raw is None:
            stop_list = []
        else:
            return err("stop must be a string or list of strings")
        # OpenAI caps stop at 4 sequences; bounding each sequence's
        # length also bounds the decoded-tail window the engine rescans
        # on every generated token
        if len(stop_list) > 4:
            return err("at most 4 stop sequences are supported")
        if any(len(s.encode("utf-8")) > 64 for s in stop_list):
            return err("each stop sequence must be at most 64 bytes")

        def visible_text(token_ids):
            """Decoded reply without chat scaffolding: trailing stop
            tokens dropped, any literal im_end remnant stripped (same
            posture as the internal provider)."""
            toks = list(token_ids)
            while toks and toks[-1] in engine.stop_token_ids:
                toks.pop()
            return tok.decode(toks).replace("<|im_end|>", "")

        def tool_calls_of(text):
            call = extract_tool_call(text)
            if call is None:
                return None
            return [{
                "id": f"call_{uuid.uuid4().hex[:12]}",
                "type": "function",
                "function": {
                    "name": call.get("name", ""),
                    "arguments": json.dumps(
                        call.get("arguments", {}) or {}
                    ),
                },
            }]
        created = int(time_mod.time())
        cid = f"chatcmpl-{uuid.uuid4().hex[:24]}"
        timeout_s = knobs.get_float("ROOM_TPU_V1_TIMEOUT_S")
        finish_map = {"stop": "stop", "length": "length",
                      "tool_call": "tool_calls"}

        if b.get("stream"):
            q: queue_mod.Queue = queue_mod.Queue()
            turn = engine.submit(
                prompt_tokens, sampling=sampling, on_token=q.put,
                stop_strings=stop_list,
            )

            def sse():
                ids: list[int] = []
                committed = 0        # tokens already turned into text
                sent = ""            # text already delivered
                held = ""            # decoded but not yet delivered
                suppressing = False  # inside tool-call XML: drop text
                deadline = time_mod.monotonic() + timeout_s

                def chunk(delta, finish=None):
                    return {
                        "id": cid, "object": "chat.completion.chunk",
                        "created": created, "model": raw_model,
                        "choices": [{
                            "index": 0, "delta": delta,
                            "finish_reason": finish,
                        }],
                    }

                TOOL_TAG = "<tool_call>"
                hold_pats = [TOOL_TAG] + stop_list

                def emit_new(final=False):
                    """Incremental detokenization: decode only the
                    uncommitted tail (linear total cost) and hold back
                    text that ends in a replacement char (a split
                    multi-byte sequence) or in a prefix of the
                    tool-call tag — tool-call XML must never leak as
                    content. ``final`` flushes everything still held."""
                    nonlocal committed, sent, held, suppressing
                    tail = tok.decode([
                        t for t in ids[committed:]
                        if t not in engine.stop_token_ids
                    ])
                    committed = len(ids)
                    if suppressing:
                        # everything after the tag is tool-call XML:
                        # it surfaces via the tool_calls chunk instead
                        return None
                    held += tail
                    stop_cut = min(
                        (held.index(p) for p in stop_list if p in held),
                        default=None,
                    )
                    if TOOL_TAG in held and (
                        stop_cut is None
                        or held.index(TOOL_TAG) < stop_cut
                    ):
                        out_text = held.split(TOOL_TAG)[0]
                        held = ""   # XML and beyond stays unsent
                        suppressing = True
                    elif stop_cut is not None:
                        # custom stop sequence: deliver text before it,
                        # drop the sequence and everything after
                        out_text = held[:stop_cut]
                        held = ""
                        suppressing = True
                    elif not final and held.endswith("�"):
                        # split multi-byte sequence: wait for the rest
                        return None
                    else:
                        # longest suffix that could still grow into the
                        # tool tag or a stop sequence stays held
                        # (unless flushing)
                        hold_n = 0
                        if not final:
                            for pat in hold_pats:
                                for n in range(
                                    min(len(pat) - 1, len(held)), 0, -1
                                ):
                                    if pat.startswith(held[-n:]):
                                        hold_n = max(hold_n, n)
                                        break
                        out_text = held[: len(held) - hold_n]
                        held = held[len(held) - hold_n:]
                    out_text = out_text.replace("<|im_end|>", "")
                    if out_text:
                        sent += out_text
                        return chunk({"content": out_text})
                    return None

                try:
                    yield chunk({"role": "assistant", "content": ""})
                    while time_mod.monotonic() < deadline:
                        if faults.should_fire("client_disconnect"):
                            # chaos fault point: the browser vanished
                            # mid-stream — the finally below must still
                            # return the session's pages to the pool
                            return
                        try:
                            ids.append(q.get(timeout=0.1))
                        except queue_mod.Empty:
                            if turn.done.is_set() and q.empty():
                                break
                            continue
                        c = emit_new()
                        if c is not None:
                            yield c
                    if turn.finish_reason == "error":
                        # OpenAI streams signal failures as an error
                        # event, not a normal finish
                        yield {"error": {
                            "message": turn.error or "generation failed",
                            "type": "overloaded_error" if turn.shed
                                    else "server_error",
                        }}
                        return
                    ids = list(turn.new_tokens)
                    c = emit_new(final=True)
                    if c is not None:
                        yield c
                    if not turn.done.is_set():
                        # deadline hit mid-generation: a truncated reply
                        # must not masquerade as a clean stop
                        finish = "length"
                    else:
                        finish = finish_map.get(turn.finish_reason,
                                                "stop")
                    if finish == "tool_calls":
                        calls = tool_calls_of(
                            tok.decode(turn.new_tokens)
                        )
                        if calls:
                            for call in calls:
                                call["index"] = 0
                            yield chunk({"tool_calls": calls})
                    yield chunk({}, finish)
                    yield "[DONE]"
                finally:
                    # runs on normal completion AND client disconnect
                    # (GeneratorExit): the one-shot session must not pin
                    # its pages
                    engine.release_session(turn.session_id)

            return {"status": 200, "sse": sse()}

        turn = engine.submit(prompt_tokens, sampling=sampling,
                             stop_strings=stop_list)
        if not turn.done.wait(timeout=timeout_s):
            # release now: deferred-release frees the pages once the
            # in-flight turn finishes, so timeouts can't pin the pool
            engine.release_session(turn.session_id)
            return err("generation timed out", 504)
        raw_text = tok.decode(turn.new_tokens)
        engine.release_session(turn.session_id)
        if turn.finish_reason == "error":
            if turn.shed:
                # degradation ladder rung 3: the engine shed this turn
                # under sustained pressure — 503 + Retry-After, the
                # backoff contract SDK retry logic understands
                return {"status": 503,
                        "error": turn.error or "server overloaded",
                        "headers": {"Retry-After": "30"}}
            return err(turn.error or "generation failed", 500)

        text = visible_text(turn.new_tokens)
        if turn.stop_hit and turn.stop_hit in text:
            # OpenAI semantics: the matched stop sequence is excluded
            text = text[: text.index(turn.stop_hit)]
        message: dict = {"role": "assistant", "content": text}
        finish = finish_map.get(turn.finish_reason, "stop")
        if turn.finish_reason == "tool_call":
            calls = tool_calls_of(raw_text)
            if calls is not None:
                pre = text[: text.find("<tool_call>")].strip() \
                    if "<tool_call>" in text else ""
                message = {
                    "role": "assistant",
                    "content": pre or None,
                    "tool_calls": calls,
                }
        return ok({
            "id": cid, "object": "chat.completion", "created": created,
            "model": raw_model,
            "choices": [{
                "index": 0, "message": message,
                "finish_reason": finish,
            }],
            "usage": {
                "prompt_tokens": len(prompt_tokens),
                "completion_tokens": len(turn.new_tokens),
                "total_tokens":
                    len(prompt_tokens) + len(turn.new_tokens),
            },
        })

    def embeddings(ctx):
        b = ctx.body or {}
        raw = b.get("input")
        if isinstance(raw, str):
            texts = [raw]
        elif isinstance(raw, list) and raw and all(
            isinstance(t, str) for t in raw
        ):
            texts = raw
        else:
            return err("input must be a string or a non-empty list "
                       "of strings")
        if len(texts) > 512:
            return err("too many inputs (max 512)")
        from ..serving.embed_service import (
            MAX_TOKENS, embed_texts, get_embed_host,
        )

        host = get_embed_host()
        vecs = embed_texts(texts)
        # the tokens the encoder actually consumed (it truncates at
        # MAX_TOKENS), counted with its own tokenizer
        n_tokens = sum(
            min(len(host.tokenizer.encode(t)), MAX_TOKENS)
            for t in texts
        )
        return ok({
            "object": "list",
            "model": b.get("model") or f"room-embed-{host.dim}",
            "data": [{
                "object": "embedding", "index": i,
                "embedding": [float(x) for x in v],
            } for i, v in enumerate(vecs)],
            "usage": {
                "prompt_tokens": n_tokens,
                "total_tokens": n_tokens,
            },
        })

    r.get("/v1/models", models)
    r.post("/v1/chat/completions", chat)
    r.post("/v1/embeddings", embeddings)


def register_extended_routes(r: Router) -> None:
    """Detail/patch endpoints matching the remainder of the reference's
    route surface (reference: src/server/routes/{goals,decisions,
    memory,messages,workers,runs,rooms,settings,tasks,clerk}.ts)."""

    # -- goals --
    def get_goal_detail(ctx):
        g = goals_mod.get_goal(ctx.db, ctx.int_param("id"))
        if g is None:
            return err("goal not found", 404)
        g["updates"] = ctx.db.query(
            "SELECT * FROM goal_updates WHERE goal_id=? ORDER BY id",
            (g["id"],),
        )
        g["subgoals"] = ctx.db.query(
            "SELECT * FROM goals WHERE parent_goal_id=? ORDER BY id",
            (g["id"],),
        )
        return ok(g)

    def add_goal_update_route(ctx):
        b = ctx.body or {}
        if goals_mod.get_goal(ctx.db, ctx.int_param("id")) is None:
            return err("goal not found", 404)
        goals_mod.add_goal_update(
            ctx.db, ctx.int_param("id"),
            b.get("update") or b.get("content") or "",
            worker_id=b.get("workerId"),
            metric_value=b.get("progress"),
        )
        return ok(goals_mod.get_goal(ctx.db, ctx.int_param("id")),
                  201)

    def patch_goal(ctx):
        gid = ctx.int_param("id")
        g = goals_mod.get_goal(ctx.db, gid)
        if g is None:
            return err("goal not found", 404)
        b = ctx.body or {}
        if "description" in b:
            ctx.db.execute(
                "UPDATE goals SET description=? WHERE id=?",
                (b["description"], gid),
            )
        if "workerId" in b:
            goals_mod.assign_goal(ctx.db, gid, b["workerId"])
        if "progress" in b:
            goals_mod.set_goal_progress(
                ctx.db, gid, ctx.float_body("progress")
            )
        return ok(goals_mod.get_goal(ctx.db, gid))

    def delete_goal(ctx):
        gid = ctx.int_param("id")
        if goals_mod.get_goal(ctx.db, gid) is None:
            return err("goal not found", 404)
        ctx.db.execute("DELETE FROM goals WHERE id=?", (gid,))
        return ok({"deleted": gid})

    r.get("/api/goals/:id", get_goal_detail)
    r.post("/api/goals/:id/updates", add_goal_update_route)
    r.put("/api/goals/:id", patch_goal)
    r.delete("/api/goals/:id", delete_goal)

    # -- decisions --
    def get_decision_detail(ctx):
        d = quorum_mod.get_decision(ctx.db, ctx.int_param("id"))
        if d is None:
            return err("decision not found", 404)
        d["votes"] = ctx.db.query(
            "SELECT * FROM quorum_votes WHERE decision_id=? ORDER BY id",
            (d["id"],),
        )
        d["tally"] = quorum_mod.tally(ctx.db, d["id"])
        return ok(d)

    def decision_votes(ctx):
        return ok(ctx.db.query(
            "SELECT * FROM quorum_votes WHERE decision_id=? ORDER BY id",
            (ctx.int_param("id"),),
        ))

    def create_decision(ctx):
        room, e = _room_or_404(ctx)
        if e:
            return e
        b = ctx.body or {}
        if not b.get("proposal"):
            return err("proposal is required")
        try:
            d = quorum_mod.announce(
                ctx.db, room["id"], b.get("proposerId"),
                b["proposal"],
                decision_type=b.get("decisionType", "low_impact"),
            )
        except quorum_mod.QuorumError as ex:
            return err(str(ex))
        return ok(d, 201)

    def resolve_decision(ctx):
        """Keeper force-resolve (reference: decisions.ts resolve)."""
        did = ctx.int_param("id")
        d = quorum_mod.get_decision(ctx.db, did)
        if d is None:
            return err("decision not found", 404)
        if d["status"] not in ("announced", "voting"):
            return err(f"decision already {d['status']}", 409)
        approve = bool((ctx.body or {}).get("approve", True))
        quorum_mod._resolve(
            ctx.db, did,
            "effective" if approve else "rejected",
            "Resolved by keeper",
        )
        return ok(quorum_mod.get_decision(ctx.db, did))

    r.get("/api/decisions/:id", get_decision_detail)
    r.get("/api/decisions/:id/votes", decision_votes)
    r.post("/api/rooms/:id/decisions", create_decision)
    r.post("/api/decisions/:id/resolve", resolve_decision)

    # -- memory graph --
    def list_entities(ctx):
        room_id = ctx.int_query("roomId", 0) or None
        limit = ctx.int_query("limit", 100)
        return ok(ctx.db.query(
            "SELECT * FROM entities "
            + ("WHERE room_id=? " if room_id else "")
            + "ORDER BY id DESC LIMIT ?",
            ((room_id, limit) if room_id else (limit,)),
        ))

    def memory_stats(ctx):
        return ok({
            "entities": ctx.db.query_one(
                "SELECT COUNT(*) AS n FROM entities")["n"],
            "observations": ctx.db.query_one(
                "SELECT COUNT(*) AS n FROM observations")["n"],
            "relations": ctx.db.query_one(
                "SELECT COUNT(*) AS n FROM relations")["n"],
            "embedded": ctx.db.query_one(
                "SELECT COUNT(*) AS n FROM embeddings")["n"],
        })

    def add_observation_route(ctx):
        eid = ctx.int_param("id")
        if memory_mod.get_entity(ctx.db, eid) is None:
            return err("entity not found", 404)
        content = (ctx.body or {}).get("content")
        if not content:
            return err("content is required")
        oid = memory_mod.add_observation(ctx.db, eid, content)
        return ok({"observationId": oid}, 201)

    def add_relation_route(ctx):
        b = ctx.body or {}
        for field in ("fromId", "toId", "relationType"):
            if not b.get(field):
                return err(f"{field} is required")
        rid = memory_mod.create_relation(
            ctx.db, ctx.int_body("fromId"), ctx.int_body("toId"),
            b["relationType"],
        )
        return ok({"relationId": rid}, 201)

    def delete_observation(ctx):
        ctx.db.execute(
            "DELETE FROM observations WHERE id=?",
            (ctx.int_param("id"),),
        )
        return ok({"deleted": ctx.int_param("id")})

    def delete_relation(ctx):
        ctx.db.execute(
            "DELETE FROM relations WHERE id=?",
            (ctx.int_param("id"),),
        )
        return ok({"deleted": ctx.int_param("id")})

    def list_observations(ctx):
        return ok(memory_mod.get_observations(
            ctx.db, ctx.int_param("id"),
            newest_first=True, limit=100,
        ))

    r.get("/api/memory/entities", list_entities)
    r.get("/api/memory/entities/:id/observations", list_observations)
    r.get("/api/memory/stats", memory_stats)
    r.post("/api/memory/entities/:id/observations",
           add_observation_route)
    r.post("/api/memory/relations", add_relation_route)
    r.delete("/api/memory/observations/:id", delete_observation)
    r.delete("/api/memory/relations/:id", delete_relation)

    # -- messages --
    def get_message(ctx):
        m = ctx.db.query_one(
            "SELECT * FROM room_messages WHERE id=?",
            (ctx.int_param("id"),),
        )
        return ok(m) if m else err("message not found", 404)

    def delete_message(ctx):
        ctx.db.execute(
            "DELETE FROM room_messages WHERE id=?",
            (ctx.int_param("id"),),
        )
        return ok({"deleted": ctx.int_param("id")})

    def read_all_messages(ctx):
        room, e = _room_or_404(ctx)
        if e:
            return e
        ctx.db.execute(
            "UPDATE room_messages SET status='read' WHERE room_id=? "
            "AND status='unread'",
            (room["id"],),
        )
        return ok({"ok": True})

    r.get("/api/messages/:id", get_message)
    r.delete("/api/messages/:id", delete_message)
    r.post("/api/rooms/:id/messages/read-all", read_all_messages)

    # -- workers / runs / rooms --
    def list_all_workers(ctx):
        return ok(ctx.db.query(
            "SELECT * FROM workers ORDER BY room_id, id"
        ))

    def stop_worker(ctx):
        w = workers_mod.get_worker(ctx.db, ctx.int_param("id"))
        if w is None:
            return err("worker not found", 404)
        from ..core.agent_loop import stop_worker_loop

        stopped = stop_worker_loop(w["id"])
        return ok({"stopped": stopped})

    def list_runs(ctx):
        return ok(ctx.db.query(
            "SELECT tr.*, t.name AS task_name FROM task_runs tr "
            "LEFT JOIN tasks t ON t.id = tr.task_id "
            "ORDER BY tr.id DESC LIMIT ?",
            (ctx.int_query("limit", 50),),
        ))

    def room_queen(ctx):
        room, e = _room_or_404(ctx)
        if e:
            return e
        if not room["queen_worker_id"]:
            return err("room has no queen", 404)
        return ok(workers_mod.get_worker(
            ctx.db, room["queen_worker_id"]
        ))

    def restart_room(ctx):
        room, e = _room_or_404(ctx)
        if e:
            return e
        if ctx.runtime is None:
            return err("runtime not running", 503)
        ctx.runtime.stop_room(room["id"])
        started = ctx.runtime.start_room(room["id"])
        return ok({"restarted": started})

    def queen_states(ctx):
        out = {}
        for room in rooms_mod.list_rooms(ctx.db):
            qid = room["queen_worker_id"]
            queen = workers_mod.get_worker(ctx.db, qid) if qid else None
            out[str(room["id"])] = {
                "queenWorkerId": qid,
                "state": queen["agent_state"] if queen else None,
            }
        return ok(out)

    def room_selfmod(ctx):
        room, e = _room_or_404(ctx)
        if e:
            return e
        return ok(ctx.db.query(
            "SELECT * FROM self_mod_audit WHERE room_id=? "
            "ORDER BY id DESC",
            (room["id"],),
        ))

    r.get("/api/workers", list_all_workers)
    r.post("/api/workers/:id/stop", stop_worker)
    r.get("/api/runs", list_runs)
    r.get("/api/rooms/:id/queen", room_queen)
    r.post("/api/rooms/:id/restart", restart_room)
    r.get("/api/rooms/queen-states", queen_states)
    r.get("/api/rooms/:id/self-mod", room_selfmod)

    # -- settings / tasks / clerk --
    def get_setting_route(ctx):
        value = messages_mod.get_setting(ctx.db, ctx.params["key"])
        return ok({"key": ctx.params["key"], "value": value})

    def put_setting_route(ctx):
        value = (ctx.body or {}).get("value")
        messages_mod.set_setting(
            ctx.db, ctx.params["key"],
            "" if value is None else str(value),
        )
        return ok({"key": ctx.params["key"], "value": value})

    def patch_task(ctx):
        tid = ctx.int_param("id")
        if task_runner.get_task(ctx.db, tid) is None:
            return err("task not found", 404)
        b = ctx.body or {}
        fields = {"name": "name", "prompt": "prompt",
                  "cronExpression": "cron_expression",
                  "description": "description"}
        for api_key, col in fields.items():
            if api_key in b:
                ctx.db.execute(
                    f"UPDATE tasks SET {col}=? WHERE id=?",
                    (b[api_key], tid),
                )
        return ok(task_runner.get_task(ctx.db, tid))

    def reset_task_session(ctx):
        tid = ctx.int_param("id")
        if task_runner.get_task(ctx.db, tid) is None:
            return err("task not found", 404)
        ctx.db.execute(
            "UPDATE tasks SET session_id=NULL WHERE id=?", (tid,),
        )
        return ok(task_runner.get_task(ctx.db, tid))

    def clerk_status(ctx):
        last = ctx.db.query_one(
            "SELECT * FROM clerk_messages ORDER BY id DESC LIMIT 1"
        )
        usage = ctx.db.query_one(
            "SELECT COUNT(*) AS turns FROM clerk_usage"
        )
        return ok({
            "lastMessageAt": last["created_at"] if last else None,
            "messages": ctx.db.query_one(
                "SELECT COUNT(*) AS n FROM clerk_messages")["n"],
            "turns": usage["turns"] if usage else 0,
        })

    def clerk_reset(ctx):
        ctx.db.execute("DELETE FROM clerk_messages")
        return ok({"ok": True})

    r.get("/api/settings/:key", get_setting_route)
    r.put("/api/settings/:key", put_setting_route)
    r.put("/api/tasks/:id", patch_task)
    r.post("/api/tasks/:id/reset-session", reset_task_session)
    r.get("/api/clerk/status", clerk_status)
    r.post("/api/clerk/reset", clerk_reset)


def register_contact_routes(r: Router) -> None:
    """Keeper contact channels (reference: src/server/routes/contacts.ts
    — email code verification + telegram deep-link flow)."""
    from . import contacts as contacts_mod

    def _api_err(e: contacts_mod.ApiError):
        out = err(str(e), e.status)
        if e.retry_after_s is not None:
            out["data"] = {"retryAfterSec": e.retry_after_s}
        return out

    def status(ctx):
        return ok(contacts_mod.contacts_status(ctx.db))

    def email_start(ctx):
        email = str((ctx.body or {}).get("email") or "").strip().lower()
        if not contacts_mod.is_valid_email(email):
            return err("Valid email is required")
        current = (contacts_mod._get(ctx.db, contacts_mod.K_EMAIL)
                   or "").lower()
        verified = contacts_mod._get(
            ctx.db, contacts_mod.K_EMAIL_VERIFIED_AT
        )
        if current == email and verified:
            return ok({"ok": True, "alreadyVerified": True,
                       "email": email})
        try:
            out = contacts_mod.issue_email_verification(ctx.db, email)
        except contacts_mod.ApiError as e:
            return _api_err(e)
        return ok({"ok": True, **out})

    def email_resend(ctx):
        email = (contacts_mod._get(ctx.db, contacts_mod.K_EMAIL)
                 or "").lower()
        if not contacts_mod.is_valid_email(email):
            return err("No email to resend verification to")
        if contacts_mod._get(ctx.db, contacts_mod.K_EMAIL_VERIFIED_AT):
            return ok({"ok": True, "alreadyVerified": True,
                       "email": email})
        try:
            out = contacts_mod.issue_email_verification(ctx.db, email)
        except contacts_mod.ApiError as e:
            return _api_err(e)
        return ok({"ok": True, **out})

    def email_verify(ctx):
        code = str((ctx.body or {}).get("code") or "").strip()
        try:
            out = contacts_mod.verify_email_code(ctx.db, code)
        except contacts_mod.ApiError as e:
            return _api_err(e)
        return ok({"ok": True, **out})

    def tg_start(ctx):
        return ok({"ok": True,
                   **contacts_mod.start_telegram_verification(ctx.db)})

    def tg_check(ctx):
        return ok({"ok": True,
                   **contacts_mod.check_telegram_verification(ctx.db)})

    def tg_disconnect(ctx):
        contacts_mod.disconnect_telegram(ctx.db)
        return ok({"ok": True})

    r.get("/api/contacts/status", status)
    r.post("/api/contacts/email/start", email_start)
    r.post("/api/contacts/email/resend", email_resend)
    r.post("/api/contacts/email/verify", email_verify)
    r.post("/api/contacts/telegram/start", tg_start)
    r.post("/api/contacts/telegram/check", tg_check)
    r.post("/api/contacts/telegram/disconnect", tg_disconnect)


def register_provider_routes(r: Router) -> None:
    """CLI provider probes + server-side login sessions (reference:
    src/server/provider-cli.ts, provider-auth.ts, routes/providers)."""

    def providers_status(ctx):
        from ..providers.cli import probe_connected, probe_installed

        out = {}
        for provider in ("claude", "codex"):
            probe = probe_installed(provider)
            out[provider] = {
                "installed": probe["installed"],
                "version": probe.get("version"),
                "connected": probe_connected(provider),
            }
        return ok(out)

    def auth_start(ctx):
        from .provider_auth import get_auth_manager

        provider = ctx.params["provider"]
        try:
            return ok(get_auth_manager().start(provider), 201)
        except ValueError as e:
            return err(str(e))
        except FileNotFoundError as e:
            return err(str(e), 409)

    def auth_get(ctx):
        from .provider_auth import get_auth_manager

        view = get_auth_manager().active_for(ctx.params["provider"])
        if view is None:
            return err("no active auth session", 404)
        return ok(view)

    def auth_session_get(ctx):
        from .provider_auth import get_auth_manager

        view = get_auth_manager().get(ctx.params["sid"])
        return ok(view) if view else err("unknown session", 404)

    def auth_cancel(ctx):
        from .provider_auth import get_auth_manager

        view = get_auth_manager().cancel(ctx.params["sid"])
        return ok(view) if view else err("unknown session", 404)

    def update_status(ctx):
        from .updater import get_update_checker

        return ok(get_update_checker().status_view())

    def update_check(ctx):
        from .updater import get_update_checker

        checker = get_update_checker()
        checker.force_check(
            ignore_backoff=bool((ctx.body or {}).get("ignoreBackoff"))
        )
        return ok(checker.status_view())

    r.get("/api/update", update_status)
    r.post("/api/update/check", update_check)
    def install_start(ctx):
        from .provider_auth import get_install_manager

        provider = ctx.params["provider"]
        try:
            return ok(get_install_manager().start(provider), 201)
        except ValueError as e:
            return err(str(e))
        except FileNotFoundError as e:
            return err(str(e), 409)

    def install_get(ctx):
        from .provider_auth import get_install_manager

        view = get_install_manager().get(ctx.params["sid"])
        return ok(view) if view else err("unknown session", 404)

    def install_cancel(ctx):
        from .provider_auth import get_install_manager

        view = get_install_manager().cancel(ctx.params["sid"])
        return ok(view) if view else err("unknown session", 404)

    r.get("/api/providers", providers_status)
    r.post("/api/providers/:provider/auth/start", auth_start)
    r.get("/api/providers/:provider/auth", auth_get)
    r.get("/api/providers/auth/sessions/:sid", auth_session_get)
    r.post("/api/providers/auth/sessions/:sid/cancel", auth_cancel)
    r.post("/api/providers/:provider/install/start", install_start)
    r.get("/api/providers/install/sessions/:sid", install_get)
    r.post("/api/providers/install/sessions/:sid/cancel",
           install_cancel)


def register_aux_routes(r: Router) -> None:
    """Templates, identity, watches, prompt sync, TPU manager, feed."""

    def list_templates(ctx):
        from ..core.templates import ROOM_TEMPLATES, WORKER_TEMPLATES

        return ok({
            "rooms": [vars(t) for t in ROOM_TEMPLATES.values()],
            "workers": [vars(t) for t in WORKER_TEMPLATES.values()],
        })

    def instantiate_template(ctx):
        from ..core.templates import instantiate_room_template

        key = (ctx.body or {}).get("template")
        if not key:
            return err("template is required")
        try:
            room = instantiate_room_template(
                ctx.db, key, name=(ctx.body or {}).get("name"),
                worker_model=(ctx.body or {}).get("workerModel", "tpu"),
            )
        except KeyError as e:
            return err(str(e.args[0]), 404)
        return ok(room, 201)

    def identity(ctx):
        from ..core.identity import get_identity

        ident = get_identity(ctx.db, ctx.int_param("id"))
        return ok(ident) if ident else err("room has no wallet", 404)

    def identity_register(ctx):
        from ..core.identity import register_room_identity
        from ..core.wallet import WalletError

        try:
            out = register_room_identity(
                ctx.db, ctx.int_param("id"),
                dry_run=bool((ctx.body or {}).get("dryRun", True)),
            )
        except WalletError as e:
            return err(str(e), 503)
        return ok(out)

    def list_watches_route(ctx):
        from ..core.watches import list_watches

        room_id = ctx.int_query("roomId", 0) or None
        return ok(list_watches(
            ctx.db, room_id
        ))

    def create_watch_route(ctx):
        from ..core.watches import create_watch

        b = ctx.body or {}
        if not b.get("path"):
            return err("path is required")
        try:
            wid = create_watch(
                ctx.db, b["path"], b.get("actionPrompt", ""),
                description=b.get("description"),
                room_id=b.get("roomId"),
            )
        except ValueError as e:
            return err(str(e))
        return ok({"id": wid}, 201)

    def delete_watch_route(ctx):
        from ..core.watches import delete_watch

        if not delete_watch(ctx.db, ctx.int_param("id")):
            return err("watch not found", 404)
        return ok({"deleted": ctx.int_param("id")})

    def export_prompts(ctx):
        from ..core.prompt_sync import export_worker_prompts

        return ok({"paths": export_worker_prompts(
            ctx.db, ctx.int_param("id")
        )})

    def import_prompts(ctx):
        from ..core.prompt_sync import import_worker_prompts

        return ok(import_worker_prompts(
            ctx.db, ctx.int_param("id"),
            force=bool((ctx.body or {}).get("force")),
        ))

    def tpu_status(ctx):
        from .tpu_manager import get_tpu_status

        return ok(get_tpu_status(
            ctx.query.get("model", "qwen3-coder-30b")
        ))

    def tpu_provision(ctx):
        from .tpu_manager import start_provision_session

        session = start_provision_session(
            (ctx.body or {}).get("model", "qwen3-coder-30b")
        )
        return ok({"session": session}, 202)

    def tpu_session(ctx):
        from .tpu_manager import get_provision_session

        s = get_provision_session(ctx.params["sid"])
        return ok(s) if s else err("session not found", 404)

    def tpu_apply(ctx):
        from .tpu_manager import apply_tpu_model_to_all

        return ok(apply_tpu_model_to_all(
            ctx.db, (ctx.body or {}).get("model", "qwen3-coder-30b")
        ))

    def tpu_plan(ctx):
        """Hetero capacity planner: will these model placements fit
        their submeshes (weights at the chosen quant + KV pool +
        workspace vs HBM)? Suggests int8 / more chips when not."""
        from .tpu_manager import plan_mesh

        b = ctx.body or {}
        placements = b.get("placements")
        if not isinstance(placements, list) or not placements:
            return err("placements (a non-empty list) is required")
        try:
            plan = plan_mesh(
                placements,
                int(b.get("totalChips", 8)),
                b.get("hbmPerChipGb"),
            )
        except (KeyError, TypeError, ValueError) as e:
            return err(f"bad placement: {e}")
        return ok(plan)

    def public_feed(ctx):
        return ok(activity_mod.get_public_feed(ctx.db))

    def create_invite(ctx):
        """Mint a member-role JWT so a collaborator can watch/vote
        (reference: src/mcp/tools/invite.ts, re-based on the cloud-JWT
        auth instead of a cloud service)."""
        import os as _os
        import time as _time

        from .auth import JWT_AUD, JWT_ISS, sign_cloud_jwt

        # member POSTs never reach here: access.py whitelists exclude
        # /api/invites, so only agent/user tokens can mint
        secret = knobs.get_str("ROOM_TPU_CLOUD_JWT_SECRET")
        if not secret:
            return err(
                "set ROOM_TPU_CLOUD_JWT_SECRET to enable invites", 503
            )
        try:
            days = float((ctx.body or {}).get("ttlDays", 7))
        except (TypeError, ValueError):
            return err("ttlDays must be a number")
        if not (0 < days <= 365):  # rejects inf/nan and zero/negative
            return err("ttlDays must be in (0, 365]")
        import secrets as _secrets
        claims = {
            "iss": JWT_ISS, "aud": JWT_AUD, "role": "member",
            "sub": f"invite-{_secrets.token_hex(8)}",
            "exp": _time.time() + days * 86400,
        }
        instance = knobs.get_str("ROOM_TPU_INSTANCE_ID")
        if instance:
            claims["instanceId"] = instance
        return ok({
            "token": sign_cloud_jwt(claims, secret),
            "role": "member",
            "expiresInDays": days,
        }, 201)

    r.get("/api/templates", list_templates)
    r.post("/api/templates/instantiate", instantiate_template)
    r.get("/api/rooms/:id/identity", identity)
    r.post("/api/rooms/:id/identity/register", identity_register)
    r.get("/api/watches", list_watches_route)
    r.post("/api/watches", create_watch_route)
    r.delete("/api/watches/:id", delete_watch_route)
    r.post("/api/rooms/:id/prompts/export", export_prompts)
    r.post("/api/rooms/:id/prompts/import", import_prompts)
    def engine_stats(ctx):
        from ..providers.tpu import engines_snapshot

        return ok(engines_snapshot())

    def tpu_health(ctx):
        """Degraded-mode health surface (docs/chaos.md): per-engine
        degradation rung + crash/stall/requeue/shed counters, armed
        fault points, process resilience counters, and the swarm
        runtime's loop-supervision + crash-journal state
        (docs/swarm_recovery.md) — what the TPU panel and external
        monitors poll."""
        from ..core import journal as journal_mod
        from ..core.agent_loop import supervision_snapshot
        from ..core.telemetry import counters_snapshot
        from ..providers.registry import fallback_models
        from ..providers.tpu import engines_snapshot
        from ..chaos import invariants as invariants_mod
        from ..serving import faults as faults_mod

        engines = engines_snapshot()
        keys = ("degradation_level", "engine_crashes", "stall_events",
                "requeues", "shed_turns", "deadline_timeouts",
                "fault_retries", "healthy",
                # tiered KV offload churn (docs/kv_offload.md)
                "offloads", "offload_restores", "offload_prefetches",
                "offload_resident_fallbacks", "offload_reprefills",
                # multi-step decode pipeline (docs/serving.md): window
                # depth, host time blocked on drains, injected-window
                # failures and trimmed overshoot
                "steps_per_dispatch", "host_stall_ms",
                "decode_windows", "window_faults", "overshoot_tokens",
                # in-window speculative decoding (docs/serving.md)
                "spec_rounds", "spec_proposed", "spec_accepted",
                "spec_throttles",
                # SLO scheduler (docs/scheduler.md): interleaved
                # chunked-prefill churn
                "prefill_chunks_interleaved", "prefill_chunk_defers",
                "prefill_chunk_faults",
                # fused-window diagnosability (docs/serving.md): a
                # mixed-mesh fleet must show WHY a replica fell back
                # to split per-chunk dispatches
                "fused_window", "fused_window_mode",
                "fused_window_disabled_reason",
                "fused_windows", "fused_chunks", "fused_dp_windows",
                # shared prefix store + disagg ships (docs/disagg.md)
                "prefix_store_hits", "prefix_store_tokens_reused",
                "prefix_store_pull_fallbacks",
                "prefix_store_publishes", "sessions_shipped")
        summary = {
            name: {k: e[k] for k in keys if k in e}
            for name, e in engines.items()
        }
        # tier occupancy + restore-latency histogram ride along whole:
        # the TPU panel's offload row renders them directly
        for name, e in engines.items():
            if e.get("offload") is not None:
                summary[name]["offload"] = e["offload"]
            # per-engine lifecycle block (docs/lifecycle.md): phase +
            # drain/restore counters, rendered whole by the TPU panel
            if e.get("lifecycle") is not None:
                summary[name]["lifecycle"] = e["lifecycle"]
            # per-engine scheduler block (docs/scheduler.md): per-class
            # queue depth, TTFT/TPOT vs target, chunk budget
            # utilization, and per-class ladder rung — rendered whole
            # by the TPU panel's scheduler table
            if e.get("scheduler") is not None:
                summary[name]["scheduler"] = e["scheduler"]
            # per-class speculative decoding block (docs/serving.md):
            # live gamma, acceptance EMA, spec-off decisions from the
            # gamma tuner — rendered whole by the TPU panel's
            # speculation table
            if e.get("spec") is not None:
                summary[name]["spec"] = e["spec"]
            # dp-sharded fused-window block (docs/serving.md): shard
            # count, sharded-window count and per-shard chunk-row
            # placement — rendered whole by the TPU panel
            if e.get("fused_dp") is not None:
                summary[name]["fused_dp"] = e["fused_dp"]
            # fleet blocks (docs/fleet.md): the aggregate (bare model
            # key) carries router/failover counters + per-replica
            # health scores; each model#rid key carries its replica's
            # placement identity — keyed PER REPLICA so siblings never
            # overwrite each other's engine blocks
            if e.get("fleet") is not None:
                summary[name]["fleet"] = e["fleet"]
            if e.get("replica") is not None:
                summary[name]["replica"] = e["replica"]
            # shared prefix store block (docs/disagg.md): publish/
            # pull/eviction counters + dir occupancy, rendered whole
            # by the TPU panel's prefix-store row
            if e.get("prefix_store") is not None:
                summary[name]["prefix_store"] = e["prefix_store"]
            # invariant witness block (docs/chaosfuzz.md): probe count
            # + per-invariant violation tallies, present only while
            # ROOM_TPU_INVARIANTS is armed — rendered whole by the TPU
            # panel's invariants row
            if e.get("invariants") is not None:
                summary[name]["invariants"] = e["invariants"]
        from ..core.telemetry import histograms_snapshot
        from ..serving import trace as trace_mod

        swarm = supervision_snapshot()
        # db-less contexts (bare router probes) get zeroed journal stats
        swarm["journal"] = journal_mod.stats(ctx.db) if ctx.db else {
            "backlog": 0, "recovered": 0,
            "replay_pending": 0, "replay_consumed": 0,
        }
        # swarm shard tier (docs/swarmshard.md): per-shard state +
        # supervision + journal, and the fleet-wide journal aggregate —
        # with shards the default-domain supervision above only covers
        # rooms that never went through the router
        from ..swarm import maybe_default_router as _maybe_swarm

        swarm_router = _maybe_swarm()
        if swarm_router is not None:
            shard_block = swarm_router.snapshot()
            agg = dict.fromkeys(swarm["journal"], 0)
            for s, shard in zip(shard_block["shards"],
                                swarm_router.shards):
                db = shard.db
                s["journal"] = (
                    journal_mod.stats(db) if db is not None else None
                )
                for k in agg:
                    agg[k] += (s["journal"] or {}).get(k, 0)
            swarm["journal"] = agg
            swarm["shards"] = shard_block
        # multi-process swarm shards (docs/swarmshard.md "Process
        # mode"): per-child state, restart ledger, placement, and the
        # process-spanning SLO merge ride the supervisor's snapshot
        from ..swarm import maybe_default_proc as _maybe_proc

        swarm_proc = _maybe_proc()
        if swarm_proc is not None:
            swarm["proc"] = swarm_proc.snapshot()
        degraded = any(
            e.get("degradation_level", 0) > 0 or not e.get("healthy",
                                                           True)
            for e in engines.values()
        ) or bool(swarm["unhealthy_workers"]) or any(
            # a suspect/dead pod member (docs/podnet.md) is a
            # degraded pod even while the surviving replicas keep the
            # model healthy — monitors must see the partition
            m.get("state") != "alive"
            for e in engines.values()
            for m in (((e.get("fleet") or {}).get("pod") or {})
                      .get("members") or {}).values()
        ) or any(
            # a dead router shard (docs/podnet.md) is degraded until a
            # sibling adopts its journal — its rooms shed meanwhile.
            # "retired" (journal adopted, placement redirected) is the
            # failover COMPLETE, not a degradation.
            s.get("state") == "dead"
            for e in engines.values()
            for s in (((e.get("fleet") or {}).get("router_shards")
                       or {}).get("shards") or {}).values()
        ) or any(
            # a dead SWARM shard (docs/swarmshard.md): its rooms shed
            # until a sibling adopts the file; "retired" is healed
            s.get("state") == "dead"
            for s in (swarm.get("shards") or {}).get("shards", [])
        ) or any(
            # a dead/failed shard CHILD PROCESS (process mode): dead
            # until the restart lands; failed (budget exhausted,
            # sibling adopted the file) stays unhealthy until an
            # operator intervenes
            c.get("state") in ("dead", "failed")
            for c in (swarm.get("proc") or {}).get("children", [])
        )
        from .runtime import lifecycle_snapshot

        return ok({
            "degraded": degraded,
            # process lifecycle (docs/lifecycle.md): phase, how the
            # previous process died, and the last drain's summaries
            "lifecycle": lifecycle_snapshot(),
            "engines": summary,
            "swarm": swarm,
            "faults": faults_mod.snapshot(),
            # process-wide invariant witness (docs/chaosfuzz.md):
            # null while ROOM_TPU_INVARIANTS is off, else the armed
            # snapshot — external monitors alert on .violations > 0
            "invariants": invariants_mod.snapshot()
            if invariants_mod.enabled() else None,
            "counters": counters_snapshot(),
            # cumulative latency histograms (telemetry.observe_ms,
            # le semantics) — the same data /metrics exposes
            "histograms": histograms_snapshot(),
            # turnscope per-class SLO attribution
            # (docs/observability.md): where each class's latency
            # budget went — the TPU panel's attribution table
            "trace": trace_mod.recorder.attribution(),
            "fallback_models": fallback_models(),
        })

    def profiling(ctx):
        from ..utils.profiling import http_profiler

        return ok(http_profiler.snapshot())

    def tpu_trace(ctx):
        """Flight-recorder dump (docs/observability.md): recent turn
        span trees, the SLO-violation/fault evidence ring, global
        serving events (fault firings, re-homes), and per-class
        attribution aggregates."""
        from ..serving import trace as trace_mod

        return ok(trace_mod.recorder.snapshot(
            limit=ctx.int_query("limit", 64)
        ))

    def tpu_profile(ctx):
        """Trigger a bounded on-demand jax.profiler device-trace
        capture (docs/observability.md): writes a TensorBoard trace
        dir under ROOM_TPU_TRACE_DIR while the engines keep serving.
        409 while a capture is already running."""
        from ..utils.profiling import device_profiler

        try:
            started = device_profiler.start(
                ctx.float_body("duration_s", 5.0)
            )
        except RuntimeError as e:
            return err(str(e), 409)
        return ok(started, 202)

    def tpu_profile_status(ctx):
        from ..utils.profiling import device_profiler

        return ok(device_profiler.status())

    def tpu_metrics(ctx):
        """Authed JSON wrapper over the Prometheus exposition (the
        dashboard's view; scrapers use the pre-auth GET /metrics)."""
        from .metrics import metrics_enabled, render_metrics

        if not metrics_enabled():
            return err("metrics disabled (ROOM_TPU_METRICS=0)", 404)
        return ok({"exposition": render_metrics()})

    r.get("/api/profiling/http", profiling)
    r.get("/api/tpu/trace", tpu_trace)
    r.post("/api/tpu/profile", tpu_profile)
    r.get("/api/tpu/profile", tpu_profile_status)
    r.get("/api/tpu/metrics", tpu_metrics)
    r.get("/api/tpu/engines", engine_stats)
    r.get("/api/tpu/health", tpu_health)
    r.get("/api/tpu/status", tpu_status)
    r.post("/api/tpu/provision", tpu_provision)
    r.get("/api/tpu/provision/:sid", tpu_session)
    r.post("/api/tpu/apply", tpu_apply)
    r.post("/api/tpu/plan", tpu_plan)
    r.get("/api/feed", public_feed)
    r.post("/api/invites", create_invite)


# ---- rooms ----

def register_room_routes(r: Router) -> None:
    def list_rooms(ctx):
        return ok([
            dict(room, launched=agent_loop.is_room_launched(room["id"]))
            for room in rooms_mod.list_rooms(ctx.db)
        ])

    def create_room(ctx):
        b = ctx.body or {}
        if not b.get("name"):
            return err("name is required")
        room = rooms_mod.create_room(
            ctx.db,
            b["name"],
            goal=b.get("goal"),
            worker_model=b.get("workerModel", "tpu"),
            queen_model=b.get("queenModel"),
            create_wallet=b.get("createWallet", True),
        )
        return ok(room, 201)

    def get_room(ctx):
        room, e = _room_or_404(ctx)
        return e or ok(room)

    def update_room(ctx):
        room, e = _room_or_404(ctx)
        if e:
            return e
        allowed = {
            "name", "goal", "status", "visibility", "autonomy_mode",
            "max_concurrent_tasks", "worker_model", "queen_cycle_gap_ms",
            "queen_max_turns", "queen_quiet_from", "queen_quiet_until",
            "queen_nickname", "allowed_tools",
        }
        camel = re.compile(r"(?<!^)(?=[A-Z])")
        fields = {}
        for k, v in (ctx.body or {}).items():
            snake = camel.sub("_", k).lower()
            if snake in allowed:
                fields[snake] = v
        rooms_mod.update_room(ctx.db, room["id"], **fields)
        if "config" in (ctx.body or {}):
            ctx.db.execute(
                "UPDATE rooms SET config=? WHERE id=?",
                (json.dumps(ctx.body["config"]), room["id"]),
            )
        return ok(rooms_mod.get_room(ctx.db, room["id"]))

    def delete_room(ctx):
        room, e = _room_or_404(ctx)
        if e:
            return e
        if ctx.runtime:
            ctx.runtime.stop_room(room["id"])
        rooms_mod.delete_room(ctx.db, room["id"])
        return ok({"deleted": room["id"]})

    def start_room(ctx):
        room, e = _room_or_404(ctx)
        if e:
            return e
        if ctx.runtime is None:
            return err("runtime not running", 503)
        if not ctx.runtime.start_room(room["id"]):
            return err("room has no queen", 409)
        return ok({"started": room["id"]})

    def stop_room(ctx):
        room, e = _room_or_404(ctx)
        if e:
            return e
        n = ctx.runtime.stop_room(room["id"]) if ctx.runtime else 0
        return ok({"stopped": room["id"], "loops": n})

    def pause_room(ctx):
        room, e = _room_or_404(ctx)
        if e:
            return e
        rooms_mod.pause_room(ctx.db, room["id"])
        if ctx.runtime:
            ctx.runtime.stop_room(room["id"])
        return ok({"paused": room["id"]})

    def room_status(ctx):
        st = rooms_mod.get_room_status(ctx.db, ctx.int_param("id"))
        if st is None:
            return err("room not found", 404)
        st["launched"] = agent_loop.is_room_launched(ctx.int_param("id"))
        return ok(st)

    def room_cycles(ctx):
        room, e = _room_or_404(ctx)
        if e:
            return e
        return ok(ctx.db.query(
            "SELECT * FROM worker_cycles WHERE room_id=? "
            "ORDER BY id DESC LIMIT 50",
            (room["id"],),
        ))

    def cycle_logs(ctx):
        return ok(get_cycle_logs(ctx.db, ctx.int_param("cycle_id")))

    def room_activity(ctx):
        room, e = _room_or_404(ctx)
        if e:
            return e
        return ok(activity_mod.recent_activity(ctx.db, room["id"]))

    def room_usage(ctx):
        room, e = _room_or_404(ctx)
        if e:
            return e
        return ok(ctx.db.query_one(
            "SELECT COUNT(*) AS cycles, "
            "COALESCE(SUM(input_tokens),0) AS input_tokens, "
            "COALESCE(SUM(output_tokens),0) AS output_tokens "
            "FROM worker_cycles WHERE room_id=?",
            (room["id"],),
        ))

    def room_chat(ctx):
        room, e = _room_or_404(ctx)
        if e:
            return e
        return ok(messages_mod.chat_history(ctx.db, room["id"]))

    def post_chat(ctx):
        room, e = _room_or_404(ctx)
        if e:
            return e
        content = (ctx.body or {}).get("content", "").strip()
        if not content:
            return err("content is required")
        mid = messages_mod.add_chat_message(
            ctx.db, room["id"], "user", content
        )
        # wake the queen to answer (reference queen inbox poll)
        if room["queen_worker_id"] and agent_loop.is_room_launched(
            room["id"]
        ):
            agent_loop.trigger_agent(
                ctx.db, room["id"], room["queen_worker_id"]
            )
        return ok({"id": mid}, 201)

    r.get("/api/rooms", list_rooms)
    r.post("/api/rooms", create_room)
    r.get("/api/rooms/:id", get_room)
    r.put("/api/rooms/:id", update_room)
    r.delete("/api/rooms/:id", delete_room)
    r.post("/api/rooms/:id/start", start_room)
    r.post("/api/rooms/:id/stop", stop_room)
    r.post("/api/rooms/:id/pause", pause_room)
    r.get("/api/rooms/:id/status", room_status)
    r.get("/api/rooms/:id/cycles", room_cycles)
    r.get("/api/cycles/:cycle_id/logs", cycle_logs)
    r.get("/api/rooms/:id/activity", room_activity)
    r.get("/api/rooms/:id/usage", room_usage)
    r.get("/api/rooms/:id/chat", room_chat)
    r.post("/api/rooms/:id/chat", post_chat)


# ---- workers ----

def register_worker_routes(r: Router) -> None:
    def list_workers(ctx):
        room, e = _room_or_404(ctx)
        return e or ok(workers_mod.list_room_workers(ctx.db, room["id"]))

    def create_worker(ctx):
        room, e = _room_or_404(ctx)
        if e:
            return e
        b = ctx.body or {}
        if not b.get("name"):
            return err("name is required")
        wid = workers_mod.create_worker(
            ctx.db, b["name"], b.get("systemPrompt", ""),
            room_id=room["id"], role=b.get("role"),
            model=b.get("model"),
        )
        return ok(workers_mod.get_worker(ctx.db, wid), 201)

    def get_worker(ctx):
        w = workers_mod.get_worker(ctx.db, ctx.int_param("id"))
        return ok(w) if w else err("worker not found", 404)

    def update_worker(ctx):
        wid = ctx.int_param("id")
        if workers_mod.get_worker(ctx.db, wid) is None:
            return err("worker not found", 404)
        b = ctx.body or {}
        mapped = {
            "name": b.get("name"),
            "role": b.get("role"),
            "system_prompt": b.get("systemPrompt"),
            "model": b.get("model"),
            "cycle_gap_ms": b.get("cycleGapMs"),
            "max_turns": b.get("maxTurns"),
        }
        workers_mod.update_worker(
            ctx.db, wid,
            **{k: v for k, v in mapped.items() if v is not None},
        )
        return ok(workers_mod.get_worker(ctx.db, wid))

    def delete_worker(ctx):
        wid = ctx.int_param("id")
        w = workers_mod.get_worker(ctx.db, wid)
        if w is None:
            return err("worker not found", 404)
        room = rooms_mod.get_room(ctx.db, w["room_id"]) if w["room_id"] \
            else None
        if room and room["queen_worker_id"] == wid:
            return err("cannot delete the queen", 409)
        agent_loop.pause_agent(wid)
        workers_mod.delete_worker(ctx.db, wid)
        return ok({"deleted": wid})

    def start_worker(ctx):
        """The cross-process nudge target (reference mcp/nudge.ts)."""
        wid = ctx.int_param("id")
        w = workers_mod.get_worker(ctx.db, wid)
        if w is None or w["room_id"] is None:
            return err("worker not found", 404)
        handle = agent_loop.trigger_agent(
            ctx.db, w["room_id"], wid,
            allow_cold_start=bool((ctx.body or {}).get("coldStart")),
        )
        return ok({"triggered": handle is not None})

    r.get("/api/rooms/:id/workers", list_workers)
    r.post("/api/rooms/:id/workers", create_worker)
    r.get("/api/workers/:id", get_worker)
    r.put("/api/workers/:id", update_worker)
    r.delete("/api/workers/:id", delete_worker)
    r.post("/api/workers/:id/start", start_worker)


# ---- goals ----

def register_goal_routes(r: Router) -> None:
    def list_goals(ctx):
        room, e = _room_or_404(ctx)
        return e or ok(goals_mod.get_goal_tree(ctx.db, room["id"]))

    def create_goal(ctx):
        room, e = _room_or_404(ctx)
        if e:
            return e
        b = ctx.body or {}
        if not b.get("description"):
            return err("description is required")
        gid = goals_mod.create_goal(
            ctx.db, room["id"], b["description"],
            parent_goal_id=b.get("parentGoalId"),
            assigned_worker_id=b.get("workerId"),
        )
        return ok(goals_mod.get_goal(ctx.db, gid), 201)

    def complete(ctx):
        gid = ctx.int_param("id")
        if goals_mod.get_goal(ctx.db, gid) is None:
            return err("goal not found", 404)
        goals_mod.complete_goal(ctx.db, gid)
        return ok(goals_mod.get_goal(ctx.db, gid))

    def abandon(ctx):
        gid = ctx.int_param("id")
        if goals_mod.get_goal(ctx.db, gid) is None:
            return err("goal not found", 404)
        goals_mod.abandon_goal(ctx.db, gid)
        return ok(goals_mod.get_goal(ctx.db, gid))

    r.get("/api/rooms/:id/goals", list_goals)
    r.post("/api/rooms/:id/goals", create_goal)
    r.post("/api/goals/:id/complete", complete)
    r.post("/api/goals/:id/abandon", abandon)


# ---- tasks + runs ----

def register_task_routes(r: Router) -> None:
    def list_tasks(ctx):
        room_id = ctx.int_query("roomId", 0) or None
        return ok(task_runner.list_tasks(
            ctx.db, room_id
        ))

    def create_task(ctx):
        b = ctx.body or {}
        for field in ("name", "prompt"):
            if not b.get(field):
                return err(f"{field} is required")
        try:
            tid = task_runner.create_task(
                ctx.db, b["name"], b["prompt"],
                trigger_type=b.get("triggerType", "cron"),
                cron_expression=b.get("cronExpression"),
                scheduled_at=b.get("scheduledAt"),
                room_id=b.get("roomId"),
                worker_id=b.get("workerId"),
                session_continuity=bool(b.get("sessionContinuity")),
                max_runs=b.get("maxRuns"),
                description=b.get("description"),
                timeout_minutes=b.get("timeoutMinutes"),
                max_turns=b.get("maxTurns"),
            )
        except ValueError as e:
            return err(str(e))
        return ok(task_runner.get_task(ctx.db, tid), 201)

    def get_task(ctx):
        t = task_runner.get_task(ctx.db, ctx.int_param("id"))
        return ok(t) if t else err("task not found", 404)

    def delete_task(ctx):
        if not task_runner.delete_task(ctx.db, ctx.int_param("id")):
            return err("task not found", 404)
        return ok({"deleted": ctx.int_param("id")})

    def run_now(ctx):
        tid = ctx.int_param("id")
        if task_runner.get_task(ctx.db, tid) is None:
            return err("task not found", 404)
        if ctx.runtime is None:
            return err("runtime not running", 503)
        queued = ctx.runtime.run_task_now(tid)
        return ok({"queued": queued})

    def pause(ctx):
        task_runner.pause_task(ctx.db, ctx.int_param("id"))
        return ok(task_runner.get_task(ctx.db, ctx.int_param("id")))

    def resume(ctx):
        task_runner.resume_task(ctx.db, ctx.int_param("id"))
        return ok(task_runner.get_task(ctx.db, ctx.int_param("id")))

    def task_runs(ctx):
        return ok(ctx.db.query(
            "SELECT * FROM task_runs WHERE task_id=? ORDER BY id DESC "
            "LIMIT 50",
            (ctx.int_param("id"),),
        ))

    def get_run(ctx):
        run = ctx.db.query_one(
            "SELECT * FROM task_runs WHERE id=?",
            (ctx.int_param("id"),),
        )
        return ok(run) if run else err("run not found", 404)

    def run_logs(ctx):
        return ok(ctx.db.query(
            "SELECT * FROM console_logs WHERE run_id=? ORDER BY seq",
            (ctx.int_param("id"),),
        ))

    r.get("/api/tasks", list_tasks)
    r.post("/api/tasks", create_task)
    r.get("/api/tasks/:id", get_task)
    r.delete("/api/tasks/:id", delete_task)
    r.post("/api/tasks/:id/run", run_now)
    r.post("/api/tasks/:id/pause", pause)
    r.post("/api/tasks/:id/resume", resume)
    r.get("/api/tasks/:id/runs", task_runs)
    r.get("/api/runs/:id", get_run)
    r.get("/api/runs/:id/logs", run_logs)


# ---- memory ----

def register_memory_routes(r: Router) -> None:
    def search(ctx):
        q = ctx.query.get("q", "")
        room_id = ctx.int_query("roomId", 0) or None
        limit = ctx.int_query("limit", 10)
        if not q:
            # memory browser: empty query lists the newest entities —
            # in the SAME row shape as hybrid_search (the panel renders
            # entity_id/observations/score either way; the render
            # harness caught the old id/content shape producing
            # memDelete(undefined) buttons on first load)
            rows = ctx.db.query(
                "SELECT e.* FROM entities e "
                + ("WHERE e.room_id=? " if room_id else "")
                + "ORDER BY e.id DESC LIMIT ?",
                ((room_id, limit) if room_id else (limit,)),
            )
            return ok([{
                "entity_id": row["id"],
                "name": row["name"],
                "category": row.get("category"),
                "score": 0.0,
                # same 5-newest cap as hybrid_search rows, oldest
                # first (an entity can carry hundreds of observations)
                "observations": [
                    o["content"] for o in reversed(
                        memory_mod.get_observations(
                            ctx.db, row["id"],
                            newest_first=True, limit=5,
                        ))
                ],
            } for row in rows])
        from ..core.queen_tools import _embed_query

        return ok(memory_mod.hybrid_search(
            ctx.db, q, query_vector=_embed_query(q),
            room_id=room_id,
            limit=limit,
        ))

    def remember(ctx):
        b = ctx.body or {}
        for field in ("name", "content"):
            if not b.get(field):
                return err(f"{field} is required")
        eid = memory_mod.remember(
            ctx.db, b["name"], b["content"],
            category=b.get("category"), room_id=b.get("roomId"),
        )
        return ok({"entityId": eid}, 201)

    def get_entity(ctx):
        ent = memory_mod.get_entity(ctx.db, ctx.int_param("id"))
        if ent is None:
            return err("entity not found", 404)
        ent["observations"] = memory_mod.get_observations(
            ctx.db, ent["id"]
        )
        ent["relations"] = memory_mod.get_relations(ctx.db, ent["id"])
        return ok(ent)

    def delete_entity(ctx):
        if not memory_mod.delete_entity(ctx.db, ctx.int_param("id")):
            return err("entity not found", 404)
        return ok({"deleted": ctx.int_param("id")})

    r.get("/api/memory/search", search)
    r.post("/api/memory", remember)
    r.get("/api/memory/:id", get_entity)
    r.delete("/api/memory/:id", delete_entity)


# ---- decisions ----

def register_decision_routes(r: Router) -> None:
    def list_decisions(ctx):
        room, e = _room_or_404(ctx)
        return e or ok(ctx.db.query(
            "SELECT * FROM quorum_decisions WHERE room_id=? "
            "ORDER BY id DESC LIMIT 50",
            (room["id"],),
        ))

    def _normalize_vote(body) -> str:
        """UI vocabulary (approve/reject, reference VotesPanel) ->
        quorum core vocabulary (yes/no); anything non-string passes
        through as "" so the core's validation 409s instead of a
        TypeError 500."""
        v = (body or {}).get("vote", "")
        if not isinstance(v, str):
            return ""
        return {"approve": "yes", "reject": "no"}.get(v, v)

    def vote(ctx):
        b = ctx.body or {}
        if not b.get("workerId"):
            # worker ballots need an actor; the human keeper votes via
            # /keeper-vote (worker 0 used to FK-crash into a 500 here)
            return err("workerId is required (keeper votes go to "
                       "/api/decisions/:id/keeper-vote)", 400)
        try:
            d = quorum_mod.vote(
                ctx.db, ctx.int_param("id"), ctx.int_body("workerId"),
                _normalize_vote(b), b.get("reasoning"),
            )
        except quorum_mod.QuorumError as e:
            return err(str(e), 409)
        return ok(d)

    def keeper_vote(ctx):
        # the core's keeper_vote approves on anything but "no", so the
        # mapping (reject -> no) must happen before the call — an
        # unmapped "reject" would INVERT a keeper veto into approval
        try:
            d = quorum_mod.keeper_vote(
                ctx.db, ctx.int_param("id"),
                _normalize_vote(ctx.body)
            )
        except quorum_mod.QuorumError as e:
            return err(str(e), 409)
        return ok(d)

    def object_to(ctx):
        b = ctx.body or {}
        try:
            d = quorum_mod.object_to(
                ctx.db, ctx.int_param("id"),
                ctx.int_body("workerId", 0), b.get("reason", ""),
            )
        except quorum_mod.QuorumError as e:
            return err(str(e), 409)
        return ok(d)

    r.get("/api/rooms/:id/decisions", list_decisions)
    r.post("/api/decisions/:id/vote", vote)
    r.post("/api/decisions/:id/keeper-vote", keeper_vote)
    r.post("/api/decisions/:id/object", object_to)


# ---- skills + self-mod ----

def register_skill_routes(r: Router) -> None:
    def list_skills(ctx):
        room_id = ctx.int_query("roomId", 0) or None
        return ok(skills_mod.list_skills(
            ctx.db, room_id
        ))

    def create(ctx):
        b = ctx.body or {}
        for field in ("name", "content"):
            if not b.get(field):
                return err(f"{field} is required")
        sid = skills_mod.create_skill(
            ctx.db, b["name"], b["content"], room_id=b.get("roomId"),
            activation_context=b.get("activationContext"),
            auto_activate=bool(b.get("autoActivate")),
        )
        return ok(skills_mod.get_skill(ctx.db, sid), 201)

    def update(ctx):
        sid = ctx.int_param("id")
        if skills_mod.get_skill(ctx.db, sid) is None:
            return err("skill not found", 404)
        content = (ctx.body or {}).get("content")
        if content is None:
            return err("content is required")
        skills_mod.update_skill(ctx.db, sid, content)
        return ok(skills_mod.get_skill(ctx.db, sid))

    def delete(ctx):
        if not skills_mod.delete_skill(ctx.db, ctx.int_param("id")):
            return err("skill not found", 404)
        return ok({"deleted": ctx.int_param("id")})

    def audit(ctx):
        room_id = ctx.int_query("roomId", 0) or None
        return ok(selfmod_mod.audit_log(
            ctx.db, room_id
        ))

    def revert(ctx):
        try:
            done = selfmod_mod.revert_modification(
                ctx.db, ctx.int_param("id")
            )
        except selfmod_mod.SelfModError as e:
            return err(str(e), 409)
        if not done:
            return err("nothing to revert", 409)
        return ok({"reverted": ctx.int_param("id")})

    r.get("/api/skills", list_skills)
    r.post("/api/skills", create)
    r.put("/api/skills/:id", update)
    r.delete("/api/skills/:id", delete)
    r.get("/api/self-mod/audit", audit)
    r.post("/api/self-mod/:id/revert", revert)


# ---- escalations ----

def register_escalation_routes(r: Router) -> None:
    def list_escalations(ctx):
        room_id = ctx.int_query("roomId", 0) or None
        return ok(escalations_mod.pending_escalations(
            ctx.db, room_id
        ))

    def answer(ctx):
        eid = ctx.int_param("id")
        esc = escalations_mod.get_escalation(ctx.db, eid)
        if esc is None:
            return err("escalation not found", 404)
        answer_text = (ctx.body or {}).get("answer", "").strip()
        if not answer_text:
            return err("answer is required")
        escalations_mod.answer_escalation(ctx.db, eid, answer_text)
        # answered escalations wake the asking room's queen
        room = rooms_mod.get_room(ctx.db, esc["room_id"])
        if room and room["queen_worker_id"]:
            agent_loop.trigger_agent(
                ctx.db, room["id"], room["queen_worker_id"]
            )
        return ok(escalations_mod.get_escalation(ctx.db, eid))

    def dismiss(ctx):
        eid = ctx.int_param("id")
        if escalations_mod.get_escalation(ctx.db, eid) is None:
            return err("escalation not found", 404)
        escalations_mod.dismiss_escalation(ctx.db, eid)
        return ok(escalations_mod.get_escalation(ctx.db, eid))

    r.get("/api/escalations", list_escalations)
    r.post("/api/escalations/:id/answer", answer)
    r.post("/api/escalations/:id/dismiss", dismiss)


# ---- inter-room messages ----

def register_message_routes(r: Router) -> None:
    def list_messages(ctx):
        room, e = _room_or_404(ctx)
        return e or ok(ctx.db.query(
            "SELECT * FROM room_messages WHERE room_id=? "
            "ORDER BY id DESC LIMIT 100",
            (room["id"],),
        ))

    def send(ctx):
        room, e = _room_or_404(ctx)
        if e:
            return e
        b = ctx.body or {}
        if b.get("toRoomId") is None or not b.get("body"):
            return err("toRoomId and body are required")
        to_room = ctx.int_body("toRoomId")
        if rooms_mod.get_room(ctx.db, to_room) is None:
            return err("destination room not found", 404)
        out_id, in_id = messages_mod.send_room_message(
            ctx.db, room["id"], to_room, b.get("subject", ""),
            b["body"],
        )
        return ok({"outboundId": out_id, "inboundId": in_id}, 201)

    def mark_read(ctx):
        messages_mod.mark_message_read(ctx.db, ctx.int_param("id"))
        return ok({"read": ctx.int_param("id")})

    def reply(ctx):
        mid = ctx.int_param("id")
        msg = ctx.db.query_one(
            "SELECT * FROM room_messages WHERE id=?", (mid,)
        )
        if msg is None:
            return err("message not found", 404)
        body = (ctx.body or {}).get("body", "").strip()
        if not body:
            return err("body is required")
        try:
            from_room = int(msg["from_room_id"])
        except (TypeError, ValueError):
            return err("message has no replyable source", 409)
        messages_mod.send_room_message(
            ctx.db, msg["room_id"], from_room,
            f"Re: {msg['subject']}", body,
        )
        messages_mod.mark_message_replied(ctx.db, mid)
        return ok({"replied": mid})

    r.get("/api/rooms/:id/messages", list_messages)
    r.post("/api/rooms/:id/messages", send)
    r.post("/api/messages/:id/read", mark_read)
    r.post("/api/messages/:id/reply", reply)


# ---- credentials ----

def register_credential_routes(r: Router) -> None:
    def list_creds(ctx):
        room, e = _room_or_404(ctx)
        return e or ok(credentials_mod.list_credentials(
            ctx.db, room["id"]
        ))

    def store(ctx):
        room, e = _room_or_404(ctx)
        if e:
            return e
        b = ctx.body or {}
        for field in ("name", "value"):
            if not b.get(field):
                return err(f"{field} is required")
        credentials_mod.store_credential(
            ctx.db, room["id"], b["name"], b["value"],
            type_=b.get("type", "other"),
        )
        return ok({"stored": b["name"]}, 201)

    def delete(ctx):
        room, e = _room_or_404(ctx)
        if e:
            return e
        name = ctx.params["name"]
        if not credentials_mod.delete_credential(ctx.db, room["id"], name):
            return err("credential not found", 404)
        return ok({"deleted": name})

    r.get("/api/rooms/:id/credentials", list_creds)
    r.post("/api/rooms/:id/credentials", store)
    r.delete("/api/rooms/:id/credentials/:name", delete)


# ---- wallet ----

def register_wallet_routes(r: Router) -> None:
    def get_wallet(ctx):
        room, e = _room_or_404(ctx)
        if e:
            return e
        w = wallet_mod.get_room_wallet(ctx.db, room["id"])
        if w is None:
            return err("room has no wallet", 404)
        safe = {k: v for k, v in w.items()
                if k != "private_key_encrypted"}
        return ok(safe)

    def transactions(ctx):
        room, e = _room_or_404(ctx)
        if e:
            return e
        w = wallet_mod.get_room_wallet(ctx.db, room["id"])
        if w is None:
            return err("room has no wallet", 404)
        return ok(wallet_mod.list_transactions(ctx.db, w["id"]))

    def balance(ctx):
        room, e = _room_or_404(ctx)
        if e:
            return e
        try:
            native = wallet_mod.get_native_balance(ctx.db, room["id"])
            usdc = wallet_mod.get_token_balance(ctx.db, room["id"])
        except wallet_mod.WalletError as ex:
            return err(str(ex), 503)
        return ok({"native_wei": str(native), "usdc_units": str(usdc)})

    def withdraw(ctx):
        """ERC-20 withdraw (reference: routes/wallet.ts:162-230
        POST /wallet/withdraw): validate → sign → broadcast; fails
        closed (503) without chain RPC."""
        room, e = _room_or_404(ctx)
        if e:
            return e
        body = ctx.body or {}
        to = (body.get("to") or "").strip()
        amount_raw = str(body.get("amount") or "").strip()
        token = body.get("token") or "usdc"
        if not to or not amount_raw:
            return err("Missing required fields: to, amount")
        if not re.fullmatch(r"0x[0-9a-fA-F]{40}", to):
            return err("Invalid address")
        try:
            amount = int(amount_raw)
        except ValueError:
            return err("Invalid amount: integer token units required")
        if amount <= 0:
            return err("Invalid amount")
        try:
            out = wallet_mod.transfer_token(
                ctx.db, room["id"], to, amount, token,
                description=body.get("description"),
            )
        except wallet_mod.WalletError as ex:
            status = 503 if "unreachable" in str(ex) else 400
            return err(str(ex), status)
        return ok(out)

    r.get("/api/rooms/:id/wallet", get_wallet)
    r.get("/api/rooms/:id/wallet/transactions", transactions)
    r.get("/api/rooms/:id/wallet/balance", balance)
    r.post("/api/rooms/:id/wallet/withdraw", withdraw)


# ---- settings / status / clerk ----

def register_settings_routes(r: Router) -> None:
    SECRET_HINTS = ("key", "token", "secret", "password")

    def get_settings(ctx):
        out = {}
        for k, v in messages_mod.all_settings(ctx.db).items():
            if any(h in k.lower() for h in SECRET_HINTS) and v:
                out[k] = "***"
            else:
                out[k] = v
        return ok(out)

    def put_settings(ctx):
        for k, v in (ctx.body or {}).items():
            messages_mod.set_setting(ctx.db, str(k),
                                     None if v is None else str(v))
        return ok({"updated": len(ctx.body or {})})

    r.get("/api/settings", get_settings)
    r.put("/api/settings", put_settings)


def register_status_routes(r: Router) -> None:
    def status(ctx):
        import jax

        rooms_active = len(rooms_mod.list_rooms(ctx.db, "active"))
        return ok({
            "version": __version__,
            "platform": jax.default_backend(),
            "devices": jax.device_count(),
            "activeRooms": rooms_active,
            "runningWorkers": agent_loop.running_workers(),
            "runtime": ctx.runtime is not None,
        })

    def models(ctx):
        out = {}
        for model in ("tpu:qwen3-coder-30b", "tpu:qwen2.5-72b",
                      "openai:gpt-4o-mini", "anthropic:claude-3-5-haiku",
                      "ollama:qwen3-coder:30b"):
            out[model] = get_model_auth_status(model, ctx.db)
        return ok(out)

    r.get("/api/status", status)
    r.get("/api/models/status", models)


def register_clerk_routes(r: Router) -> None:
    def clerk_messages(ctx):
        return ok(list(reversed(ctx.db.query(
            "SELECT * FROM clerk_messages ORDER BY id DESC LIMIT 100"
        ))))

    def clerk_message(ctx):
        content = (ctx.body or {}).get("content", "").strip()
        if not content:
            return err("content is required")
        from ..core.clerk import run_clerk_turn

        reply = run_clerk_turn(ctx.db, content, runtime=ctx.runtime)
        return ok(reply, 201)

    def clerk_usage(ctx):
        return ok(ctx.db.query(
            "SELECT * FROM clerk_usage ORDER BY id DESC LIMIT 100"
        ))

    r.get("/api/clerk/messages", clerk_messages)
    r.post("/api/clerk/message", clerk_message)
    r.get("/api/clerk/usage", clerk_usage)
