"""Tokenizers for the serving engine.

Production path: the upstream Qwen BPE via a local `transformers`
tokenizer directory (no network; pass the path or set
ROOM_TPU_TOKENIZER_PATH). Hermetic path: a byte-level tokenizer with the
same special-token interface, used by tests and bench so the whole stack
runs with zero downloads.

The chat template follows the Qwen convention the reference's Ollama path
relied on (im_start/im_end role blocks; tool calls fenced by
<tool_call>/</tool_call> carrying JSON).
"""

from __future__ import annotations

import json
import os
from typing import Optional, Protocol

from ..utils import knobs


class Tokenizer(Protocol):
    vocab_size: int
    eos_id: int
    pad_id: int

    def encode(self, text: str) -> list[int]: ...
    def decode(self, ids: list[int]) -> str: ...


class ByteTokenizer:
    """Bytes 0-255 + specials. Deterministic, download-free."""

    PAD, BOS, EOS = 256, 257, 258
    IM_START, IM_END = 259, 260
    TOOL_START, TOOL_END = 261, 262

    SPECIAL_STRINGS = {
        IM_START: "<|im_start|>",
        IM_END: "<|im_end|>",
        TOOL_START: "<tool_call>",
        TOOL_END: "</tool_call>",
    }

    def __init__(self) -> None:
        self.vocab_size = 512
        self.pad_id = self.PAD
        self.bos_id = self.BOS
        self.eos_id = self.EOS

    def encode(self, text: str) -> list[int]:
        out: list[int] = []
        i = 0
        specials = sorted(
            self.SPECIAL_STRINGS.items(), key=lambda kv: -len(kv[1])
        )
        while i < len(text):
            for tok_id, s in specials:
                if text.startswith(s, i):
                    out.append(tok_id)
                    i += len(s)
                    break
            else:
                out.extend(text[i].encode("utf-8"))
                i += 1
        return out

    def decode(self, ids: list[int]) -> str:
        parts: list[str] = []
        buf = bytearray()
        for t in ids:
            t = int(t)
            if t < 256:
                buf.append(t)
            else:
                if buf:
                    parts.append(buf.decode("utf-8", errors="replace"))
                    buf = bytearray()
                if t in self.SPECIAL_STRINGS:
                    parts.append(self.SPECIAL_STRINGS[t])
                # PAD/BOS/EOS render as nothing
        if buf:
            parts.append(buf.decode("utf-8", errors="replace"))
        return "".join(parts)


class HFTokenizer:
    """transformers-backed tokenizer loaded from a local directory."""

    def __init__(self, path: str) -> None:
        from transformers import AutoTokenizer

        self._tok = AutoTokenizer.from_pretrained(
            path, local_files_only=True
        )
        self.vocab_size = len(self._tok)
        self.eos_id = self._tok.eos_token_id
        pad = self._tok.pad_token_id
        self.pad_id = pad if pad is not None else self.eos_id  # id 0 is a valid pad

    def encode(self, text: str) -> list[int]:
        return self._tok.encode(text, add_special_tokens=False)

    def decode(self, ids: list[int]) -> str:
        return self._tok.decode(ids, skip_special_tokens=False)


def load_tokenizer(path: Optional[str] = None) -> Tokenizer:
    path = path or knobs.get_str("ROOM_TPU_TOKENIZER_PATH")
    if path and os.path.isdir(path):
        return HFTokenizer(path)
    return ByteTokenizer()


# ---- chat template ----

def render_chat(
    messages: list[dict],
    tools: Optional[list[dict]] = None,
    add_generation_prompt: bool = True,
) -> str:
    """Render a conversation in the upstream Qwen3 chat template.

    Faithful to the Jinja template shipped in the Qwen3 tokenizer config
    (the model family the reference pinned via Ollama's qwen3-coder:30b —
    reference: src/shared/local-model.ts:3-5): tools render as a
    ``# Tools`` section inside the system block with one JSON signature
    per line in <tools></tools>; assistant tool calls are
    ``<tool_call>\\n{...}\\n</tool_call>`` blocks; role="tool" results
    are <tool_response> blocks, with consecutive tool messages sharing
    one ``<|im_start|>user`` envelope. Golden fixtures:
    tests/fixtures/chat_template/.

    messages: [{role, content, tool_calls?}]; tools: OpenAI-format defs.
    """
    out: list[str] = []
    msgs = list(messages)

    if tools:
        out.append("<|im_start|>system\n")
        if msgs and msgs[0].get("role") == "system":
            out.append(f"{msgs[0]['content']}\n\n")
            msgs = msgs[1:]
        out.append(
            "# Tools\n\nYou may call one or more functions to assist "
            "with the user query.\n\nYou are provided with function "
            "signatures within <tools></tools> XML tags:\n<tools>"
        )
        for t in tools:
            out.append("\n" + json.dumps(t, ensure_ascii=False))
        out.append(
            "\n</tools>\n\nFor each function call, return a json object "
            "with function name and arguments within <tool_call>"
            "</tool_call> XML tags:\n<tool_call>\n{\"name\": "
            "<function-name>, \"arguments\": <args-json-object>}\n"
            "</tool_call><|im_end|>\n"
        )
    elif msgs and msgs[0].get("role") == "system":
        out.append(
            f"<|im_start|>system\n{msgs[0]['content']}<|im_end|>\n"
        )
        msgs = msgs[1:]

    for i, m in enumerate(msgs):
        role = m.get("role")
        content = m.get("content") or ""
        if role == "assistant":
            out.append("<|im_start|>assistant\n")
            out.append(content)
            for call in m.get("tool_calls") or []:
                fn = call.get("function", call)
                args = fn.get("arguments")
                if isinstance(args, str):
                    try:
                        args = json.loads(args)
                    except json.JSONDecodeError:
                        pass
                out.append(
                    "\n<tool_call>\n"
                    + json.dumps(
                        {"name": fn.get("name"), "arguments": args},
                        ensure_ascii=False,
                    )
                    + "\n</tool_call>"
                )
            out.append("<|im_end|>\n")
        elif role == "tool":
            prev_tool = i > 0 and msgs[i - 1].get("role") == "tool"
            if not prev_tool:
                out.append("<|im_start|>user")
            out.append(f"\n<tool_response>\n{content}\n</tool_response>")
            next_tool = (
                i + 1 < len(msgs) and msgs[i + 1].get("role") == "tool"
            )
            if not next_tool:
                out.append("<|im_end|>\n")
        else:
            out.append(f"<|im_start|>{role}\n{content}<|im_end|>\n")

    if add_generation_prompt:
        out.append("<|im_start|>assistant\n")
    return "".join(out)


def extract_tool_call(text: str) -> Optional[dict]:
    """Parse the first <tool_call>...</tool_call> block, if any."""
    start = text.find("<tool_call>")
    if start < 0:
        return None
    end = text.find("</tool_call>", start)
    if end < 0:
        return None
    payload = text[start + len("<tool_call>"):end].strip()
    try:
        out = json.loads(payload)
    except json.JSONDecodeError:
        return None
    return out if isinstance(out, dict) else None
