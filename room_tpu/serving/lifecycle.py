"""Durable process lifecycle: drain manifests, clean-shutdown markers,
spool hygiene (docs/lifecycle.md).

The chaos layer (docs/chaos.md), the cycle journal
(docs/swarm_recovery.md), and the decode pipeline's crash recovery all
keep a *live* process correct under failure — but the process boundary
was a cliff: a SIGTERM (the normal event in any production rollout)
dropped every in-flight turn and every hibernated session, because the
offload spool defaulted to a per-process tempdir and nothing wrote
restart state. This module is the durable half of the fix:

- a **versioned session manifest** written at graceful drain
  (``ServingEngine.drain``): per session — id, full token history,
  pending token, generation counter, sampling params, and (when the KV
  could be spooled) a dtype/layout-fingerprinted spool file with a
  per-file sha256. The next boot (``restore_from_manifest``) validates
  every entry against the live engine's config; anything corrupt,
  truncated, or config-mismatched falls back to a history re-prefill —
  never a crash, and greedy continuations stay token-identical either
  way;
- a **clean-shutdown marker** the server runtime consumes at boot, so
  journal recovery can tell a rolling restart from a crash;
- an **orphan spool sweep** that deletes ``*.kvspool`` files left by
  dead processes (age-thresholded, and never a file a live manifest
  still references) — an unclean exit no longer leaks the spool dir
  forever.

Failure policy: every read/write here sits behind the ``shutdown_io``
fault point and degrades — a failed manifest write loses warmth, not
the exit; a failed read cold-starts. Nothing in this module may hang or
crash a drain or a boot.

Env knobs (docs/lifecycle.md):

    ROOM_TPU_LIFECYCLE           enable drain/restore on the provider
                                 path ("1"/"0"; deployment default on,
                                 library engines opt in per call)
    ROOM_TPU_LIFECYCLE_DIR       durable state root (default
                                 <tmp>/room_tpu_lifecycle — stable
                                 across process restarts on one host)
    ROOM_TPU_DRAIN_DEADLINE_S    drain budget (default 30); past it
                                 remaining sessions are abandoned to
                                 the manifest's intent record instead
                                 of blocking the exit
    ROOM_TPU_SPOOL_SWEEP_AGE_S   orphan spool files older than this are
                                 swept at boot (default 3600)
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile
import time
from typing import Iterable, Optional

from . import faults
from ..utils import knobs
from .faults import FaultError

__all__ = [
    "MANIFEST_VERSION", "MANIFEST_NAME", "MARKER_NAME",
    "lifecycle_enabled_from_env", "lifecycle_root", "engine_dir",
    "drain_deadline_s", "sweep_age_s", "file_sha256",
    "write_manifest", "read_manifest", "consume_manifest",
    "manifest_spool_files", "manifest_subdirs", "spool_owner_pid",
    "sweep_orphans",
    "write_clean_marker", "consume_clean_marker", "record_boot",
]

MANIFEST_VERSION = 1
MANIFEST_NAME = "manifest.json"
MARKER_NAME = "clean_shutdown.marker"
STATE_NAME = "lifecycle_state.json"
SPOOL_SUFFIX = ".kvspool"


def lifecycle_enabled_from_env(default: str = "0") -> bool:
    return knobs.get_bool("ROOM_TPU_LIFECYCLE", default=default)


def lifecycle_root() -> str:
    """Durable state root. The default lives under the system temp dir
    — stable across process restarts on one host (the rolling-restart
    case this subsystem exists for), without writing to $HOME from
    library code. Deployments that need reboot durability point
    ROOM_TPU_LIFECYCLE_DIR at a real volume."""
    return knobs.get_str("ROOM_TPU_LIFECYCLE_DIR") or os.path.join(
        tempfile.gettempdir(), "room_tpu_lifecycle"
    )


def engine_dir(model_name: str) -> str:
    """Per-model manifest/spool dir: rolling restarts hand state from
    exactly one old process to one new one per served model."""
    slug = "".join(
        c if c.isalnum() or c in "._-" else "_" for c in model_name
    )
    return os.path.join(lifecycle_root(), "engines", slug)


def drain_deadline_s() -> float:
    try:
        return knobs.get_float("ROOM_TPU_DRAIN_DEADLINE_S")
    except ValueError:
        return 30.0


def sweep_age_s() -> float:
    try:
        return knobs.get_float("ROOM_TPU_SPOOL_SWEEP_AGE_S")
    except ValueError:
        return 3600.0


def file_sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


# ---- manifest ----

def write_manifest(dir_path: str, manifest: dict) -> bool:
    """Atomic (tmp + rename) manifest write. Returns False — never
    raises, never hangs the drain — on an injected ``shutdown_io``
    fault or a real I/O error."""
    try:
        faults.maybe_fail("shutdown_io")
        os.makedirs(dir_path, exist_ok=True)
        path = os.path.join(dir_path, MANIFEST_NAME)
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(manifest, f, separators=(",", ":"))
        os.replace(tmp, path)
        return True
    except (FaultError, OSError, TypeError, ValueError):
        return False


def read_manifest(dir_path: str) -> Optional[dict]:
    """Load + minimally validate the manifest. Any failure — injected
    ``shutdown_io`` fault, missing/truncated/garbage file — returns
    None: the caller cold-starts (or re-prefills), never crashes."""
    path = os.path.join(dir_path, MANIFEST_NAME)
    try:
        faults.maybe_fail("shutdown_io")
        with open(path, "r", encoding="utf-8") as f:
            manifest = json.load(f)
    except (FaultError, OSError, ValueError):
        return None
    if not isinstance(manifest, dict) or \
            not isinstance(manifest.get("sessions"), list):
        return None
    return manifest


def next_generation(dir_path: str) -> int:
    """Monotonic manifest generation: max of the previous manifest's
    and the per-engine-dir state sidecar's, + 1. The sidecar is what
    makes the counter honest — restore_from_manifest *consumes*
    (unlinks) the manifest, so without it every normal drain→restore
    cycle reset the count to 1 and it never actually counted rolling
    restarts. Deliberately NOT behind the shutdown_io fault point —
    it's advisory bookkeeping and must not consume a one-shot fault
    budget armed for the real manifest write."""
    last = 0
    for name in (MANIFEST_NAME, STATE_NAME):
        try:
            with open(os.path.join(dir_path, name), "r",
                      encoding="utf-8") as f:
                last = max(
                    last,
                    int((json.load(f) or {}).get("generation") or 0),
                )
        except (OSError, ValueError, TypeError, AttributeError):
            pass
    gen = last + 1
    try:
        os.makedirs(dir_path, exist_ok=True)
        tmp = os.path.join(dir_path, STATE_NAME + ".tmp")
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump({"generation": gen}, f)
        os.replace(tmp, os.path.join(dir_path, STATE_NAME))
    except OSError:
        pass
    return gen


def manifest_subdirs(dir_path: str) -> list[str]:
    """Per-replica manifest subdirs under an engine dir: a fleet
    drains ``replica-<rid>/`` dirs and blue/green handoffs leave
    ``bluegreen-<rid>/`` (docs/fleet.md). Both the fleet restore and a
    single engine restoring after a fleet-size rollback must absorb
    every one — this is the ONE place the naming convention lives."""
    out: list[str] = []
    try:
        for name in sorted(os.listdir(dir_path)):
            sub = os.path.join(dir_path, name)
            if os.path.isdir(sub) and name.startswith(
                ("replica-", "bluegreen-")
            ):
                out.append(sub)
    except OSError:
        pass
    return out


def consume_manifest(dir_path: str) -> None:
    try:
        os.unlink(os.path.join(dir_path, MANIFEST_NAME))
    except OSError:
        pass


def spool_owner_pid(name: str) -> Optional[int]:
    """Owner PID encoded in a live-tier spool filename
    (``pid<NNN>-<slug>.kvspool`` — see TieredKVStore._spool_path), or
    None for unowned files (drain spools, pre-PID-tag leftovers)."""
    base = os.path.basename(name)
    if not base.startswith("pid"):
        return None
    head, sep, _ = base[3:].partition("-")
    if not sep or not head.isdigit():
        return None
    return int(head)


def _pid_alive(pid: int) -> bool:
    if pid <= 0:
        return False
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except OSError:
        pass  # EPERM etc. — it exists, just isn't ours
    return True


def manifest_spool_files(manifest: Optional[dict]) -> set[str]:
    """Basenames of every spool file a manifest still references —
    the sweep's protected set."""
    out: set[str] = set()
    for entry in (manifest or {}).get("sessions", ()):
        kv = entry.get("kv") if isinstance(entry, dict) else None
        if isinstance(kv, dict) and kv.get("file"):
            out.add(os.path.basename(str(kv["file"])))
    return out


def sweep_orphans(
    dir_path: str,
    keep: Iterable[str] = (),
    max_age_s: Optional[float] = None,
) -> int:
    """Delete orphaned ``*.kvspool`` files (and ``*.kvspool.tmp``
    partials from writes a crash interrupted) left behind by dead
    processes. Skips files referenced by a live manifest in the same
    dir, files in ``keep``, files whose PID-tagged owner process is
    still alive (a SHARED offload dir holds live sibling engines'
    hibernated sessions — age alone must never delete those; a
    recycled PID can over-protect an orphan, which merely defers the
    sweep to that process's exit), and anything younger than
    ``max_age_s`` (default ROOM_TPU_SPOOL_SWEEP_AGE_S) — a concurrent
    drain's fresh spool files must survive a racing boot. Returns
    files removed; never raises."""
    if max_age_s is None:
        max_age_s = sweep_age_s()
    manifest = read_manifest(dir_path)
    if manifest is None and os.path.exists(
        os.path.join(dir_path, MANIFEST_NAME)
    ):
        # a manifest is PRESENT but unreadable (transient I/O error or
        # an armed shutdown_io fault): its protected set is unknown, so
        # deleting anything could destroy still-referenced warm-restart
        # data — "a failed read cold-starts", it never destroys. A
        # permanently corrupt manifest merely defers the sweep until
        # the next successful drain replaces it.
        return 0
    protected = set(keep) | manifest_spool_files(manifest)
    removed = 0
    try:
        names = os.listdir(dir_path)
    except OSError:
        return 0
    cutoff = time.time() - max(max_age_s, 0.0)
    for name in names:
        # .kvspool.tmp: a crash mid-_write_spool leaves the partial
        # file under the tmp name forever (the rename never ran) — no
        # manifest can reference it, so only age and a live PID tag
        # protect it
        if not name.endswith((SPOOL_SUFFIX, SPOOL_SUFFIX + ".tmp")) \
                or name in protected:
            continue
        owner = spool_owner_pid(name)
        if owner is not None and _pid_alive(owner):
            continue
        path = os.path.join(dir_path, name)
        try:
            if os.path.getmtime(path) > cutoff:
                continue
            os.unlink(path)
            removed += 1
        except OSError:
            continue
    return removed


# ---- clean-shutdown marker ----

def write_clean_marker(
    root: Optional[str] = None,
    summaries: Optional[dict] = None,
) -> bool:
    """Stamp the root after a fully-drained shutdown. Consumed (and
    deleted) by the next boot; its absence while prior lifecycle state
    exists means the last process crashed.

    ``summaries`` (name -> drain summary) arms the drain-marker
    honesty invariant (chaos/invariants.py): a marker attests every
    engine's drain manifested, so writing one over a summary with
    ``manifest_written`` False is the witness's business."""
    if summaries is not None:
        from ..chaos import invariants as invariants_mod

        if invariants_mod.enabled():
            invariants_mod.probe_drain_marker(summaries)
    root = root or lifecycle_root()
    try:
        faults.maybe_fail("shutdown_io")
        os.makedirs(root, exist_ok=True)
        with open(os.path.join(root, MARKER_NAME), "w",
                  encoding="utf-8") as f:
            json.dump({"written_at": time.time()}, f)
        return True
    except (FaultError, OSError):
        return False


def record_boot(root: Optional[str] = None) -> None:
    """Leave a state stamp so the NEXT boot can tell "no marker because
    we crashed" from "no marker because this host never ran room-tpu"."""
    root = root or lifecycle_root()
    try:
        os.makedirs(root, exist_ok=True)
        with open(os.path.join(root, STATE_NAME), "w",
                  encoding="utf-8") as f:
            json.dump({"booted_at": time.time()}, f)
    except OSError:
        pass


def consume_clean_marker(root: Optional[str] = None) -> str:
    """How did the previous process die? ``clean`` (marker present —
    consumed here so it attests exactly one shutdown), ``crash`` (prior
    state but no marker: journal recovery has real work), or
    ``first_boot``. Never raises."""
    root = root or lifecycle_root()
    marker = os.path.join(root, MARKER_NAME)
    try:
        os.unlink(marker)
        return "clean"
    except OSError:
        pass
    return "crash" if os.path.exists(os.path.join(root, STATE_NAME)) \
        else "first_boot"
