"""Paged KV cache: device page pool + host page table.

The TPU analogue of vLLM-style paged attention (PAPERS.md: Ragged Paged
Attention for TPU): KV lives in fixed-size pages [L, n_pages, page_size,
Hkv, Dh]; a session owns a list of pages; the decode batch addresses them
through a block table [B, max_pages]. Sessions can be parked mid-turn
(tool call) and resumed later without recomputing prefix KV — the
on-chip equivalent of the reference's session continuity
(reference: src/shared/agent-loop.ts:462-532, agent_sessions table).

The device side is pure functions (jit/scan-safe); the host-side
PageTable does allocation bookkeeping only.
"""

from __future__ import annotations

import threading
from typing import Any, Optional

import jax
import jax.numpy as jnp  # noqa: F401  (used in jit-side helpers)

from ..models.config import DecoderConfig
from ..ops import attention_ref
from ..utils import knobs, locks

Params = dict[str, Any]


def kv_quant_mode() -> Optional[str]:
    """KV-cache quantization (env ROOM_TPU_KV_QUANT): ``int8`` stores
    pages as int8 with one f32 scale per (token, kv-head) — ~49% of the
    bf16 pool's HBM bytes AND decode-attention read traffic, the
    dominant cost at long context. None (default) keeps bf16 pages."""
    mode = knobs.get_str("ROOM_TPU_KV_QUANT").strip() or None
    if mode not in (None, "int8"):
        raise ValueError(f"unknown ROOM_TPU_KV_QUANT {mode!r}")
    return mode


def init_page_cache(
    cfg: DecoderConfig, n_pages: int, page_size: int, dtype=None,
    quant: Optional[str] = None,
) -> Params:
    dt = dtype or cfg.activation_dtype
    shape = (cfg.n_layers, n_pages, page_size, cfg.n_kv_heads, cfg.head_dim)
    if quant == "int8":
        sshape = shape[:-1]
        return {
            "k_pages": jnp.zeros(shape, jnp.int8),
            "v_pages": jnp.zeros(shape, jnp.int8),
            "k_scale": jnp.zeros(sshape, jnp.float32),
            "v_scale": jnp.zeros(sshape, jnp.float32),
        }
    return {"k_pages": jnp.zeros(shape, dt), "v_pages": jnp.zeros(shape, dt)}


def _quantize_kv(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Per-(token, head) symmetric int8: scale = max|x| / 127 along D."""
    scale = jnp.maximum(
        jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1), 1e-8
    ) / 127.0
    q = jnp.clip(
        jnp.round(x.astype(jnp.float32) / scale[..., None]),
        -127, 127,
    ).astype(jnp.int8)
    return q, scale


def use_pallas_kernel() -> bool:
    """Paged attention backend selection: the Pallas kernels on TPU
    when ROOM_TPU_PAGED_KERNEL is pallas/ragged (ragged additionally
    forces the unified ragged kernel for fused windows), the XLA
    gather reference otherwise."""
    mode = knobs.get_str("ROOM_TPU_PAGED_KERNEL")
    if mode in ("pallas", "ragged"):
        return True
    if mode == "xla":
        return False
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


_PREFILL_PROBE: dict[tuple, bool] = {}
_DECODE_INT8_PROBE: dict[tuple, bool] = {}


def _probe_gate(env_var: str, cache: dict, probe_fn, *shape) -> bool:
    """Shared kernel-gating scaffold: env force (on|off), else a
    one-shot compile + numerics probe cached per shape (the shape key
    is (n_q_heads, n_kv_heads, head_dim, page_size[, q_block]))."""
    mode = knobs.get_str(env_var)
    if mode == "on":
        return True
    if mode == "off":
        return False
    key = tuple(int(x) for x in shape)
    got = cache.get(key)
    if got is None:
        got = probe_fn(*key)
        cache[key] = got
    return got


def pallas_prefill_ok(
    n_q_heads: int, n_kv_heads: int, head_dim: int, page_size: int
) -> bool:
    """One-shot compile + numerics smoke of the S>1 Pallas prefill
    kernel for these shapes, cached per shape. The decode kernel was
    validated on real v5e, but Mosaic has diverged from interpret mode
    on this hardware before (see ops/paged_attention.py docstring), and
    the prefill kernel's [qblk*Hq, rows] dots are a different lowering:
    routing production continuation-prefill through an unproven compile
    could crash serving. A failed probe falls back to the bounded XLA
    gather. ROOM_TPU_PREFILL_KERNEL=on|off skips the probe either way.
    """
    return _probe_gate(
        "ROOM_TPU_PREFILL_KERNEL", _PREFILL_PROBE,
        _probe_prefill_kernel,
        n_q_heads, n_kv_heads, head_dim, page_size,
    )


def pallas_decode_int8_ok(
    n_q_heads: int, n_kv_heads: int, head_dim: int, page_size: int
) -> bool:
    """Startup smoke for the int8-KV decode kernel (same contract as
    pallas_prefill_ok): the bf16 decode kernel is hardware-validated,
    but the int8 variant's scale DMAs are a new lowering — probe
    compile + numerics before routing traffic, fall back to the XLA
    dequant gather otherwise."""
    return _probe_gate(
        "ROOM_TPU_PAGED_INT8_KERNEL", _DECODE_INT8_PROBE,
        _probe_decode_int8_kernel,
        n_q_heads, n_kv_heads, head_dim, page_size,
    )


_PREFILL_INT8_PROBE: dict[tuple, bool] = {}
_RAGGED_PROBE: dict[tuple, bool] = {}
_RAGGED_INT8_PROBE: dict[tuple, bool] = {}


def pallas_ragged_ok(
    n_q_heads: int, n_kv_heads: int, head_dim: int, page_size: int,
    q_block: int,
) -> bool:
    """Startup smoke for the unified ragged kernel (env
    ROOM_TPU_RAGGED_KERNEL, same contract as pallas_prefill_ok): one
    compile + numerics check of a mixed [decode-lane + prefill-chunk]
    batch before the fused dispatch routes production traffic through
    it. A failed probe keeps the fused dispatch on the XLA
    gather+einsum reference."""
    return _probe_gate(
        "ROOM_TPU_RAGGED_KERNEL", _RAGGED_PROBE,
        _probe_ragged_kernel,
        n_q_heads, n_kv_heads, head_dim, page_size, q_block,
    )


def pallas_ragged_int8_ok(
    n_q_heads: int, n_kv_heads: int, head_dim: int, page_size: int,
    q_block: int,
) -> bool:
    """Startup smoke for the int8-KV unified ragged kernel (env
    ROOM_TPU_RAGGED_INT8_KERNEL) — in-kernel dequant of the quantized
    pool for the fused mixed dispatch."""
    return _probe_gate(
        "ROOM_TPU_RAGGED_INT8_KERNEL", _RAGGED_INT8_PROBE,
        _probe_ragged_int8_kernel,
        n_q_heads, n_kv_heads, head_dim, page_size, q_block,
    )


def pallas_prefill_int8_ok(
    n_q_heads: int, n_kv_heads: int, head_dim: int, page_size: int
) -> bool:
    """Startup smoke for the int8-KV S>1 prefill kernel (env
    ROOM_TPU_PREFILL_INT8_KERNEL) — keeps quantized chunked prefill
    O(actual context) instead of falling back to the dequant gather."""
    return _probe_gate(
        "ROOM_TPU_PREFILL_INT8_KERNEL", _PREFILL_INT8_PROBE,
        _probe_prefill_int8_kernel,
        n_q_heads, n_kv_heads, head_dim, page_size,
    )


def _probe_pages(
    seed: int, total: int, hkv: int, d: int, page_size: int,
    quantize: bool,
):
    """Shared probe scaffold: random k/v packed into pool pages (page 0
    stays scratch, as in production tables), optionally int8-quantized.
    Returns (page_inputs, tables, kd, vd, q_rng) where page_inputs is
    the positional tuple the kernel takes after q — (k, v) or
    (k, v, k_scale, v_scale) — and kd/vd are the [total, hkv, d] bf16
    references the expected attention is computed over (dequantized
    when quantize: the quantization error is the cache's, not the
    kernel's)."""
    import numpy as np

    npg = -(-total // page_size)
    rng = np.random.default_rng(seed)
    # 0.5 scale keeps bf16 softmax rounding inside the probe tolerance
    k = rng.standard_normal((total, hkv, d)).astype(np.float32) * 0.5
    v = rng.standard_normal((total, hkv, d)).astype(np.float32) * 0.5
    pad = npg * page_size - total
    kpad = np.concatenate([k, np.zeros((pad, hkv, d), np.float32)])
    vpad = np.concatenate([v, np.zeros((pad, hkv, d), np.float32)])
    tables = jnp.arange(1, npg + 1, dtype=jnp.int32)[None]
    if quantize:
        qk, sk = _quantize_kv(jnp.asarray(kpad))
        qv, sv = _quantize_kv(jnp.asarray(vpad))
        k_pages = jnp.zeros(
            (npg + 1, page_size, hkv, d), jnp.int8
        ).at[1:].set(qk.reshape(npg, page_size, hkv, d))
        v_pages = jnp.zeros(
            (npg + 1, page_size, hkv, d), jnp.int8
        ).at[1:].set(qv.reshape(npg, page_size, hkv, d))
        k_scale = jnp.zeros(
            (npg + 1, page_size, hkv), jnp.float32
        ).at[1:].set(sk.reshape(npg, page_size, hkv))
        v_scale = jnp.zeros(
            (npg + 1, page_size, hkv), jnp.float32
        ).at[1:].set(sv.reshape(npg, page_size, hkv))
        kd = (qk.astype(jnp.float32) * sk[..., None])[:total]
        vd = (qv.astype(jnp.float32) * sv[..., None])[:total]
        return (
            (k_pages, v_pages, k_scale, v_scale), tables,
            kd.astype(jnp.bfloat16), vd.astype(jnp.bfloat16), rng,
        )
    k_pages = jnp.zeros(
        (npg + 1, page_size, hkv, d), jnp.bfloat16
    ).at[1:].set(jnp.asarray(
        kpad.reshape(npg, page_size, hkv, d), jnp.bfloat16))
    v_pages = jnp.zeros(
        (npg + 1, page_size, hkv, d), jnp.bfloat16
    ).at[1:].set(jnp.asarray(
        vpad.reshape(npg, page_size, hkv, d), jnp.bfloat16))
    return (
        (k_pages, v_pages), tables,
        jnp.asarray(k, jnp.bfloat16), jnp.asarray(v, jnp.bfloat16),
        rng,
    )


def _probe_run(label: str, fn) -> bool:
    """Shared probe harness: run fn() -> (out, expected); any
    compile/lowering failure or numerics mismatch means fallback."""
    import logging

    import numpy as np

    try:
        out, expected = fn()
        ok = bool(np.allclose(
            np.asarray(out, np.float32),
            np.asarray(expected, np.float32),
            atol=6e-2,
        ))
        if not ok:
            logging.getLogger(__name__).warning(
                "%s kernel probe: numerics mismatch; using XLA "
                "fallback", label,
            )
        return ok
    except Exception as e:
        logging.getLogger(__name__).warning(
            "%s kernel probe failed (%s); using XLA fallback",
            label, e,
        )
        return False


def _probe_prefill_common(
    hq: int, hkv: int, d: int, page_size: int, quantize: bool
) -> bool:
    from ..ops.paged_attention import (
        PREFILL_Q_BLOCK, paged_attention_prefill,
        paged_attention_prefill_int8,
    )

    def run():
        s = PREFILL_Q_BLOCK
        prefix = page_size          # one full page of paged prefix
        total = prefix + s
        inputs, tables, kd, vd, rng = _probe_pages(
            3 if quantize else 0, total, hkv, d, page_size, quantize
        )
        q = jnp.asarray(
            rng.standard_normal((1, s, hq, d)) * 0.5, jnp.bfloat16
        )
        lengths = jnp.full((1,), prefix, jnp.int32)
        kernel = paged_attention_prefill_int8 if quantize \
            else paged_attention_prefill
        out = kernel(q, *inputs, tables, lengths, page_size=page_size)
        expected = attention_ref(
            q, kd[None], vd[None], causal=True,
            q_positions=prefix + jnp.arange(s)[None],
            kv_positions=jnp.arange(total)[None],
        )
        return out, expected

    label = "int8 prefill" if quantize else "pallas prefill"
    return _probe_run(label, run)


def _probe_prefill_kernel(
    hq: int, hkv: int, d: int, page_size: int
) -> bool:
    return _probe_prefill_common(hq, hkv, d, page_size, False)


def _probe_prefill_int8_kernel(
    hq: int, hkv: int, d: int, page_size: int
) -> bool:
    return _probe_prefill_common(hq, hkv, d, page_size, True)


def _probe_decode_int8_kernel(
    hq: int, hkv: int, d: int, page_size: int
) -> bool:
    from ..ops.paged_attention import paged_attention_decode_int8

    def run():
        total = 2 * page_size + 3    # ragged tail crosses a page
        inputs, tables, kd, vd, rng = _probe_pages(
            1, total, hkv, d, page_size, True
        )
        q = jnp.asarray(
            rng.standard_normal((1, hq, d)) * 0.5, jnp.bfloat16
        )
        lengths = jnp.full((1,), total, jnp.int32)
        out = paged_attention_decode_int8(
            q, *inputs, tables, lengths, page_size=page_size
        )
        expected = attention_ref(
            q[:, None], kd[None], vd[None], causal=True,
            q_positions=jnp.full((1, 1), total - 1, jnp.int32),
            kv_positions=jnp.arange(total)[None],
        )[:, 0]
        return out, expected

    return _probe_run("int8 decode", run)


def _probe_ragged_common(
    hq: int, hkv: int, d: int, page_size: int, q_block: int,
    quantize: bool,
) -> bool:
    """Mixed-batch probe of the unified ragged kernel: one decode lane
    (query_len 1) and one two-block prefill chunk share the dispatch,
    each checked against attention_ref over its own dequantized
    pages."""
    import numpy as np

    from ..ops.paged_attention import (
        paged_attention_ragged, paged_attention_ragged_int8,
        ragged_block_layout,
    )

    def run():
        chunk = 2 * q_block
        rows = ((1, page_size + 3), (chunk, page_size))  # (qlen, prefix)
        seeds = (5, 7)
        max_pages = 8
        tables_np, refs, qs = [], [], []
        # build each row's pages with the shared scaffold, then merge
        # the single-row pools into one (offsetting page ids past the
        # one shared scratch page 0)
        merged: Optional[list] = None
        next_page = 1
        rng = np.random.default_rng(11)
        for (ql, prefix), seed in zip(rows, seeds):
            total = prefix + ql
            inputs, tbl, kd, vd, _ = _probe_pages(
                seed, total, hkv, d, page_size, quantize
            )
            npg = tbl.shape[1]
            row_tbl = np.zeros((max_pages,), np.int32)
            row_tbl[:npg] = np.arange(next_page, next_page + npg)
            tables_np.append(row_tbl)
            refs.append((kd, vd, total, prefix, ql))
            qs.append(jnp.asarray(
                rng.standard_normal((ql, hq, d)) * 0.5, jnp.bfloat16
            ))
            if merged is None:
                merged = [[jnp.asarray(x)[0:1]] for x in inputs]
            for li, leaf in enumerate(inputs):
                merged[li].append(jnp.asarray(leaf)[1:])
            next_page += npg
        merged_pool = tuple(
            jnp.concatenate(parts, axis=0) for parts in merged
        )
        q_lens = [r[0] for r in rows]
        prefixes = [r[1] for r in rows]
        rowmap, blkmap, gather, scatter = ragged_block_layout(
            q_lens, q_block
        )
        q_flat = jnp.concatenate(qs, axis=0)
        q_pad = q_flat[jnp.asarray(gather)].reshape(
            len(rowmap), q_block, hq, d
        )
        kernel = paged_attention_ragged_int8 if quantize \
            else paged_attention_ragged
        out = kernel(
            q_pad, *merged_pool,
            jnp.asarray(np.stack(tables_np)),
            jnp.asarray(prefixes, jnp.int32),
            jnp.asarray(q_lens, jnp.int32),
            jnp.asarray(rowmap), jnp.asarray(blkmap),
            page_size=page_size, q_block=q_block,
        )
        out_flat = out.reshape(-1, hq, d)[jnp.asarray(scatter)]
        got, expected = [], []
        off = 0
        for (kd, vd, total, prefix, ql), qrow in zip(refs, qs):
            exp = attention_ref(
                qrow[None], kd[None], vd[None], causal=True,
                q_positions=prefix + jnp.arange(ql)[None],
                kv_positions=jnp.arange(total)[None],
            )[0]
            expected.append(np.asarray(exp, np.float32))
            got.append(np.asarray(out_flat[off:off + ql], np.float32))
            off += ql
        return np.concatenate(got), np.concatenate(expected)

    label = "int8 ragged" if quantize else "pallas ragged"
    return _probe_run(label, run)


def _probe_ragged_kernel(
    hq: int, hkv: int, d: int, page_size: int, q_block: int
) -> bool:
    return _probe_ragged_common(hq, hkv, d, page_size, q_block, False)


def _probe_ragged_int8_kernel(
    hq: int, hkv: int, d: int, page_size: int, q_block: int
) -> bool:
    return _probe_ragged_common(hq, hkv, d, page_size, q_block, True)


def make_ragged_kv_hook(
    block_tables: jax.Array,   # [R, max_pages] page ids per ragged row
    prefix_lens: jax.Array,    # [R] KV tokens already in cache per row
    page_size: int,
    *,
    n_decode: int,             # total decode rows (ONE query token each)
    n_chunks: int,             # total chunk rows (chunk_width tokens)
    chunk_width: int,
    active_pages: Optional[int] = None,
    pallas_ragged: Optional[bool] = None,
    q_block: int = 8,
    n_shards: int = 1,
):
    """kv_hook for the engine's FUSED dispatch: one forward over the
    ragged [decode-lanes + prefill-chunks] token stream (shape
    [1, n_decode + n_chunks*chunk_width]), laid out decode lanes first.
    Writes every token's k/v into its row's pages at
    prefix_lens[row] + offset, then attends — through the unified
    Pallas ragged kernel when ``pallas_ragged`` (one kernel, no padding
    to the batch max, O(actual context) page traffic per q-block), or
    through the XLA gather+einsum reference otherwise (the CPU/tier-1
    fallback: the decode segment and the chunk segment each take
    exactly the same bounded-gather attention_ref path the split
    dispatches take, so greedy streams stay token-identical to the
    split engine).

    The same overrun contracts as make_paged_kv_hook hold: positions
    past the block table divert to scratch page 0, and rows that are
    padding (inactive decode lanes, chunk-batch pad rows) write scratch
    KV that is garbage by construction.

    ``n_shards > 1`` is the dp-sharded fused window (docs/serving.md):
    the token stream arrives as [n_shards, T_local] with rows stored
    shard-major and each shard laid out decode-lanes-first exactly like
    the dp=1 stream (ops.paged_attention.ragged_shard_layout), so each
    dp shard's slice is a self-contained ragged sub-batch — writes,
    page gathers and attention are all row-local and the token path
    needs no cross-shard collective. The per-segment reference math is
    unchanged (the same rows through the same attention_ref), which is
    what keeps dp-sharded greedy streams token-identical to dp=1."""
    from ..ops.paged_attention import ragged_shard_layout

    r_total, max_pages = block_tables.shape
    if r_total != n_decode + n_chunks:
        raise ValueError(
            f"ragged rows {r_total} != {n_decode} decode + "
            f"{n_chunks} chunks"
        )
    # static token -> row / offset maps (the ragged layout is a pure
    # function of the fused batch shape, so these fold into the jit);
    # with n_shards == 1 these reduce to the flat decode-first layout
    lay = ragged_shard_layout(
        n_decode, n_chunks, chunk_width, n_shards
    )
    row_of_token = lay["row_of_token"]
    off_in_row = lay["off_in_row"]
    n_tokens = row_of_token.shape[0]
    t_local = n_tokens // n_shards

    def hook(q, k, v, layer_cache):
        if q.shape[0] != n_shards or q.shape[1] != t_local:
            raise ValueError(
                f"ragged hook expects [{n_shards}, {t_local}, H, D] "
                f"q, got {q.shape}"
            )
        quantized = "k_scale" in layer_cache
        rows_j = jnp.asarray(row_of_token)
        positions = prefix_lens[rows_j] + jnp.asarray(off_in_row)  # [T]
        page_idx = positions // page_size
        in_range = page_idx < max_pages
        page_of = jnp.where(
            in_range,
            block_tables[rows_j, jnp.minimum(page_idx, max_pages - 1)],
            0,
        )
        offset = positions % page_size

        k_flat = k.reshape(n_tokens, *k.shape[2:])     # [T, Hkv, D]
        v_flat = v.reshape(n_tokens, *v.shape[2:])
        if quantized:
            qk, sk = _quantize_kv(k_flat)
            qv, sv = _quantize_kv(v_flat)
            kp = layer_cache["k_pages"].at[page_of, offset].set(qk)
            vp = layer_cache["v_pages"].at[page_of, offset].set(qv)
            ks = layer_cache["k_scale"].at[page_of, offset].set(sk)
            vs = layer_cache["v_scale"].at[page_of, offset].set(sv)
            out_cache = {
                "k_pages": kp, "v_pages": vp,
                "k_scale": ks, "v_scale": vs,
            }
        else:
            kp = layer_cache["k_pages"].at[page_of, offset].set(k_flat)
            vp = layer_cache["v_pages"].at[page_of, offset].set(v_flat)
            ks = vs = None
            out_cache = {"k_pages": kp, "v_pages": vp}

        hq_n, d_n = q.shape[2], q.shape[3]

        use_ragged = pallas_ragged
        if use_ragged is None:
            use_ragged = use_pallas_kernel() and \
                (n_chunks == 0 or chunk_width % q_block == 0)
        if use_ragged:
            from ..ops.paged_attention import (
                paged_attention_ragged, paged_attention_ragged_int8,
                ragged_block_layout,
            )

            # shard-major per-row query lengths: each shard's rows are
            # [its decode lanes, its chunk rows] — rows never straddle
            # shards, so the flat kernel layout is shard-local
            q_lens = (
                (1,) * (n_decode // n_shards)
                + (chunk_width,) * (n_chunks // n_shards)
            ) * n_shards
            rowmap, blkmap, gather, scatter = ragged_block_layout(
                q_lens, q_block
            )
            q_pad = q.reshape(n_tokens, hq_n, d_n)[
                jnp.asarray(gather)
            ].reshape(len(rowmap), q_block, hq_n, d_n)
            args = (kp, vp, ks, vs) if quantized else (kp, vp)
            kernel = paged_attention_ragged_int8 if quantized \
                else paged_attention_ragged
            out_pad = kernel(
                q_pad, *args, block_tables, prefix_lens,
                jnp.asarray(q_lens, jnp.int32),
                jnp.asarray(rowmap), jnp.asarray(blkmap),
                page_size=page_size, q_block=q_block,
            )
            attn = out_pad.reshape(-1, hq_n, d_n)[
                jnp.asarray(scatter)
            ].reshape(n_shards, t_local, hq_n, d_n)
            return attn, out_cache

        # XLA reference: bounded page gather + attention_ref per
        # segment — the decode rows as a [n_decode, 1] batch and the
        # chunk rows as an [n_chunks, chunk_width] batch, exactly the
        # shapes the SPLIT dispatches feed it (masked positions
        # contribute exact zeros, so the fused result is bit-identical
        # per row). Sharded layouts gather each segment's rows through
        # the static shard-major row maps — same rows, same math, so
        # the dp-sharded result stays bit-identical to dp=1 per row.
        tbl = block_tables
        if active_pages is not None and active_pages < max_pages:
            tbl = block_tables[:, :active_pages]
        kv_len = tbl.shape[1] * page_size
        if quantized:
            k_all = (
                kp[tbl].astype(jnp.float32) * ks[tbl][..., None]
            ).astype(jnp.bfloat16)
            v_all = (
                vp[tbl].astype(jnp.float32) * vs[tbl][..., None]
            ).astype(jnp.bfloat16)
        else:
            k_all = kp[tbl]
            v_all = vp[tbl]
        k_all = k_all.reshape(r_total, kv_len, *k.shape[2:])
        v_all = v_all.reshape(r_total, kv_len, *v.shape[2:])
        kv_positions = jnp.broadcast_to(
            jnp.arange(kv_len)[None], (r_total, kv_len)
        )
        q_flat = q.reshape(n_tokens, hq_n, d_n)
        dec_rows = jnp.asarray(lay["dec_rows"])
        ch_rows = jnp.asarray(lay["ch_rows"])

        parts = []
        if n_decode:
            q_dec = q_flat[jnp.asarray(lay["dec_toks"])][:, None]
            attn_dec = attention_ref(
                q_dec, k_all[dec_rows], v_all[dec_rows],
                causal=True,
                q_positions=prefix_lens[dec_rows][:, None],
                kv_positions=kv_positions[dec_rows],
                kv_mask=kv_positions[dec_rows]
                < (prefix_lens[dec_rows] + 1)[:, None],
            )
            parts.append(attn_dec.reshape(n_decode, hq_n, d_n))
        if n_chunks:
            q_ch = q_flat[jnp.asarray(lay["ch_toks"])].reshape(
                n_chunks, chunk_width, hq_n, d_n
            )
            ch_prefix = prefix_lens[ch_rows]
            attn_ch = attention_ref(
                q_ch, k_all[ch_rows], v_all[ch_rows],
                causal=True,
                q_positions=ch_prefix[:, None]
                + jnp.arange(chunk_width)[None],
                kv_positions=kv_positions[ch_rows],
                kv_mask=kv_positions[ch_rows]
                < (ch_prefix + chunk_width)[:, None],
            )
            parts.append(
                attn_ch.reshape(n_chunks * chunk_width, hq_n, d_n)
            )
        attn = jnp.concatenate(parts, axis=0)[
            jnp.asarray(lay["inv_perm"])
        ].reshape(n_shards, t_local, hq_n, d_n)
        return attn, out_cache

    return hook


def make_paged_kv_hook(
    block_tables: jax.Array,   # [B, max_pages] page ids (0 = also a real page; unused slots may be any valid id, masked by length)
    lengths: jax.Array,        # [B] tokens already in cache per sequence
    page_size: int,
    pallas_decode: Optional[bool] = None,
    fresh_prefill: bool = False,
    active_pages: Optional[int] = None,
    pallas_prefill: Optional[bool] = None,
):
    """Build the kv_hook used by models.qwen3.forward: writes the chunk's
    k/v into the page pool and attends over (prefix + chunk).

    Works for single-token decode (S=1) and chunked prefill (S>1) alike.
    Single-token decode can route through the Pallas paged-attention
    kernel (no dense gather). ``fresh_prefill`` is a static promise that
    every sequence starts at length 0, so attention runs over the chunk
    itself and the page gather is skipped entirely — the common
    new-session prefill does no cache reads at all.

    ``active_pages`` is a static caller promise that every sequence's
    final length (prefix + this chunk) fits in that many leading
    block-table pages: the XLA gather then reads only those pages, so
    continuation prefill / decode cost scales with the actual session
    length instead of the table's full 32k-token capacity. Callers
    bucket it (powers of two) to bound compile variants.

    Multi-step dispatch windows (docs/serving.md) rebuild this hook
    every scan step with the CARRIED lengths, so step j of a window
    writes at position length+j with no host involvement; the engine's
    reservations address length+window ahead of the drain. Two
    contracts the pipeline leans on live here: (1) positions past the
    block table divert to scratch page 0 (a finishing turn's overshoot
    can overrun its reservation), and (2) writes past a session's
    recorded length are garbage by construction — attention masks reads
    by length, and resumes overwrite them in device order.
    """
    b, max_pages = block_tables.shape
    if pallas_decode is None:
        pallas_decode = use_pallas_kernel()

    def hook(q, k, v, layer_cache):
        s = q.shape[1]
        quantized = "k_scale" in layer_cache
        positions = lengths[:, None] + jnp.arange(s)[None]      # [B, S]
        # positions beyond the block table (chunked decode can overrun a
        # finishing turn) divert to scratch page 0 rather than clamping
        # into the last real page and corrupting live KV
        page_idx = positions // page_size
        in_range = page_idx < max_pages
        page_of = jnp.where(
            in_range,
            jnp.take_along_axis(
                block_tables, jnp.minimum(page_idx, max_pages - 1),
                axis=1,
            ),
            0,
        )                                                        # [B, S]
        offset = positions % page_size

        flat_pages = page_of.reshape(-1)
        flat_off = offset.reshape(-1)
        k_flat = k.reshape(-1, *k.shape[2:])
        v_flat = v.reshape(-1, *v.shape[2:])
        if quantized:
            qk, sk = _quantize_kv(k_flat)
            qv, sv = _quantize_kv(v_flat)
            kp = layer_cache["k_pages"].at[flat_pages, flat_off].set(qk)
            vp = layer_cache["v_pages"].at[flat_pages, flat_off].set(qv)
            ks = layer_cache["k_scale"].at[flat_pages, flat_off].set(sk)
            vs = layer_cache["v_scale"].at[flat_pages, flat_off].set(sv)
            out_cache = {
                "k_pages": kp, "v_pages": vp,
                "k_scale": ks, "v_scale": vs,
            }
        else:
            kp = layer_cache["k_pages"].at[flat_pages, flat_off].set(
                k_flat
            )
            vp = layer_cache["v_pages"].at[flat_pages, flat_off].set(
                v_flat
            )
            ks = vs = None
            out_cache = {"k_pages": kp, "v_pages": vp}

        if fresh_prefill:
            positions_q = jnp.broadcast_to(jnp.arange(s)[None], (b, s))
            attn = attention_ref(
                q, k, v, causal=True,
                q_positions=positions_q, kv_positions=positions_q,
            )
            return attn, out_cache

        if s == 1 and pallas_decode and (
            not quantized or pallas_decode_int8_ok(
                q.shape[2], k.shape[2], k.shape[3], page_size
            )
        ):
            if quantized:
                from ..ops.paged_attention import (
                    paged_attention_decode_int8,
                )

                attn = paged_attention_decode_int8(
                    q[:, 0], kp, vp, ks, vs, block_tables,
                    lengths + 1, page_size=page_size,
                )[:, None]
            else:
                from ..ops.paged_attention import paged_attention_decode

                attn = paged_attention_decode(
                    q[:, 0], kp, vp, block_tables, lengths + 1,
                    page_size=page_size,
                )[:, None]
            return attn, out_cache

        if s > 1:
            from ..ops.paged_attention import (
                PREFILL_Q_BLOCK, paged_attention_prefill,
                paged_attention_prefill_int8,
            )

            use_prefill = pallas_prefill
            if use_prefill is None and pallas_decode \
                    and s % PREFILL_Q_BLOCK == 0:
                ok_fn = pallas_prefill_int8_ok if quantized \
                    else pallas_prefill_ok
                use_prefill = ok_fn(
                    q.shape[2], k.shape[2], k.shape[3], page_size
                )
            if use_prefill and s % PREFILL_Q_BLOCK == 0:
                # ragged chunked-prefill kernel: walks each row's own
                # pages (prefix + the chunk KV written above) — page
                # traffic scales with actual context, never capacity
                if quantized:
                    attn = paged_attention_prefill_int8(
                        q, kp, vp, ks, vs, block_tables, lengths,
                        page_size=page_size,
                    )
                else:
                    attn = paged_attention_prefill(
                        q, kp, vp, block_tables, lengths,
                        page_size=page_size,
                    )
                return attn, out_cache

        # gather this batch's pages into a dense view (XLA reference path;
        # the Pallas kernel replaces this gather), bounded to the pages
        # the batch can actually reach when the caller promised a limit
        tbl = block_tables
        if active_pages is not None and active_pages < max_pages:
            tbl = block_tables[:, :active_pages]
        kv_len = tbl.shape[1] * page_size
        if quantized:
            # dequantize right after the gather; bf16 keeps the dense
            # view at the unquantized path's footprint
            k_all = (
                kp[tbl].astype(jnp.float32) * ks[tbl][..., None]
            ).astype(jnp.bfloat16)
            v_all = (
                vp[tbl].astype(jnp.float32) * vs[tbl][..., None]
            ).astype(jnp.bfloat16)
        else:
            k_all = kp[tbl]                                  # [B,P,p,H,D]
            v_all = vp[tbl]
        k_all = k_all.reshape(b, kv_len, *k.shape[2:])
        v_all = v_all.reshape(b, kv_len, *v.shape[2:])

        kv_positions = jnp.broadcast_to(
            jnp.arange(kv_len)[None], (b, kv_len)
        )
        kv_mask = kv_positions < (lengths + s)[:, None]
        attn = attention_ref(
            q, k_all, v_all, causal=True,
            q_positions=positions, kv_positions=kv_positions,
            kv_mask=kv_mask,
        )
        return attn, out_cache

    return hook


class PageTable:
    """Host-side page allocator: free list + per-session page lists."""

    def __init__(self, n_pages: int, page_size: int) -> None:
        self.n_pages = n_pages
        self.page_size = page_size
        self._free: list[int] = list(range(n_pages - 1, -1, -1))
        self._sessions: dict[str, list[int]] = {}
        self._lock = locks.make_lock("kv_page_table")

    @property
    def free_pages(self) -> int:
        with self._lock:
            return len(self._free)

    @property
    def free_fraction(self) -> float:
        """Fraction of the pool currently free — the offload tier
        manager's watermark signal (kv_offload.py)."""
        with self._lock:
            return len(self._free) / max(self.n_pages, 1)

    def pages_of(self, session_id: str) -> list[int]:
        with self._lock:
            return list(self._sessions.get(session_id, []))

    def tokens_capacity(self, session_id: str) -> int:
        return len(self.pages_of(session_id)) * self.page_size

    def ensure_capacity(self, session_id: str, n_tokens: int) -> list[int]:
        """Grow the session's page list to hold n_tokens total. Raises
        MemoryError when the pool is exhausted (caller pre-empts or
        queues)."""
        # chaos fault point: an injected allocation failure takes the
        # same MemoryError recovery path (evict -> degrade -> requeue)
        # a genuinely exhausted pool does. The __null__ scratch page is
        # exempt — it's allocated by engine bootstrap and crash
        # RECOVERY, where a fired fault would kill the supervisor
        # itself instead of exercising a traffic path.
        if session_id != "__null__":
            from .faults import maybe_fail

            maybe_fail("kv_alloc", MemoryError)
        with self._lock:
            pages = self._sessions.setdefault(session_id, [])
            need = -(-n_tokens // self.page_size) - len(pages)
            if need > len(self._free):
                raise MemoryError(
                    f"page pool exhausted: need {need}, free "
                    f"{len(self._free)}"
                )
            for _ in range(max(need, 0)):
                pages.append(self._free.pop())
            return list(pages)

    def try_capacity(
        self, session_id: str, n_tokens: int
    ) -> Optional[list[int]]:
        """ensure_capacity that takes FREE pages only: returns None
        instead of raising when the pool can't grow the session.
        Partial-prefill reservations (docs/scheduler.md) use this for
        background-class chunks — an opportunistic chunk write must
        never push the caller into evicting live KV to make room."""
        try:
            return self.ensure_capacity(session_id, n_tokens)
        except MemoryError:
            return None

    def release(self, session_id: str) -> int:
        """Free all pages of a session (session end or eviction)."""
        with self._lock:
            pages = self._sessions.pop(session_id, [])
            self._free.extend(reversed(pages))
            return len(pages)

    def audit(self) -> dict:
        """Conservation audit for the invariant witness
        (chaos/invariants.py): every page is either free or owned by
        exactly one session, and the two partitions cover the pool.
        A page counted twice (double-free / double-alloc) or missing
        (leak) breaks ``balanced``."""
        with self._lock:
            free = list(self._free)
            owned = [
                p for pages in self._sessions.values() for p in pages
            ]
        every = free + owned
        dupes = len(every) - len(set(every))
        out_of_range = sum(
            1 for p in every if not 0 <= p < self.n_pages
        )
        return {
            "n_pages": self.n_pages,
            "free": len(free),
            "owned": len(owned),
            "dupes": dupes,
            "out_of_range": out_of_range,
            "balanced": (
                len(every) == self.n_pages and dupes == 0
                and out_of_range == 0
            ),
        }
