"""Paged KV cache: device page pool + host page table.

The TPU analogue of vLLM-style paged attention (PAPERS.md: Ragged Paged
Attention for TPU): KV lives in fixed-size pages [L, n_pages, page_size,
Hkv, Dh]; a session owns a list of pages; the decode batch addresses them
through a block table [B, max_pages]. Sessions can be parked mid-turn
(tool call) and resumed later without recomputing prefix KV — the
on-chip equivalent of the reference's session continuity
(reference: src/shared/agent-loop.ts:462-532, agent_sessions table).

The device side is pure functions (jit/scan-safe); the host-side
PageTable does allocation bookkeeping only.
"""

from __future__ import annotations

import threading
from typing import Any, Optional

import jax
import jax.numpy as jnp  # noqa: F401  (used in jit-side helpers)

from ..models.config import DecoderConfig
from ..ops import attention_ref

Params = dict[str, Any]


def init_page_cache(
    cfg: DecoderConfig, n_pages: int, page_size: int, dtype=None
) -> Params:
    dt = dtype or cfg.activation_dtype
    shape = (cfg.n_layers, n_pages, page_size, cfg.n_kv_heads, cfg.head_dim)
    return {"k_pages": jnp.zeros(shape, dt), "v_pages": jnp.zeros(shape, dt)}


def use_pallas_kernel() -> bool:
    """Decode attention backend selection: the Pallas kernel on TPU when
    ROOM_TPU_PAGED_KERNEL=pallas, XLA gather reference otherwise."""
    import os

    mode = os.environ.get("ROOM_TPU_PAGED_KERNEL", "auto")
    if mode == "pallas":
        return True
    if mode == "xla":
        return False
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def make_paged_kv_hook(
    block_tables: jax.Array,   # [B, max_pages] page ids (0 = also a real page; unused slots may be any valid id, masked by length)
    lengths: jax.Array,        # [B] tokens already in cache per sequence
    page_size: int,
    pallas_decode: Optional[bool] = None,
    fresh_prefill: bool = False,
    active_pages: Optional[int] = None,
):
    """Build the kv_hook used by models.qwen3.forward: writes the chunk's
    k/v into the page pool and attends over (prefix + chunk).

    Works for single-token decode (S=1) and chunked prefill (S>1) alike.
    Single-token decode can route through the Pallas paged-attention
    kernel (no dense gather). ``fresh_prefill`` is a static promise that
    every sequence starts at length 0, so attention runs over the chunk
    itself and the page gather is skipped entirely — the common
    new-session prefill does no cache reads at all.

    ``active_pages`` is a static caller promise that every sequence's
    final length (prefix + this chunk) fits in that many leading
    block-table pages: the XLA gather then reads only those pages, so
    continuation prefill / decode cost scales with the actual session
    length instead of the table's full 32k-token capacity. Callers
    bucket it (powers of two) to bound compile variants.
    """
    b, max_pages = block_tables.shape
    if pallas_decode is None:
        pallas_decode = use_pallas_kernel()

    def hook(q, k, v, layer_cache):
        s = q.shape[1]
        positions = lengths[:, None] + jnp.arange(s)[None]      # [B, S]
        # positions beyond the block table (chunked decode can overrun a
        # finishing turn) divert to scratch page 0 rather than clamping
        # into the last real page and corrupting live KV
        page_idx = positions // page_size
        in_range = page_idx < max_pages
        page_of = jnp.where(
            in_range,
            jnp.take_along_axis(
                block_tables, jnp.minimum(page_idx, max_pages - 1),
                axis=1,
            ),
            0,
        )                                                        # [B, S]
        offset = positions % page_size

        flat_pages = page_of.reshape(-1)
        flat_off = offset.reshape(-1)
        kp = layer_cache["k_pages"].at[flat_pages, flat_off].set(
            k.reshape(-1, *k.shape[2:])
        )
        vp = layer_cache["v_pages"].at[flat_pages, flat_off].set(
            v.reshape(-1, *v.shape[2:])
        )

        if fresh_prefill:
            positions_q = jnp.broadcast_to(jnp.arange(s)[None], (b, s))
            attn = attention_ref(
                q, k, v, causal=True,
                q_positions=positions_q, kv_positions=positions_q,
            )
            return attn, {"k_pages": kp, "v_pages": vp}

        if s == 1 and pallas_decode:
            from ..ops.paged_attention import paged_attention_decode

            attn = paged_attention_decode(
                q[:, 0], kp, vp, block_tables, lengths + 1,
                page_size=page_size,
            )[:, None]
            return attn, {"k_pages": kp, "v_pages": vp}

        if s > 1 and pallas_decode:
            from ..ops.paged_attention import (
                PREFILL_Q_BLOCK, paged_attention_prefill,
            )

            if s % PREFILL_Q_BLOCK == 0:
                # ragged chunked-prefill kernel: walks each row's own
                # pages (prefix + the chunk KV written above) — page
                # traffic scales with actual context, never capacity
                attn = paged_attention_prefill(
                    q, kp, vp, block_tables, lengths,
                    page_size=page_size,
                )
                return attn, {"k_pages": kp, "v_pages": vp}

        # gather this batch's pages into a dense view (XLA reference path;
        # the Pallas kernel replaces this gather), bounded to the pages
        # the batch can actually reach when the caller promised a limit
        tbl = block_tables
        if active_pages is not None and active_pages < max_pages:
            tbl = block_tables[:, :active_pages]
        k_all = kp[tbl]                                          # [B,P,p,H,D]
        v_all = vp[tbl]
        kv_len = tbl.shape[1] * page_size
        k_all = k_all.reshape(b, kv_len, *k.shape[2:])
        v_all = v_all.reshape(b, kv_len, *v.shape[2:])

        kv_positions = jnp.broadcast_to(
            jnp.arange(kv_len)[None], (b, kv_len)
        )
        kv_mask = kv_positions < (lengths + s)[:, None]
        attn = attention_ref(
            q, k_all, v_all, causal=True,
            q_positions=positions, kv_positions=kv_positions,
            kv_mask=kv_mask,
        )
        return attn, {"k_pages": kp, "v_pages": vp}

    return hook


class PageTable:
    """Host-side page allocator: free list + per-session page lists."""

    def __init__(self, n_pages: int, page_size: int) -> None:
        self.n_pages = n_pages
        self.page_size = page_size
        self._free: list[int] = list(range(n_pages - 1, -1, -1))
        self._sessions: dict[str, list[int]] = {}
        self._lock = threading.Lock()

    @property
    def free_pages(self) -> int:
        with self._lock:
            return len(self._free)

    def pages_of(self, session_id: str) -> list[int]:
        with self._lock:
            return list(self._sessions.get(session_id, []))

    def tokens_capacity(self, session_id: str) -> int:
        return len(self.pages_of(session_id)) * self.page_size

    def ensure_capacity(self, session_id: str, n_tokens: int) -> list[int]:
        """Grow the session's page list to hold n_tokens total. Raises
        MemoryError when the pool is exhausted (caller pre-empts or
        queues)."""
        with self._lock:
            pages = self._sessions.setdefault(session_id, [])
            need = -(-n_tokens // self.page_size) - len(pages)
            if need > len(self._free):
                raise MemoryError(
                    f"page pool exhausted: need {need}, free "
                    f"{len(self._free)}"
                )
            for _ in range(max(need, 0)):
                pages.append(self._free.pop())
            return list(pages)

    def release(self, session_id: str) -> int:
        """Free all pages of a session (session end or eviction)."""
        with self._lock:
            pages = self._sessions.pop(session_id, [])
            self._free.extend(reversed(pages))
            return len(pages)
