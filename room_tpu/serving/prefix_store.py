"""Fleet-global shared prefix store (docs/disagg.md).

The engine's automatic prefix cache (engine._PrefixEntry) is
process-local: every replica — and every re-homed or freshly placed
session on one — re-prefills the identical multi-thousand-token
queen/worker system prompt its sibling already computed. At millions of
sessions the same few KB of boilerplate is recomputed millions of
times. This module is the shared tier underneath those caches: a
content-addressed, filesystem-backed store of page-aligned prompt
prefix KV, layered on the same spool machinery as the tiered offload
(kv_offload._write_spool/_read_spool — bf16-safe raw buffers behind a
json header, atomic tmp+rename writes).

Addressing
----------
An entry's key is ``sha256(config fingerprint || token prefix)`` where
the fingerprint is the engine's lifecycle fingerprint (model name,
dtype/layout, page size, KV quant mode): two engines produce the same
key only when their KV bytes are interchangeable. The prefix is always
page-aligned — the same alignment rule as the in-process prefix cache
— so a pulled entry scatters directly into whole pages.

Sharing model
-------------
One directory, many readers and writers — sibling replicas in one
process, other processes on the host, and (via a shared volume) other
hosts. There is no coordination protocol:

- **publish** writes ``<key>.pfxspool`` via tmp+rename; the sidecar
  ``<key>.pfxmeta`` (token count, page count, nbytes, spool sha256) is
  written after the spool and is what lookups trust. Content
  addressing makes concurrent publishes of the same prefix idempotent
  — both writers produce identical bytes.
- **pull** reads the sidecar, then the spool (sha256 verified over the
  same read). Any mismatch/truncation/eviction race degrades to a
  miss — the caller prefills as if the store never existed.
- **eviction** is byte-cap LRU by file mtime (reads touch the spool's
  mtime, best-effort). A racing reader losing its file mid-read is a
  miss, never an error.

Files carry no owner PID: unlike live-session hibernation spools
(lifecycle.sweep_orphans territory), prefix KV is immortal shared
content — a dead donor's entries are exactly as valid as a live one's,
which is what makes cross-process adoption after a crash free. The
``.pfxspool`` suffix keeps these files invisible to the ``.kvspool``
orphan sweeps.

Every read/write sits behind the ``prefix_io`` fault point and
degrades: publish failures skip, pull failures miss. Nothing here may
raise into the engine.

Env knobs (docs/knobs.md): ROOM_TPU_PREFIX_STORE,
ROOM_TPU_PREFIX_STORE_DIR, ROOM_TPU_PREFIX_STORE_MB,
ROOM_TPU_PREFIX_STORE_PUBLISH.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from typing import Optional

import numpy as np

from . import faults
from ..utils import knobs, locks
from .faults import FaultError
from .kv_offload import _read_spool, _write_spool

__all__ = [
    "SharedPrefixStore", "prefix_store_enabled_from_env",
    "prefix_store_dir",
]

SPOOL_SUFFIX = ".pfxspool"
META_SUFFIX = ".pfxmeta"


def prefix_store_enabled_from_env(default: str = "0") -> bool:
    return knobs.get_bool("ROOM_TPU_PREFIX_STORE", default=default)


def prefix_store_dir() -> str:
    """Store root: explicit ROOM_TPU_PREFIX_STORE_DIR, else a stable
    dir under the lifecycle root (same durability story as drain
    manifests — survives process restarts on one host; deployments
    point it at a shared volume for cross-host sharing)."""
    explicit = knobs.get_str("ROOM_TPU_PREFIX_STORE_DIR")
    if explicit:
        return explicit
    from .lifecycle import lifecycle_root

    return os.path.join(lifecycle_root(), "prefix_store")


class SharedPrefixStore:
    """Content-addressed prefix-KV tier shared across replicas, processes
    and hosts.

    Pure host-side bytes: the engine owns all device copies
    (copy-on-adopt scatter into its local pool) and all page-table
    mutation. Thread-safe for the in-process part (the lock covers the
    length index and counters); cross-process safety comes from atomic
    renames + verify-on-read, not locking.
    """

    def __init__(
        self,
        fingerprint: dict,
        dir_path: Optional[str] = None,
        bytes_cap: Optional[int] = None,
        page_size: int = 16,
    ) -> None:
        self.dir = dir_path or prefix_store_dir()
        if bytes_cap is None:
            bytes_cap = int(
                knobs.get_float("ROOM_TPU_PREFIX_STORE_MB")
                * 1024 * 1024
            )
        self.bytes_cap = bytes_cap
        self.page_size = page_size
        # the fingerprint participates in every key: KV bytes are only
        # interchangeable between identically-configured engines
        self._fp_digest = hashlib.sha256(
            json.dumps(fingerprint, sort_keys=True).encode()
        ).digest()
        self._lock = locks.make_lock("prefix_store")
        # prefix lengths (tokens) known to exist in the dir — bounds
        # the longest-prefix probe to O(|lengths|) hashes instead of
        # one per aligned length. Refreshed by directory scan (other
        # processes publish too).
        self._lengths: set[int] = set()
        self._scanned_at = 0.0
        self._stats = {
            "publishes": 0, "publish_skips": 0, "publish_errors": 0,
            "hits": 0, "misses": 0, "pull_errors": 0,
            "evictions": 0, "bytes_published": 0, "bytes_pulled": 0,
        }
        self._scan(force=True)

    def _bump(self, key: str, n: int = 1) -> None:
        with self._lock:
            self._stats[key] += n

    # ---- addressing ----

    def key_of(self, tokens) -> str:
        h = hashlib.sha256(self._fp_digest)
        h.update(np.asarray(list(tokens), np.int64).tobytes())
        return h.hexdigest()

    def _paths(self, key: str) -> tuple[str, str]:
        return (
            os.path.join(self.dir, key + SPOOL_SUFFIX),
            os.path.join(self.dir, key + META_SUFFIX),
        )

    # ---- length index (in-process hint; misses rescan) ----

    def _scan(self, force: bool = False) -> None:
        now = time.monotonic()
        with self._lock:
            if not force and now - self._scanned_at < 2.0:
                return
            self._scanned_at = now
        lengths: set[int] = set()
        try:
            for name in os.listdir(self.dir):
                if not name.endswith(META_SUFFIX):
                    continue
                try:
                    with open(os.path.join(self.dir, name), "r",
                              encoding="utf-8") as f:
                        meta = json.load(f)
                    lengths.add(int(meta["tokens"]))
                except (OSError, ValueError, KeyError, TypeError):
                    continue
        except OSError:
            pass
        with self._lock:
            self._lengths = lengths

    # ---- publish ----

    def has(self, tokens) -> bool:
        spool, meta = self._paths(self.key_of(tokens))
        return os.path.exists(spool) and os.path.exists(meta)

    def publish(
        self, tokens, arrays: dict[str, np.ndarray], n_pages: int,
    ) -> bool:
        """Write one prefix's KV page block (host arrays keyed like the
        engine cache, ``[L, n_pages, ...]``) under its content key.
        Idempotent; never raises — a prefix_io fault or real I/O error
        counts and skips (the store is an accelerator, not a
        dependency). Returns True when the entry is (now) present."""
        tokens = [int(t) for t in tokens]
        if not tokens or len(tokens) % self.page_size != 0:
            return False
        key = self.key_of(tokens)
        spool, meta = self._paths(key)
        if os.path.exists(meta) and os.path.exists(spool):
            with self._lock:
                self._lengths.add(len(tokens))
            self._bump("publish_skips")
            return True
        tmp = meta + f".tmp{os.getpid()}"
        try:
            faults.maybe_fail("prefix_io")
            os.makedirs(self.dir, exist_ok=True)
            digest = _write_spool(spool, arrays, want_digest=True)
            nbytes = os.path.getsize(spool)
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump({
                    "tokens": len(tokens),
                    "n_pages": int(n_pages),
                    "nbytes": int(nbytes),
                    "sha256": digest,
                }, f)
            os.replace(tmp, meta)
        except (FaultError, OSError, TypeError, ValueError):
            # the sidecar tmp is invisible to every sweep (length
            # scan, byte-cap eviction, .kvspool orphan sweeps) — a
            # failed publish must clean it up itself
            try:
                os.unlink(tmp)
            except OSError:
                pass
            self._bump("publish_errors")
            return False
        with self._lock:
            self._lengths.add(len(tokens))
        self._bump("publishes")
        self._bump("bytes_published", int(nbytes))
        self._evict_over_cap()
        return True

    # ---- pull ----

    def fetch_longest(
        self, prompt, max_len: int
    ) -> Optional[tuple[int, dict, dict[str, np.ndarray]]]:
        """Longest stored page-aligned prefix of ``prompt`` with length
        <= ``max_len``; returns (length, meta, arrays) or None. The
        spool's sha256 is verified over the read; any failure —
        prefix_io fault, eviction race, truncation, checksum mismatch
        — degrades to a miss and cleans the length index."""
        self._scan()
        with self._lock:
            lengths = sorted(
                (n for n in self._lengths
                 if n <= max_len and n % self.page_size == 0),
                reverse=True,
            )
        if not lengths:
            self._bump("misses")
            return None
        try:
            faults.maybe_fail("prefix_io")
        except FaultError:
            self._bump("pull_errors")
            self._bump("misses")
            return None
        for length in lengths:
            key = self.key_of(prompt[:length])
            spool, meta_path = self._paths(key)
            try:
                with open(meta_path, "r", encoding="utf-8") as f:
                    meta = json.load(f)
                arrays = _read_spool(
                    spool, expected_sha=meta.get("sha256")
                )
            except (OSError, ValueError, KeyError, TypeError):
                if not os.path.exists(meta_path):
                    continue  # plain miss at this length
                # present but unreadable/corrupt: drop the pair so the
                # next publisher can repair it
                self._discard(key)
                self._bump("pull_errors")
                continue
            try:
                # LRU touch for the byte-cap eviction
                now = time.time()
                os.utime(spool, (now, now))
            except OSError:
                pass
            self._bump("hits")
            self._bump("bytes_pulled", int(meta.get("nbytes") or 0))
            return length, meta, arrays
        self._bump("misses")
        return None

    # ---- hygiene ----

    def _discard(self, key: str) -> None:
        for path in self._paths(key):
            try:
                os.unlink(path)
            except OSError:
                pass

    def _evict_over_cap(self) -> None:
        """LRU (mtime) eviction down to the byte cap. Best-effort and
        cross-process racy by design: a concurrent reader losing its
        file takes a miss; a concurrent evictor's unlink failure is
        ignored."""
        if self.bytes_cap <= 0:
            return
        try:
            entries = []
            total = 0
            for name in os.listdir(self.dir):
                if not name.endswith(SPOOL_SUFFIX):
                    continue
                path = os.path.join(self.dir, name)
                try:
                    st = os.stat(path)
                except OSError:
                    continue
                entries.append((st.st_mtime, st.st_size, name))
                total += st.st_size
            if total <= self.bytes_cap:
                return
            for _, size, name in sorted(entries):
                self._discard(name[: -len(SPOOL_SUFFIX)])
                self._bump("evictions")
                total -= size
                if total <= self.bytes_cap:
                    break
        except OSError:
            pass

    # ---- observability ----

    def stats(self) -> dict:
        with self._lock:
            out = dict(self._stats)
            out["known_lengths"] = len(self._lengths)
        out["dir"] = self.dir
        out["bytes_cap"] = self.bytes_cap
        try:
            out["entries"] = sum(
                1 for n in os.listdir(self.dir)
                if n.endswith(SPOOL_SUFFIX)
            )
        except OSError:
            out["entries"] = 0
        return out
